"""ctypes bindings for the native host-side components (native/dllama_native.cpp).

Loading order: $DLLAMA_NATIVE_LIB, then the in-repo build
(native/build/libdllama_native.so), auto-building with `make` on first use if
the source tree and a compiler are present (set DLLAMA_NATIVE=0 to disable
everything). All callers must keep a pure-Python fallback — `available()`
gating is the contract, and tests/test_native.py pins C++ == Python semantics.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_REPO_NATIVE = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_lib = None
_tried = False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("DLLAMA_NATIVE", "1") == "0":
        return None
    candidates = []
    if os.environ.get("DLLAMA_NATIVE_LIB"):
        candidates.append(os.environ["DLLAMA_NATIVE_LIB"])
    built = os.path.join(_REPO_NATIVE, "build", "libdllama_native.so")
    candidates.append(built)
    if not any(os.path.exists(c) for c in candidates) and os.path.exists(
        os.path.join(_REPO_NATIVE, "Makefile")
    ):
        try:
            subprocess.run(
                ["make", "-C", _REPO_NATIVE],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception:
            return None
    for c in candidates:
        if os.path.exists(c):
            try:
                lib = ctypes.CDLL(c)
            except OSError:
                continue
            _bind(lib)
            _lib = lib
            return lib
    return None


def _bind(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.dllama_quantize_q40.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, u8p, ctypes.POINTER(ctypes.c_uint16)]
    lib.dllama_quantize_q40.restype = None
    lib.dllama_quantize_q80.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(ctypes.c_uint16)]
    lib.dllama_quantize_q80.restype = None
    lib.dllama_tok_create.argtypes = [
        u8p, ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
    lib.dllama_tok_create.restype = ctypes.c_void_p
    lib.dllama_tok_destroy.argtypes = [ctypes.c_void_p]
    lib.dllama_tok_destroy.restype = None
    lib.dllama_tok_encode.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
    lib.dllama_tok_encode.restype = ctypes.c_int32
    # optional symbol: older prebuilt libraries (DLLAMA_NATIVE_LIB) predate
    # it; callers gate on has_q40_shard(), everything else keeps working
    if hasattr(lib, "dllama_q40_shard"):
        lib.dllama_q40_shard.argtypes = [
            u8p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            u8p, ctypes.POINTER(ctypes.c_float)]
        lib.dllama_q40_shard.restype = None


def available() -> bool:
    return _load() is not None


def quantize_q40(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f32[..., K] -> (packed u8[..., K/32, 16], scales f16[..., K/32]);
    same contract as ops.quant.quantize_q40_np."""
    lib = _load()
    assert lib is not None
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    nb = flat.size // 32
    packed = np.empty(nb * 16, dtype=np.uint8)
    scales = np.empty(nb, dtype=np.uint16)
    lib.dllama_quantize_q40(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), flat.size,
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        scales.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)))
    shape = x.shape
    return (packed.reshape(*shape[:-1], shape[-1] // 32, 16),
            scales.view(np.float16).reshape(*shape[:-1], shape[-1] // 32))


def quantize_q80(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    lib = _load()
    assert lib is not None
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    nb = flat.size // 32
    codes = np.empty(flat.size, dtype=np.int8)
    scales = np.empty(nb, dtype=np.uint16)
    lib.dllama_quantize_q80(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), flat.size,
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        scales.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)))
    shape = x.shape
    return (codes.reshape(*shape[:-1], shape[-1] // 32, 32),
            scales.view(np.float16).reshape(*shape[:-1], shape[-1] // 32))


def has_q40_shard() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "dllama_q40_shard")


def q40_shard(rec: np.ndarray, n0: int, n1: int, b0: int, b1: int,
              want_packed: bool, want_scales: bool):
    """Decode a device-layout shard from a `.m` Q40 record array
    rec u8[n_out, nb_total, 18] — the C++ twin of LazyQ40's numpy path.
    Returns (packed u8[(b1-b0)*16, n1-n0] | None, scales f32[...] | None)."""
    lib = _load()
    assert lib is not None
    assert rec.ndim == 3 and rec.shape[2] == 18 and rec.dtype == np.uint8
    assert rec.flags["C_CONTIGUOUS"]  # the C++ kernel assumes row stride nb*18
    ns, nbs = n1 - n0, b1 - b0
    packed = np.empty((nbs * 16, ns), np.uint8) if want_packed else None
    scales = np.empty((nbs, ns), np.float32) if want_scales else None
    null_u8 = ctypes.cast(None, ctypes.POINTER(ctypes.c_uint8))
    null_f = ctypes.cast(None, ctypes.POINTER(ctypes.c_float))
    lib.dllama_q40_shard(
        rec.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), rec.shape[1],
        n0, n1, b0, b1,
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if want_packed else null_u8,
        scales.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) if want_scales else null_f,
    )
    return packed, scales


class NativeBpe:
    """Persistent native tokenizer handle (built once per Tokenizer)."""

    def __init__(self, vocab: list[bytes], scores: list[float], special_ids: list[int]):
        lib = _load()
        assert lib is not None
        self._lib = lib
        blob = b"".join(vocab)
        offsets = np.zeros(len(vocab) + 1, dtype=np.int64)
        np.cumsum([len(v) for v in vocab], out=offsets[1:])
        self._blob = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(1, np.uint8)
        self._offsets = offsets
        self._scores = np.asarray(scores, dtype=np.float32)
        self._specials = np.asarray(special_ids, dtype=np.int32)
        self._handle = lib.dllama_tok_create(
            self._blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._scores.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            len(vocab),
            self._specials.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(special_ids))

    def encode(self, data: bytes, add_special_tokens: bool) -> list[int] | None:
        """None signals 'cannot tokenize' (caller raises with its own message)."""
        out = np.empty(max(16, 2 * len(data) + 16), dtype=np.int32)
        n = self._lib.dllama_tok_encode(
            self._handle,
            np.frombuffer(data, dtype=np.uint8).ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            if data else ctypes.cast(0, ctypes.POINTER(ctypes.c_uint8)),
            len(data), int(add_special_tokens),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), out.size)
        if n == -1:
            return None
        assert n >= 0, "native encode output buffer overflow"
        return out[:n].tolist()

    def __del__(self):
        try:
            self._lib.dllama_tok_destroy(self._handle)
        except Exception:
            pass
