"""Shared logger setup: one place every entry point (CLI, benches, embedded
servers) configures logging, with an opt-in structured JSON mode
(``--log-format json``).

JSON schema (one object per line on stderr)::

    {"ts": "2026-08-03T12:00:00.123Z", "level": "INFO",
     "logger": "dllama_tpu.serve", "msg": "...",
     "request_id": "req_...",          # when the line is request-scoped
     "exc": "Traceback ..."}           # when the record carries one

Any ``extra={...}`` fields a call site attaches (request ids, fault points,
HTTP status codes) are lifted into the object — the serving tier logs with
``extra={"request_id": rid}`` so shed/completed/failed traffic is
correlatable with the ``X-Request-Id`` response header. The text formatter
appends the same request id as a ``request_id=...`` suffix, so correlation
works in both modes.
"""

from __future__ import annotations

import json
import logging
import time

#: standard LogRecord attributes — anything else on a record came from
#: `extra=` and belongs in the structured output
_RESERVED = set(vars(logging.LogRecord("", 0, "", 0, "", (), None))) | {
    "message", "asctime", "taskName",
}


def _record_extras(record: logging.LogRecord) -> dict:
    return {
        k: v if isinstance(v, (str, int, float, bool, type(None))) else repr(v)
        for k, v in record.__dict__.items()
        if k not in _RESERVED and not k.startswith("_")
    }


class JsonFormatter(logging.Formatter):
    """One JSON object per record; extras lifted to top-level keys."""

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
        out = {
            "ts": f"{ts}.{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        out.update(_record_extras(record))
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class TextFormatter(logging.Formatter):
    """The classic human format, plus a ``request_id=...`` suffix whenever a
    record carries one — grep-for-the-header works in text mode too."""

    def format(self, record: logging.LogRecord) -> str:
        s = super().format(record)
        rid = record.__dict__.get("request_id")
        if rid:
            s += f" request_id={rid}"
        return s


def setup_logging(fmt: str = "text", verbose: bool = False) -> None:
    """Install the process-wide handler (replaces any prior root handlers —
    calling twice, e.g. tests re-entering main(), must not double-log)."""
    handler = logging.StreamHandler()
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            TextFormatter("%(asctime)s %(name)s %(levelname)s %(message)s"))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(logging.DEBUG if verbose else logging.INFO)
