"""Named locks with an optional runtime lock-order sanitizer (ISSUE 14).

The serving stack holds a web of small locks across five-plus threads
(scheduler worker, watchdog, HTTP handlers, drain thread, profiler timer):
the scheduler's metrics ring, the KV page pool's reentrant allocator lock
(which the radix prefix tree deliberately piggybacks), the observability
registries (metrics families, tracer ring, compile ledger, perf windows),
and the fault-injection plan. None of them may ever deadlock a scrape or a
request thread, so the stack commits to ONE global acquisition order — the
rank table below, lowest rank acquired first, innermost (leaf) locks
ranked highest. The static half of the contract lives in
``dllama_tpu.analysis`` (the ``lock-order``/``lock-leaf`` rules build the
cross-module lock graph from the AST and fail CI on a rank inversion);
this module is the runtime half, the stack's lockdep:

* every lock is created through :func:`make_lock` / :func:`make_rlock`
  with a name from :data:`LOCK_RANKS` (an unknown name raises at
  construction — the rank table is the single definition site, drift-
  checked against the README table by the analyzer);
* with ``DLLAMA_LOCK_AUDIT=1`` (armed suite-wide by tests/conftest.py and
  scripts/chaos_soak.sh) each factory returns an audited wrapper keeping a
  thread-local stack of held locks: acquiring a lock whose rank is not
  strictly above every held lock raises :class:`LockOrderError` naming
  BOTH sites — the held lock's acquisition point and the violating one —
  at the acquisition that would eventually deadlock, not at the deadlock;
* re-acquiring a held reentrant lock is always legal (the pool audit
  re-enters the pool lock through the radix tree's audit hook);
* with the audit off the factories return plain ``threading.Lock`` /
  ``RLock`` objects — zero wrapper, zero per-acquire overhead.

Leaf discipline: the metrics registry and tracer locks hold the two
highest ranks, so acquiring ANYTHING while holding them is an order
violation by construction — the scrape-path deadlock shape (a /metrics
render re-entering the scheduler or pool) cannot be written without the
sanitizer (and the static ``lock-leaf`` rule) firing.

Stdlib-only and import-leaf: everything in ``dllama_tpu.obs`` imports
this module, so it must import nothing of dllama_tpu.
"""

from __future__ import annotations

import os
import sys
import threading

ENV_VAR = "DLLAMA_LOCK_AUDIT"

#: The global lock-acquisition order: a thread may only acquire a lock
#: whose rank is STRICTLY greater than every lock it already holds
#: (re-entering a held RLock excepted). Lowest rank = outermost. The
#: README "lock rank" table mirrors this exactly (analyzer rule
#: ``doc-ranks``), and the static lock graph's edges must all ascend it
#: (rule ``lock-order``).
LOCK_RANKS = {
    # outermost: the router's replica-registry/affinity lock — routing
    # decisions may consult anything below, nothing re-enters the router
    "serve.router": 3,
    # outermost: the single-engine API tier's request serializer — held
    # across a whole generation, everything below nests under it
    "api.single": 5,
    # the aio front-end's connection-registry/stream-list lock (held for
    # dict/list mutation only — never across a handler or a device call)
    "serve.frontend": 7,
    # the scheduler's completed-request/stall-sample ring
    "scheduler.metrics": 10,
    # the paged-KV allocator (PagePool._mu, reentrant: the radix tree
    # shares it and audit() re-enters through the tree's audit hook)
    "engine.pool": 20,
    # fault-injection plan table and per-point firing windows
    "faults.plan": 30,
    "faults.point": 32,
    # compile ledger + shape contract (obs/compile.py)
    "obs.ledger": 40,
    "obs.contract": 42,
    # perf windows / time ledger (obs/perf.py) — bill into metrics
    "obs.perf": 44,
    # transfer-accounting mirror (obs/compile.py)
    "obs.transfers": 46,
    # the one-session jax.profiler guard (utils/profiling.py)
    "utils.profiling": 48,
    # LEAF locks: nothing may be acquired while holding these. The tracer
    # ring first, the metrics registry/family locks innermost of all.
    "obs.tracer": 50,
    "obs.metrics": 60,
}

#: leaf locks (documented contract; with the ranks above, any acquisition
#: under them already violates the strict ordering — this set exists so
#: the static analyzer and error messages can say WHY)
LEAF_LOCKS = frozenset({"obs.tracer", "obs.metrics"})


class LockOrderError(RuntimeError):
    """An out-of-rank lock acquisition — the shape that deadlocks once two
    threads interleave. The message names both hold sites."""


_armed = os.environ.get(ENV_VAR, "") not in ("", "0")


def configure(on: bool) -> None:
    """Arm/disarm the audit for locks created AFTER this call (tests).
    Production arms via the env var before the process imports anything."""
    global _armed
    _armed = bool(on)


def armed() -> bool:
    return _armed


_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def held_names() -> list[str]:
    """Names of audited locks the CALLING thread currently holds,
    outermost first (introspection for tests and error paths)."""
    return [lk.name for lk, _site in _held()]


def _caller_site() -> str:
    """file:line of the acquisition OUTSIDE this module — the site a
    LockOrderError must name (``with lock:`` enters via __enter__, so the
    first frames belong to locks.py itself)."""
    try:
        f = sys._getframe(1)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:  # pragma: no cover - called from module level
            return "<unknown>"
        return f"{f.f_code.co_filename}:{f.f_lineno}"
    except Exception:  # pragma: no cover - exotic interpreters
        return "<unknown>"


def _check_name(name: str) -> None:
    """The one unknown-name validator (factories and AuditedLock share
    it): the rank table is the single definition site."""
    if name not in LOCK_RANKS:
        raise ValueError(
            f"unknown lock name {name!r}; add it to utils/locks.LOCK_RANKS "
            f"(and the README lock-rank table) — known: {sorted(LOCK_RANKS)}")


class AuditedLock:
    """threading.Lock/RLock with rank-order auditing (see module doc).
    Full Lock surface: acquire(blocking, timeout) / release / context
    manager; ``reentrant`` wraps an RLock and allows re-acquisition of the
    SAME object regardless of rank."""

    __slots__ = ("name", "rank", "reentrant", "_lk")

    def __init__(self, name: str, reentrant: bool = False):
        _check_name(name)
        self.name = name
        self.rank = LOCK_RANKS[name]
        self.reentrant = bool(reentrant)
        self._lk = threading.RLock() if reentrant else threading.Lock()

    def _check(self, site: str) -> None:
        held = _held()
        if self.reentrant and any(lk is self for lk, _ in held):
            return  # legal reentry of a held RLock
        for lk, where in held:
            if lk.rank >= self.rank:
                leaf = (" — it is a LEAF lock: nothing may be acquired "
                        "while holding it" if lk.name in LEAF_LOCKS else "")
                raise LockOrderError(
                    f"lock-order violation: acquiring {self.name!r} "
                    f"(rank {self.rank}) at {site} while holding "
                    f"{lk.name!r} (rank {lk.rank}, acquired at {where})"
                    f"{leaf}; the global order is utils/locks.LOCK_RANKS")

    def acquire(self, blocking: bool = True, timeout: float = -1):
        site = _caller_site()
        self._check(site)
        got = self._lk.acquire(blocking, timeout)
        if got:
            _held().append((self, site))
        return got

    def release(self) -> None:
        self._lk.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:  # Lock parity (RLock lacks it pre-3.12)
        probe = getattr(self._lk, "locked", None)
        if probe is not None:
            return probe()
        if self._lk.acquire(blocking=False):  # pragma: no cover - RLock
            self._lk.release()
            return False
        return True  # pragma: no cover

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"<AuditedLock {self.name} rank={self.rank}>"


def make_lock(name: str):
    """A named non-reentrant lock: plain ``threading.Lock()`` when the
    audit is off (zero overhead — the factory IS the fast path), an
    :class:`AuditedLock` when armed. `name` must be in LOCK_RANKS."""
    _check_name(name)
    if not _armed:
        return threading.Lock()
    return AuditedLock(name)


def make_rlock(name: str):
    """A named REENTRANT lock (same contract as :func:`make_lock`;
    re-acquisition by the holding thread is always rank-legal)."""
    _check_name(name)
    if not _armed:
        return threading.RLock()
    return AuditedLock(name, reentrant=True)
