"""Deterministic fault injection for the serving stack.

The reference server has no failure-path testing at all (a hung request just
stalls its one blocking client, dllama-api.cpp:522-533); our continuous-
batching tier multiplexes every client over ONE worker thread and ONE device,
so "what happens when a decode chunk dies" must be testable on demand.  This
module gives every interesting failure a NAME, and lets tests (or an operator
reproducing an incident) arm it deterministically — no monkeypatching into
jitted internals, no sleeps-and-hope.

Injection points (armed sites call :func:`fire` with their point name):

======================  =====================================================
``engine.decode``       before the fused decode-chunk dispatch
                        (BatchEngine.decode / spec_step)
``engine.prefill``      before an admission prefill chunk (BatchEngine.add_step)
``loader.read``         before the .m header read (models/formats.read_header)
``scheduler.queue``     admission-queue overflow: Scheduler.submit sheds the
                        request as if --max-queue were exceeded
``scheduler.loop``      top of the scheduler worker loop (worker-crash drill)
``pool.alloc``          before a paged-KV page allocation (PagePool._alloc_page)
                        — fails an admission per-request, or crashes a decode
                        top-up into the warm-restart path
``engine.restart``      inside the warm-restart sequence, before the engine
                        rebuild (Scheduler._try_restart) — drills a restart
                        that itself dies, which exhausts the budget
``decode.nan``          poisons one slot of the next decode chunk as if its
                        logits went non-finite (``raise`` armed, consumed via
                        :func:`flag`): the scheduler's NaN guard fails THAT
                        request with finish_reason="error", not the engine
``pool.spill``          before a radix-evicted page's d2h copy into the host
                        KV tier (BatchEngine._host_spill) — ``raise`` degrades
                        the eviction to the old discard (page lost, stream
                        correct), ``delay`` stretches the release boundary
``pool.restore``        before a host-tier page's device re-allocation + h2d
                        upload at admission (BatchEngine radix restore) —
                        ``raise`` falls back to re-prefilling the suffix,
                        ``delay`` stretches the admission
``router.proxy``        top of the router's proxy path (serve/router._proxy),
                        before any replica pick — ``raise`` sheds the request
                        with a clean 503, ``delay`` holds it (client-timeout
                        and thundering-herd drills)
======================  =====================================================

Actions: ``raise`` (throw :class:`InjectedFault`) and ``delay`` (sleep
``ms``, e.g. to trip the stall watchdog).  Options: ``after=N`` skips the
first N hits, ``times=N`` fires at most N times (default: forever).

Configuration is a comma-separated spec string, via the ``DLLAMA_FAULTS``
env var or the ``--faults`` CLI flag::

    DLLAMA_FAULTS="engine.decode:raise:after=2"
    --faults "engine.decode:delay:ms=400:times=1,scheduler.queue:raise"

Tests use the programmatic API (:func:`install` / :func:`clear`); both paths
share the same plan table.  ``fire`` is a dict-lookup no-op when nothing is
armed — production cost is one ``if``.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field

from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import trace
from dllama_tpu.utils import locks

log = logging.getLogger("dllama_tpu.faults")

ENV_VAR = "DLLAMA_FAULTS"

#: every site that calls fire(); configure() rejects unknown names so a typo
#: in a fault spec fails at startup, not by silently never firing
POINTS = frozenset({
    "engine.decode",
    "engine.prefill",
    "loader.read",
    "scheduler.queue",
    "scheduler.loop",
    "pool.alloc",
    "engine.restart",
    "decode.nan",
    "pool.spill",
    "pool.restore",
    "router.proxy",
})

ACTIONS = frozenset({"raise", "delay"})


class InjectedFault(RuntimeError):
    """The error thrown by an armed ``raise`` fault (never raised by real
    failures — tests can assert on the type to prove the drill fired)."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point!r}")
        self.point = point


@dataclass
class _Fault:
    point: str
    action: str  # 'raise' | 'delay'
    ms: float = 0.0  # delay duration
    after: int = 0  # skip the first N hits
    times: int | None = None  # fire at most N times (None = forever)
    hits: int = 0  # total fire() visits (fired or not)
    fired: int = 0
    lock: object = field(
        default_factory=lambda: locks.make_lock("faults.point"), repr=False)

    def visit(self) -> str | None:
        """Count one arrival at the point; return the action to apply (or
        None when the window says skip). Thread-safe: concurrent request
        threads hit scheduler.queue simultaneously."""
        with self.lock:
            n = self.hits
            self.hits += 1
            if n < self.after:
                return None
            if self.times is not None and self.fired >= self.times:
                return None
            self.fired += 1
            return self.action


_plan: dict[str, _Fault] = {}
_plan_lock = locks.make_lock("faults.plan")


def parse(spec: str) -> list[_Fault]:
    """Parse a spec string into fault entries (validating names eagerly)."""
    out: list[_Fault] = []
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        parts = clause.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault clause {clause!r}: want point:action[:k=v...]")
        point, action, opts = parts[0], parts[1], parts[2:]
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {sorted(POINTS)}")
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; known: {sorted(ACTIONS)}")
        f = _Fault(point, action)
        for opt in opts:
            k, _, v = opt.partition("=")
            if k == "ms":
                f.ms = float(v)
            elif k == "after":
                f.after = int(v)
            elif k == "times":
                f.times = int(v)
            else:
                raise ValueError(f"unknown fault option {opt!r} in {clause!r}")
        out.append(f)
    return out


def configure(spec: str | None) -> None:
    """Replace the active plan from a spec string ('' / None clears)."""
    faults = parse(spec) if spec else []
    with _plan_lock:
        _plan.clear()
        for f in faults:
            _plan[f.point] = f
    if faults:
        log.warning("fault injection ARMED: %s",
                    ", ".join(f"{f.point}:{f.action}" for f in faults))


def configure_from_env() -> None:
    """Arm faults from $DLLAMA_FAULTS if set (CLI startup calls this)."""
    spec = os.environ.get(ENV_VAR)
    if spec:
        configure(spec)


def install(point: str, action: str = "raise", *, ms: float = 0.0,
            after: int = 0, times: int | None = None) -> None:
    """Arm one point programmatically (tests). Replaces any prior fault at
    the same point; other points are untouched."""
    if point not in POINTS:
        raise ValueError(f"unknown fault point {point!r}; known: {sorted(POINTS)}")
    if action not in ACTIONS:
        raise ValueError(f"unknown fault action {action!r}; known: {sorted(ACTIONS)}")
    with _plan_lock:
        _plan[point] = _Fault(point, action, ms=ms, after=after, times=times)


def clear(point: str | None = None) -> None:
    """Disarm one point, or everything (tests' teardown)."""
    with _plan_lock:
        if point is None:
            _plan.clear()
        else:
            _plan.pop(point, None)


def active(point: str) -> bool:
    return point in _plan


def pending(point: str) -> bool:
    """Whether an armed fault at `point` can still fire (its ``times``
    window is not exhausted). A plan entry outlives its last firing — this
    is how a drill detects that some OTHER thread consumed the activation
    it armed (e.g. a fixture server's idle worker loop) and re-arms."""
    f = _plan.get(point)
    if f is None:
        return False
    with f.lock:
        if f.times is not None and f.fired >= f.times:
            return False
        return True


def flag(point: str) -> bool:
    """The armed-site hook for sites with their OWN failure semantics (e.g.
    ``decode.nan``, where the failure is poisoned data, not an exception):
    returns True when an armed ``raise`` fault at `point` fires — counted at
    /metrics and on the trace timeline exactly like :func:`fire` — instead
    of raising. ``delay`` still sleeps and returns False."""
    f = _plan.get(point)
    if f is None:
        return False
    action = f.visit()
    if action is None:
        return False
    # every activation is a countable incident: drills and live mishaps
    # alike show up at /metrics (dllama_fault_fires_total{point,action})
    # AND on the request-flow trace timeline (/debug/trace)
    ins.FAULT_FIRES.labels(point=point, action=action).inc()
    trace.TRACER.event("fault.fire", cat="fault", track="scheduler",
                       point=point, action=action)
    if action == "delay":
        log.warning("injected delay at %r: %.0f ms", point, f.ms,
                    extra={"fault_point": point})
        time.sleep(f.ms / 1000.0)
        return False
    log.warning("injected fault at %r", point, extra={"fault_point": point})
    return True


def fire(point: str) -> None:
    """The armed-site hook: no-op unless a fault is installed at `point`.
    Raises InjectedFault for 'raise', sleeps for 'delay'."""
    if flag(point):
        raise InjectedFault(point)
