"""Profiling / tracing / observability.

The reference's instrumentation (SURVEY.md §5.1/§5.5): DEBUG_BENCHMARK
per-step μs prints (nn-executor.cpp:100-124), per-token console lines with
elapsed ms + net bytes (dllama.cpp:54-87), network byte counters
(nn-network.cpp:483-492) and the memory report (nn-core.cpp:152-166). TPU
equivalents here:

* :func:`trace` — jax.profiler device traces (view in XProf/TensorBoard); the
  idiomatic replacement for hand-timed executor steps.
* :func:`start_profile` — the on-demand, duration-capped capture behind
  ``POST /debug/profile``: same jax.profiler session as :func:`trace`
  (one lock guards both, so a CLI ``--trace`` run and an HTTP capture can
  never double-start the profiler), stopped by a timer thread.
* :class:`TokenTimer` — host-side per-token latency recorder with the
  reference's report shape (avg/p50/p90 ms/token, tok/s).
* :func:`collective_bytes_per_token` — analytic per-token inter-chip payload
  for a given mesh, the ICI analog of the reference's sentBytes/recvBytes
  (its Fig. 6 "sync payload per token" table is the contract this reproduces).
* :func:`memory_report` — params/cache HBM accounting.
"""

from __future__ import annotations

import contextlib
import tempfile
import threading
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import trace as reqtrace
from dllama_tpu.utils import locks


class ProfileBusy(RuntimeError):
    """A jax.profiler capture is already running (there is exactly one
    profiler session per process); the API tier maps this to HTTP 409."""


#: the one-session profiler lock + state shared by trace() (CLI --trace)
#: and start_profile() (POST /debug/profile)
_prof_lock = locks.make_lock("utils.profiling")
_prof_state = {"active": False, "dir": None, "started_at": 0.0,
               "duration_s": None}

#: hard cap on an on-demand capture: profiles are heavy (host callbacks +
#: trace buffers); a forgotten long capture must not degrade serving forever
MAX_PROFILE_SECONDS = 60.0


def _profiler_begin(log_dir: str, duration_s: float | None = None) -> None:
    with _prof_lock:
        if _prof_state["active"]:
            raise ProfileBusy(
                f"a profiler capture is already running "
                f"(dir={_prof_state['dir']!r}, started "
                f"{time.time() - _prof_state['started_at']:.1f}s ago)")
        jax.profiler.start_trace(log_dir)
        _prof_state.update(active=True, dir=log_dir, started_at=time.time(),
                           duration_s=duration_s)
    reqtrace.TRACER.event("profile.start", cat="profile", track="profiler",
                          dir=log_dir)


def _profiler_end() -> None:
    with _prof_lock:
        if not _prof_state["active"]:
            return
        try:
            jax.profiler.stop_trace()
        finally:
            _prof_state.update(active=False, duration_s=None)
    reqtrace.TRACER.event("profile.stop", cat="profile", track="profiler")


def profile_status() -> dict:
    """Snapshot of the profiler session (no secrets: dir + timing only)."""
    with _prof_lock:
        return {"active": _prof_state["active"], "dir": _prof_state["dir"],
                "duration_s": _prof_state["duration_s"]}


def start_profile(log_dir: str | None = None, duration_s: float = 2.0) -> dict:
    """Start an on-demand jax.profiler capture and schedule its stop after
    `duration_s` (clamped to [0.05, MAX_PROFILE_SECONDS]) on a timer thread.
    Returns {dir, duration_s}; raises :class:`ProfileBusy` when a capture
    (this one or a CLI ``--trace`` run) is already in flight — the caller
    never blocks behind someone else's capture."""
    duration_s = min(max(float(duration_s), 0.05), MAX_PROFILE_SECONDS)
    if not log_dir:
        log_dir = tempfile.mkdtemp(prefix="dllama_profile_")
    _profiler_begin(str(log_dir), duration_s)
    t = threading.Timer(duration_s, _profiler_end)
    t.daemon = True  # a dying process must not hang on the stop timer
    t.start()
    return {"dir": str(log_dir), "duration_s": duration_s}


@contextlib.contextmanager
def trace(log_dir: str | None):
    """jax.profiler trace over a with-block; no-op when log_dir is falsy.
    Shares the process profiler session with :func:`start_profile`, so it
    raises :class:`ProfileBusy` instead of corrupting a running capture."""
    if not log_dir:
        yield
        return
    _profiler_begin(str(log_dir))
    try:
        yield
    finally:
        _profiler_end()


@dataclass
class TokenTimer:
    """Per-token wall-clock recorder (dllama.cpp:82-104 report shape).

    Every stop() also observes the sample into the metrics registry
    (dllama_token_latency_seconds), so the console report and a /metrics
    scrape read the same record — one source of truth."""

    ms: list[float] = field(default_factory=list)
    _t0: float = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        dt = (time.perf_counter() - self._t0) * 1000.0
        self.ms.append(dt)
        ins.TOKEN_LATENCY_SECONDS.observe(dt / 1000.0)
        return dt

    @contextlib.contextmanager
    def token(self):
        self.start()
        yield
        self.stop()

    def summary(self) -> str:
        if not self.ms:
            return "no tokens timed"
        a = np.asarray(self.ms)
        # throughput over TOTAL time, not 1000/mean: the reciprocal-of-mean
        # form overweights fast tokens (harmonic vs arithmetic) and lies
        # whenever latency varies; guard the degenerate all-zero-clock case
        total_s = float(a.sum()) / 1000.0
        tok_s = len(a) / total_s if total_s > 0 else 0.0
        return (
            f"{len(a)} tokens: avg {a.mean():.2f} ms/token "
            f"(p50 {np.percentile(a, 50):.2f}, p90 {np.percentile(a, 90):.2f}, "
            f"max {a.max():.2f}), {tok_s:.1f} tok/s"
        )


def collective_bytes_per_token(cfg, tp: int = 1, sp: int = 1, exchange_bytes: float = 2.0) -> dict:
    """Analytic inter-chip payload per decoded token, per chip.

    Mirrors the reference's measured sync payload (report.pdf Fig. 6; its Q80
    wire format is exchange_bytes≈1.06 per element — 34 bytes per 32 values;
    bf16 collectives are 2.0). Tensor-parallel Llama moves, per layer:

      attention out: all-gather of the wo partial sums — dim elements, each
      chip sends its 1/tp slice to tp-1 peers and receives the tp-1 others;
      ffn out: same for w2 partials.

    The logits gather moves vocab/tp elements once per token. sp>1 adds the
    decode-path query broadcast + LSE merge of the sequence-parallel
    attention (head_size+2 floats per kv head) — negligible, counted anyway.
    Reported bytes are sent+received per chip, matching the reference's
    sentBytes/recvBytes counters (nn-network.cpp:483-492).
    """
    per_chip = 0.0
    if tp > 1:
        # each sync: send (tp-1) copies of the 1/tp slice, receive tp-1 slices
        per_layer = 2 * 2 * (cfg.dim / tp) * (tp - 1) * exchange_bytes
        per_chip += cfg.n_layers * per_layer
        per_chip += 2 * (cfg.vocab_size / tp) * (tp - 1) * 4.0 / tp  # f32 logits gather
    if sp > 1:
        per_chip += 2 * cfg.n_layers * (cfg.n_kv_heads * (cfg.head_size + 2)) * 4.0 * (sp - 1) / sp
    return {
        "bytes_per_token_per_chip": per_chip,
        "kb_per_token_per_chip": per_chip / 1024.0,
        "tp": tp,
        "sp": sp,
        "exchange_bytes_per_elem": exchange_bytes,
    }


_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute", "all-to-all"
)
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}


def measured_collective_bytes(compiled_text: str) -> dict:
    """MEASURED inter-chip bytes: sum the result shapes of every collective op
    in a compiled (post-SPMD-partitioning) HLO module — the real ops XLA
    emitted, not the analytic model. The reference counts actual socket bytes
    (nn-network.cpp:483-492); this is the compiled-program equivalent on ICI.

    Pass ``jitted.lower(*args).compile().as_text()``. Collectives inside a
    ``while`` loop (e.g. the layer scan) appear once in the text but run once
    per iteration — lower the step with ``layer_unroll=True`` for exact
    per-token totals, or treat the result as bytes *per loop trip*.
    """
    import re

    per_op: dict[str, int] = {}
    # e.g.:  %all-reduce.7 = bf16[1,2048]{1,0:T(8,128)} all-reduce(...
    # (the shape group is lazy-greedy so TPU tiled layouts like
    # {1,0:T(8,128)S(1)} are spanned). Async collectives appear as
    # -start/-done pairs: count the -start (it carries the shapes), skip the
    # -done (it aliases the same transfer).
    pat = re.compile(
        r"=\s*(.+?)\s+(" + "|".join(_COLLECTIVE_OPS) + r")(-start|-done)?[\.\(]"
    )
    shape_pat = re.compile(r"([a-z]+[0-9a-z]*)\[([0-9,]*)\]")
    for line in compiled_text.splitlines():
        m = pat.search(line)
        if not m or m.group(3) == "-done":
            continue
        shapes, op = m.group(1), m.group(2)
        found = shape_pat.findall(shapes)
        if m.group(3) == "-start" and len(found) > 1:
            # -start results are (aliased input, output, ...) tuples — only
            # the output element is a transfer
            found = found[-1:]
        nbytes = 0
        for dt, dims in found:
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        per_op[op] = per_op.get(op, 0) + nbytes
    return {"total_bytes": sum(per_op.values()), "per_op": per_op}


def params_nbytes(params) -> int:
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params) if hasattr(x, "size")
    )


def cache_nbytes(cache) -> int:
    return (cache.k.size * cache.k.dtype.itemsize
            + cache.v.size * cache.v.dtype.itemsize)


def set_memory_gauges(params, cache) -> tuple[int, int]:
    """Publish the HBM accounting as startup gauges (model_params_bytes /
    kv_cache_bytes) so it is queryable at /metrics and in the /health ready
    payload, not just a one-shot --report print. Returns (params_bytes,
    cache_bytes) for callers that also embed the numbers in a payload."""
    pb, cb = params_nbytes(params), cache_nbytes(cache)
    ins.MODEL_PARAMS_BYTES.set(pb)
    ins.KV_CACHE_BYTES.set(cb)
    return pb, cb


def memory_report(cfg, params, cache) -> str:
    """HBM accounting (nn-core.cpp:152-166 role)."""
    pb = params_nbytes(params)
    cb = cache_nbytes(cache)
    return (
        f"💿 params {pb / 1e9:.2f} GB, kv-cache {cb / 1e9:.2f} GB "
        f"(seq {cache.seq_len}, batch {cache.k.shape[1]}), total {(pb + cb) / 1e9:.2f} GB"
    )
