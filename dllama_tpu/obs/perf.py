"""SLO & saturation observability core (ISSUE 7): sliding-window latency
quantiles, the scheduler time ledger, and roofline/goodput attribution.

Everything here is host-side aggregation over marks the serving stack
already produces (PR 2's metrics registry, PR 4's spans) — the layer the
ROADMAP's SLO-aware scheduling and any honest bench trajectory consume:

* :class:`WindowQuantiles` — a dependency-free sliding-window quantile
  estimator in the streaming-sketch family the ISSUE cites (P²/t-digest,
  Dunning & Ertl): time is cut into ring slices, each slice holds a bounded
  uniform reservoir of raw samples, and a quantile query merges the live
  slices. Under the per-slice cap the answer is EXACT (the common case — a
  60 s window sees hundreds of requests, not millions); past the cap the
  reservoir keeps an unbiased sample, so tails degrade gracefully instead
  of the estimator growing without bound. Bounded memory, O(1) observe,
  O(window samples · log) query — queries run at scrape/debug time, not on
  the hot path.
* :class:`TimeLedger` — every second of the scheduler worker loop
  attributed to exactly ONE exclusive state (:data:`LEDGER_STATES`). The
  attribution is transition-based: ``transition(s)`` bills the wall time
  since the previous transition to the PREVIOUS state, so the per-state
  totals partition wall time by construction — their sum equals loop wall
  time to the clock's precision, which is the invariant
  tests/test_perf.py drives a real scheduler run through.
* :class:`ChunkCostModel` / :func:`decode_step_bytes` — the per-step HBM
  byte pricing shared with ``experiments/hbm_traffic.py`` (that script's
  ``batched_step_bytes`` delegates here; one definition site, so the live
  gauges and the offline roofline tables cannot drift). The live side
  prices each consumed decode chunk and divides by its measured device
  window to export bandwidth attainment against the v5e HBM roofline.
* :class:`SloPolicy` / :class:`PerfAggregator` — configurable TTFT/ITL SLO
  targets (``--slo-ttft-ms`` / ``--slo-itl-ms``), burn counters
  (``dllama_slo_violations_total{kind}``), a windowed attainment gauge,
  and goodput-vs-throughput: goodput counts only tokens of requests that
  finished ``stop``/``length`` *within* their SLOs.

Stdlib-only like the rest of ``dllama_tpu/obs`` — scripts/checks.sh
imports this module without jax or a model.
"""

from __future__ import annotations

import math
import random
import time
from collections import deque
from dataclasses import dataclass

from dllama_tpu.obs import instruments as ins
from dllama_tpu.utils import locks

#: v5e HBM bandwidth (public spec), the same constant
#: experiments/hbm_traffic.py prices its offline rooflines against — the
#: live bandwidth-attainment gauge divides achieved bytes/s by this
PEAK_HBM_GBS = 819.0

#: the exclusive states of the scheduler worker loop — the label set of
#: dllama_scheduler_time_seconds_total{state} and the README ledger table
#: (scripts/checks.sh asserts the two stay identical). `hybrid` is the
#: dispatch of a fused chunked-prefill+decode step (ISSUE 12): host work
#: that launches BOTH a prefill slice and a decode chunk in one device
#: call — neither pure `prefill` nor pure `decode_dispatch`, so it gets
#: its own bucket instead of polluting either attribution.
LEDGER_STATES = ("idle", "admission", "prefill", "hybrid", "decode_dispatch",
                 "decode_wait", "emit", "commit", "restart_backoff")


# ------------------------------------------------------------------ windows


class WindowQuantiles:
    """Sliding-window streaming quantile estimator (see module docstring).

    ``window_s`` of history in ``slices`` ring buckets; each bucket keeps at
    most ``cap`` samples (uniform reservoir past that, unbiased). Quantiles
    use the linear-interpolation definition (``numpy.percentile`` default),
    so under the cap they match an exact sorted-list computation bit for
    bit — the contract tests/test_perf.py checks across adversarial
    streams. ``now_fn`` is injectable for deterministic window-expiry
    tests."""

    def __init__(self, window_s: float = 60.0, slices: int = 6,
                 cap: int = 512, now_fn=time.monotonic):
        if window_s <= 0 or slices <= 0 or cap <= 0:
            raise ValueError("window_s, slices and cap must be positive")
        self.window_s = float(window_s)
        self.slices = int(slices)
        self.cap = int(cap)
        self._slice_s = self.window_s / self.slices
        self._now = now_fn
        self._lock = locks.make_lock("obs.perf")
        # ring of (bucket_index, samples, seen); bucket = floor(now/slice_s)
        self._ring: list[tuple[int, list[float], int]] = []

    def _bucket(self) -> int:
        return int(self._now() / self._slice_s)

    def _live(self, bucket: int):
        """Slices still inside the window (caller holds the lock)."""
        oldest = bucket - self.slices + 1
        return [entry for entry in self._ring if entry[0] >= oldest]

    def observe(self, v: float) -> None:
        v = float(v)
        if v != v:  # NaN never enters the window
            return
        b = self._bucket()
        with self._lock:
            if not self._ring or self._ring[-1][0] != b:
                self._ring = self._live(b)
                self._ring.append((b, [], 0))
            bucket, samples, seen = self._ring[-1]
            if seen < self.cap:
                samples.append(v)
            else:
                # uniform reservoir: every sample of the slice keeps an
                # equal cap/seen chance of being retained
                j = random.randrange(seen + 1)
                if j < self.cap:
                    samples[j] = v
            self._ring[-1] = (bucket, samples, seen + 1)

    def count(self) -> int:
        """Observations currently inside the window (pre-reservoir count)."""
        with self._lock:
            return sum(seen for _, _, seen in self._live(self._bucket()))

    def _merged(self) -> list[float]:
        with self._lock:
            live = self._live(self._bucket())
            return sorted(x for _, samples, _ in live for x in samples)

    def quantile(self, q: float) -> float | None:
        """Windowed quantile, ``q`` in [0, 1]; None on an empty window."""
        xs = self._merged()
        if not xs:
            return None
        if len(xs) == 1:
            return xs[0]
        rank = min(max(q, 0.0), 1.0) * (len(xs) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(xs) - 1)
        frac = rank - lo
        return xs[lo] * (1.0 - frac) + xs[hi] * frac

    def snapshot(self) -> dict:
        """{'count', 'p50', 'p95', 'p99'} over one merged window read (a
        p-by-p loop over quantile() would re-sort the window each time)."""
        xs = self._merged()
        out: dict = {"count": self.count()}
        for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            if not xs:
                out[name] = None
                continue
            rank = q * (len(xs) - 1)
            lo = int(math.floor(rank))
            hi = min(lo + 1, len(xs) - 1)
            frac = rank - lo
            out[name] = xs[lo] * (1.0 - frac) + xs[hi] * frac
        return out


class WindowSums:
    """Time-sliced sliding-window sums (the rate companion of
    :class:`WindowQuantiles`): ``add(tokens=3, bytes=1e6)`` accumulates into
    the current slice, ``totals()`` merges live slices, ``span_s()`` is the
    window the totals cover (for rate = total / span)."""

    def __init__(self, window_s: float = 60.0, slices: int = 6,
                 now_fn=time.monotonic):
        self.window_s = float(window_s)
        self.slices = int(slices)
        self._slice_s = self.window_s / self.slices
        self._now = now_fn
        self._lock = locks.make_lock("obs.perf")
        self._ring: list[tuple[int, dict]] = []
        self._t0 = now_fn()  # windows younger than window_s rate over age

    def add(self, **fields: float) -> None:
        b = int(self._now() / self._slice_s)
        with self._lock:
            oldest = b - self.slices + 1
            self._ring = [e for e in self._ring if e[0] >= oldest]
            if not self._ring or self._ring[-1][0] != b:
                self._ring.append((b, {}))
            acc = self._ring[-1][1]
            for k, v in fields.items():
                acc[k] = acc.get(k, 0.0) + float(v)

    def totals(self) -> dict:
        b = int(self._now() / self._slice_s)
        with self._lock:
            oldest = b - self.slices + 1
            out: dict = {}
            for bucket, acc in self._ring:
                if bucket < oldest:
                    continue
                for k, v in acc.items():
                    out[k] = out.get(k, 0.0) + v
            return out

    def span_s(self) -> float:
        """Seconds the current totals cover: the full window once the
        process has lived that long, the process age before (rates must not
        read 6x too low during the first minute)."""
        return max(min(self.window_s, self._now() - self._t0), 1e-9)


# ------------------------------------------------------- clock alignment


class ClockOffset:
    """NTP-lite remote-clock offset estimator over request/response
    round-trips (ISSUE 17) — the router runs one per replica, fed by its
    health poller, to place each replica's monotonic clock on the router's
    timeline for the merged mesh trace.

    One :meth:`sample` per poll: ``t_send``/``t_recv`` are the local
    monotonic marks around the round-trip, ``t_remote`` the remote clock
    read the response carried. The classic single-exchange estimate assumes
    the remote read happened at the round-trip midpoint, so

        offset = t_remote - (t_send + t_recv) / 2

    with the true offset inside ``offset ± rtt/2`` (the read can be
    anywhere between send and receive). :meth:`estimate` returns the
    MIN-RTT sample of the sliding window — the exchange least polluted by
    queueing delay, whose error bound ``rtt/2`` is also the smallest.
    Single-writer (the replica's poller thread) / multi-reader; the deque
    append and snapshot are GIL-atomic, so no lock is needed."""

    __slots__ = ("_samples",)

    def __init__(self, window: int = 16):
        self._samples: deque = deque(maxlen=int(window))

    def sample(self, t_send: float, t_recv: float, t_remote: float) -> None:
        rtt = max(float(t_recv) - float(t_send), 0.0)
        offset = float(t_remote) - (float(t_send) + float(t_recv)) / 2.0
        self._samples.append((rtt, offset))

    def estimate(self) -> dict | None:
        """-> {offset_s, uncertainty_s, rtt_s, samples} from the min-RTT
        sample of the window, or None before the first sample."""
        samples = list(self._samples)
        if not samples:
            return None
        rtt, offset = min(samples)
        return {"offset_s": offset, "uncertainty_s": rtt / 2.0,
                "rtt_s": rtt, "samples": len(samples)}


# ------------------------------------------------------------- time ledger


class TimeLedger:
    """Exclusive-state time attribution for one worker loop.

    ``transition(state)`` bills the elapsed time since the last transition
    to the PREVIOUS state and makes ``state`` current — every instant
    between ``start()`` and ``close()`` lands in exactly one state, so the
    per-state totals sum to the loop's wall time by construction. Each
    billed span also increments the
    ``dllama_scheduler_time_seconds_total{state}`` counter (when a counter
    family is supplied), making the invariant scrape-visible.

    Thread-safety: the worker owns the state machine, but ``snapshot()``
    (and the scrape-path ``poke()``, which bills the open span without
    changing state) may run from API threads — all entry points take the
    lock, and billing stays correct because every moment is attributed to
    whatever state was current when it passed."""

    def __init__(self, counter=None, now_fn=time.monotonic,
                 states=LEDGER_STATES):
        self.states = tuple(states)
        self._counter = counter
        self._now = now_fn
        # _bill() increments the scheduler-time counter while holding this
        # (obs.perf ranks below the obs.metrics leaf, so that nesting is
        # rank-legal by construction)
        self._lock = locks.make_lock("obs.perf")
        self.totals = {s: 0.0 for s in self.states}
        self._state: str | None = None
        self._t: float | None = None
        self._t_start: float | None = None
        self._t_close: float | None = None

    def start(self, state: str = "idle") -> None:
        """Anchor the ledger at loop entry (re-entrant: a warm restart
        re-enters the loop without resetting the accumulated record)."""
        with self._lock:
            now = self._now()
            if self._t_start is None:
                self._t_start = now
            self._t_close = None
            self._bill(now)
            self._set(state, now)

    def _bill(self, now: float) -> None:
        if self._state is not None and self._t is not None:
            dt = max(now - self._t, 0.0)
            self.totals[self._state] += dt
            if self._counter is not None:
                self._counter.labels(state=self._state).inc(dt)
            self._t = now

    def _set(self, state: str | None, now: float) -> None:
        if state is not None and state not in self.totals:
            raise ValueError(f"unknown ledger state {state!r} "
                             f"(catalog: {self.states})")
        self._state = state
        self._t = now if state is not None else None

    def transition(self, state: str) -> None:
        with self._lock:
            now = self._now()
            self._bill(now)
            self._set(state, now)

    def state(self) -> str | None:
        """The current exclusive state (None before start()/after close())
        — cross-thread readers (the scheduler's drain/watchdog idleness
        check) join this with container occupancy, closing the false-idle
        window while the worker holds a request BETWEEN containers (popped
        from in-flight, slot not yet assigned)."""
        with self._lock:
            return self._state

    def poke(self) -> None:
        """Bill the open span without changing state (scrape freshness: a
        long idle park should not read as zero until the next transition)."""
        with self._lock:
            self._bill(self._now())

    def close(self) -> None:
        """Bill the tail and stop the clock (loop exit / worker death)."""
        with self._lock:
            now = self._now()
            self._bill(now)
            self._set(None, now)
            if self._t_close is None:
                self._t_close = now

    def wall_s(self) -> float:
        """start() -> now (or close()): the quantity the state totals must
        sum to."""
        with self._lock:
            if self._t_start is None:
                return 0.0
            end = self._t_close if self._t_close is not None else self._now()
            return end - self._t_start

    def snapshot(self) -> dict:
        """Per-state seconds (open span included), fractions of wall time,
        and the current state — the `/debug/perf` ledger view."""
        with self._lock:
            now = self._now()
            totals = dict(self.totals)
            if self._state is not None and self._t is not None:
                totals[self._state] += max(now - self._t, 0.0)
            if self._t_start is None:
                wall = 0.0
            else:
                end = self._t_close if self._t_close is not None else now
                wall = end - self._t_start
        covered = sum(totals.values())
        return {
            "state": self._state,
            "wall_s": round(wall, 6),
            "covered_s": round(covered, 6),
            "seconds": {s: round(v, 6) for s, v in totals.items()},
            "fractions": {s: round(v / wall, 6) if wall > 0 else 0.0
                          for s, v in totals.items()},
        }


# ---------------------------------------------------------- chunk pricing


def decode_step_bytes(*, n_layers: int, dim: int, hidden_dim: int,
                      kv_dim: int, head_size: int, n_kv_heads: int,
                      vocab_size: int, seq_len: int, weight_bytes: int,
                      slots: int, live_rows: float,
                      cache_bytes_per_el: int = 2, paged: bool = False,
                      page_size: int = 128,
                      paged_impl: str = "kernel") -> int:
    """Per-STEP HBM bytes of a ``slots``-wide batched decode — THE cost
    model (moved here from ``experiments/hbm_traffic.py``, which now
    delegates, so the offline roofline tables and the live attainment gauge
    price identically). The weight stream is read once per step and serves
    every slot; the KV stream scales with slots; activations scale with
    slots but stay negligible. ``live_rows`` is the per-slot live KV
    horizon in rows (the offline script passes ``live_frac * seq_len``; the
    live path passes the chunk's mean position).

    paged=True prices by the routed attention path (``paged_impl``, set
    from ``KernelSelection.attn_route``):

    * ``kernel`` — the Pallas flash-decode kernel: PER-PAGE KV reads (live
      rows round up to whole pages — the page DMA quantum) plus the i32
      block tables, scalar-prefetched ONCE per fused launch per layer (the
      fused scatter rides the same launch, so there is no second table
      read and no separate scatter dispatch).
    * ``gather`` — the jnp fallback: on top of the per-page pool reads,
      XLA MATERIALIZES the full ``max_blocks*page = seq_len``-row
      contiguous view for k and v (one write + one read of the whole view,
      per layer, every step) and reads the tables once per gather (k + v).
      This is the traffic blowup the kernel exists to remove — the two
      routes' bytes differ by design, not by drift."""
    L, d, h = n_layers, dim, hidden_dim
    m = max(8, slots)  # one fused step: all slots are rows of one matmul

    def mm_act(k, n):
        return m * k * 2 + m * n * 4

    acts = (mm_act(d, d) * 2 + mm_act(d, kv_dim) * 2
            + mm_act(d, h) * 2 + mm_act(h, d)) * L + mm_act(d, vocab_size)
    rows = float(live_rows)
    view_rows = 0.0
    if paged:
        # page-granular pruning horizon: live rows round up to whole pages
        rows = -(-int(rows) // page_size) * page_size
        if paged_impl == "gather":
            # full contiguous view, written then read, k and v, per layer
            view_rows = 2.0 * seq_len
    kv_stream = int(2 * slots * n_kv_heads * (rows + view_rows) * head_size
                    * cache_bytes_per_el) * L
    kv_write = 2 * slots * kv_dim * cache_bytes_per_el * L
    if not paged:
        table_read = 0
    elif paged_impl == "gather":
        table_read = 4 * slots * (seq_len // page_size) * 2 * L  # k + v gathers
    else:
        table_read = 4 * slots * (seq_len // page_size) * L  # one fused launch
    return int(weight_bytes + acts + kv_stream + kv_write + table_read
               + slots * d * 2)


@dataclass(frozen=True)
class ChunkCostModel:
    """Frozen per-engine pricing inputs for :func:`decode_step_bytes`
    (built once at scheduler construction by
    ``BatchEngine.chunk_cost_model()`` — ``weight_bytes`` is the engine's
    REAL resident parameter bytes, so an unquantized test model is priced
    as what it actually streams, not as a hypothetical Q40)."""

    n_layers: int
    dim: int
    hidden_dim: int
    kv_dim: int
    head_size: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    weight_bytes: int
    cache_bytes_per_el: int = 2
    paged: bool = False
    page_size: int = 128
    paged_impl: str = "kernel"  # 'kernel' | 'gather' (KernelSelection route)

    def step_bytes(self, slots: int, live_rows: float) -> int:
        return decode_step_bytes(
            n_layers=self.n_layers, dim=self.dim, hidden_dim=self.hidden_dim,
            kv_dim=self.kv_dim, head_size=self.head_size,
            n_kv_heads=self.n_kv_heads, vocab_size=self.vocab_size,
            seq_len=self.seq_len, weight_bytes=self.weight_bytes,
            slots=slots, live_rows=live_rows,
            cache_bytes_per_el=self.cache_bytes_per_el,
            paged=self.paged, page_size=self.page_size,
            paged_impl=self.paged_impl)


# -------------------------------------------------------------- SLO policy


@dataclass(frozen=True)
class SloPolicy:
    """Per-request latency targets (``--slo-ttft-ms`` / ``--slo-itl-ms``);
    None disables that kind. Verdicts are tri-state per kind: True (met),
    False (violated), None (no target, or the mark never happened — an
    errored request with no first token is unknowable, not a TTFT burn)."""

    ttft_ms: float | None = None
    itl_ms: float | None = None

    def enabled(self) -> bool:
        return self.ttft_ms is not None or self.itl_ms is not None

    @staticmethod
    def _judge(measured, target):
        if target is None or measured is None:
            return None, None
        over = float(measured) - float(target)
        return over <= 0.0, (round(over, 3) if over > 0 else None)

    def verdict(self, ttft_ms: float | None, itl_ms: float | None) -> dict:
        """{'ttft_ok', 'itl_ok', 'violated_by_ms': {...}, 'ok'} — `ok` is
        False iff some kind is measurably violated."""
        ttft_ok, ttft_over = self._judge(ttft_ms, self.ttft_ms)
        itl_ok, itl_over = self._judge(itl_ms, self.itl_ms)
        return {
            "ttft_ok": ttft_ok,
            "itl_ok": itl_ok,
            "violated_by_ms": {"ttft": ttft_over, "itl": itl_over},
            "ok": ttft_ok is not False and itl_ok is not False,
        }

    def verdict_from_marks(self, ttft_ms, e2e_ms, decode_tokens) -> dict:
        """Verdict from a flight-recorder record's marks (the `/debug/
        requests/{req_id}` postmortem): ITL is derived the same way
        Request.itl_ms derives it — (e2e - ttft) / (tokens - 1)."""
        itl = None
        if (ttft_ms is not None and e2e_ms is not None
                and decode_tokens is not None and decode_tokens >= 2):
            itl = (float(e2e_ms) - float(ttft_ms)) / (decode_tokens - 1)
        out = self.verdict(ttft_ms, itl)
        out["targets"] = {"ttft_ms": self.ttft_ms, "itl_ms": self.itl_ms}
        if itl is not None:
            out["itl_ms"] = round(itl, 3)
        return out


class PrefillBudgetController:
    """SLO-driven per-chunk prefill token budget (ISSUE 12): the online
    controller behind ``--prefill-budget auto``. Each hybrid step fuses up
    to ``current`` prompt tokens of an admitting request into the decode
    chunk's device launch; this controller shrinks/grows that budget from
    the windowed ITL headroom against ``SloPolicy.itl_ms``:

    * p95 ITL over the target (headroom < 0) → HALVE the budget (down to
      ``lo``): running streams are already missing their SLO, so admissions
      must slow down, not the decoders.
    * p95 ITL under ``grow_frac`` of the target (ample headroom) → DOUBLE
      the budget (up to ``hi``): decoders are comfortably inside SLO, so
      spend the slack on joiner TTFT.
    * in between → hold.

    With no ITL target (or an empty window) the controller holds ``start``
    — auto then behaves as a fixed budget, which is what a server with no
    SLO configured should do. Budgets move in powers of two so the fused
    hybrid step's prefill-slice shapes stay in the same small compile set
    as chunked admission always had (engine.pow2_chunk). Updates are
    rate-limited to ``interval_s`` so the quantile merge never rides the
    per-chunk hot path. The current budget is published as the
    ``dllama_prefill_budget_tokens`` gauge."""

    def __init__(self, slo: SloPolicy | None, *, lo: int = 16,
                 hi: int = 256, start: int = 64, grow_frac: float = 0.6,
                 interval_s: float = 0.25, now_fn=time.monotonic):
        self.slo = slo or SloPolicy()
        self.lo = max(1, int(lo))
        self.hi = max(self.lo, int(hi))
        self.current = min(max(int(start), self.lo), self.hi)
        self.grow_frac = float(grow_frac)
        self.interval_s = float(interval_s)
        self._now = now_fn
        self._t_last = None
        ins.PREFILL_BUDGET.set(self.current)

    def update(self, itl_window: "WindowQuantiles") -> int:
        """Re-evaluate against the window's p95 ITL (seconds); returns the
        (possibly unchanged) budget. Cheap no-op inside the rate limit."""
        now = self._now()
        if self._t_last is not None and now - self._t_last < self.interval_s:
            return self.current
        self._t_last = now
        target = self.slo.itl_ms
        if target is None:
            return self.current
        p95 = itl_window.quantile(0.95)
        if p95 is None:
            return self.current
        p95_ms = p95 * 1000.0
        if p95_ms > target:
            nxt = max(self.lo, self.current // 2)
        elif p95_ms < target * self.grow_frac:
            nxt = min(self.hi, self.current * 2)
        else:
            nxt = self.current
        if nxt != self.current:
            self.current = nxt
            ins.PREFILL_BUDGET.set(nxt)
        return self.current


# ------------------------------------------------------------- aggregator


class PerfAggregator:
    """The per-scheduler join of the three views: latency windows + SLO
    accounting (request finishes), and roofline pricing (decode chunks).
    Gauges live in the process registry (last scheduler wins, like every
    other serving gauge); ``refresh_gauges()`` runs at scrape time so the
    windowed values are current without putting quantile merges on the
    serving hot path."""

    def __init__(self, slo: SloPolicy | None = None,
                 cost_model: ChunkCostModel | None = None,
                 window_s: float = 60.0, slices: int = 6,
                 peak_gbs: float = PEAK_HBM_GBS, now_fn=time.monotonic):
        self.slo = slo or SloPolicy()
        self.cost_model = cost_model
        self.peak_gbs = float(peak_gbs)
        mk = lambda: WindowQuantiles(window_s, slices, now_fn=now_fn)
        self.ttft = mk()   # seconds
        self.itl = mk()    # seconds
        self.e2e = mk()    # seconds
        # request-flow window: finished counts + token sums (goodput and
        # throughput share this basis — both rate over FINISHED requests,
        # so goodput/throughput is a like-for-like fraction)
        self.flow = WindowSums(window_s, slices, now_fn=now_fn)
        # decode-chunk window: priced bytes vs measured device seconds
        self.chunks = WindowSums(window_s, slices, now_fn=now_fn)

    # ------------------------------------------------------------ feeding

    def observe_finish(self, *, finish_reason: str, ttft_ms, itl_ms, e2e_ms,
                       tokens: int) -> None:
        """One terminal request: feed the latency windows, judge the SLOs
        (burn counters per violated kind), and account goodput — tokens
        count toward goodput only when the request finished successfully
        (stop/length) AND met every configured SLO."""
        if ttft_ms is not None:
            self.ttft.observe(ttft_ms / 1000.0)
        if itl_ms is not None:
            self.itl.observe(itl_ms / 1000.0)
        if e2e_ms is not None:
            self.e2e.observe(e2e_ms / 1000.0)
        v = self.slo.verdict(ttft_ms, itl_ms)
        if v["ttft_ok"] is False:
            ins.SLO_VIOLATIONS.labels(kind="ttft").inc()
        if v["itl_ok"] is False:
            ins.SLO_VIOLATIONS.labels(kind="itl").inc()
        good = finish_reason in ("stop", "length") and v["ok"]
        self.flow.add(finished=1, ok=1 if v["ok"] else 0,
                      tokens=tokens, good_tokens=tokens if good else 0)

    def observe_chunk(self, *, occupancy: int, live_rows: float, steps: int,
                      tokens: int, device_s: float) -> None:
        """One consumed decode chunk: price its HBM traffic with the cost
        model (``steps`` fused steps at this occupancy and live-KV horizon)
        against its measured exclusive device window. Chunks with no
        measurable window (clock noise) still count their tokens."""
        fields = {"chunks": 1, "chunk_tokens": tokens,
                  "device_s": max(device_s, 0.0)}
        if self.cost_model is not None and occupancy > 0:
            fields["bytes"] = (self.cost_model.step_bytes(occupancy, live_rows)
                               * max(steps, 0))
        self.chunks.add(**fields)

    # ------------------------------------------------------------- reading

    def window_snapshot(self) -> dict:
        """p50/p95/p99 (ms) + counts for ttft/itl/e2e over the window."""
        out = {}
        for name, w in (("ttft", self.ttft), ("itl", self.itl),
                        ("e2e", self.e2e)):
            s = w.snapshot()
            out[name] = {
                "count": s["count"],
                **{p: (None if s[p] is None else round(s[p] * 1000.0, 3))
                   for p in ("p50", "p95", "p99")},
            }
        return out

    def slo_snapshot(self) -> dict:
        f = self.flow.totals()
        finished = f.get("finished", 0.0)
        att = (f.get("ok", 0.0) / finished) if finished else None
        return {
            "targets": {"ttft_ms": self.slo.ttft_ms,
                        "itl_ms": self.slo.itl_ms},
            "enabled": self.slo.enabled(),
            "window_finished": int(finished),
            "attainment": None if att is None else round(att, 4),
            "violations_total": {
                "ttft": ins.SLO_VIOLATIONS.labels(kind="ttft").value(),
                "itl": ins.SLO_VIOLATIONS.labels(kind="itl").value(),
            },
        }

    def roofline_snapshot(self) -> dict:
        c = self.chunks.totals()
        f = self.flow.totals()
        span = self.flow.span_s()
        device_s = c.get("device_s", 0.0)
        by = c.get("bytes", 0.0)
        # unpriced (no cost model) or unmeasured windows answer None, not a
        # false "0.0 attainment"
        achieved = (by / device_s) if (device_s > 0 and by > 0) else None
        att = (achieved / (self.peak_gbs * 1e9)
               if achieved is not None else None)
        thr = f.get("tokens", 0.0) / span
        good = f.get("good_tokens", 0.0) / span
        return {
            "priced": self.cost_model is not None,
            "window_chunks": int(c.get("chunks", 0.0)),
            "chunk_tokens": int(c.get("chunk_tokens", 0.0)),
            "device_s": round(device_s, 6),
            "bytes": int(by),
            "achieved_gbs": (None if achieved is None
                             else round(achieved / 1e9, 3)),
            "peak_gbs": self.peak_gbs,
            "bandwidth_attainment": (None if att is None
                                     else round(att, 6)),
            "throughput_tok_s": round(thr, 3),
            "goodput_tok_s": round(good, 3),
        }

    def refresh_gauges(self) -> None:
        """Push the windowed views into the registry gauges — called at
        scrape time (`/metrics`, `/debug/perf`) rather than per request.
        A drained window sets NaN (the Prometheus "no data" value, rendered
        as the grammar's NaN token) — never the last stale value: an idle
        server must not scrape as still carrying its old p95."""
        nan = float("nan")
        for name, w in (("ttft", self.ttft), ("itl", self.itl),
                        ("e2e", self.e2e)):
            s = w.snapshot()
            for p in ("p50", "p95", "p99"):
                ins.LATENCY_WINDOW.labels(metric=name, quantile=p).set(
                    nan if s[p] is None else s[p])
        slo = self.slo_snapshot()
        att = slo["attainment"]
        ins.SLO_ATTAINMENT.set(nan if att is None else att)
        roof = self.roofline_snapshot()
        bw = roof["bandwidth_attainment"]
        ins.BW_ATTAINMENT.set(nan if bw is None else bw)
        ins.THROUGHPUT.set(roof["throughput_tok_s"])
        ins.GOODPUT.set(roof["goodput_tok_s"])

    def snapshot(self, ledger: TimeLedger | None = None) -> dict:
        """The `/debug/perf` join: windowed quantiles, SLO accounting,
        ledger attribution, roofline/goodput — one JSON document."""
        out = {
            "window": self.window_snapshot(),
            "slo": self.slo_snapshot(),
            "roofline": self.roofline_snapshot(),
        }
        if ledger is not None:
            out["ledger"] = ledger.snapshot()
        return out
