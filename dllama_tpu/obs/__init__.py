"""Serving telemetry: metrics registry + Prometheus exposition + request-id
minting. Strictly stdlib (no jax, no third-party) so every layer — engine,
scheduler, API tier, fault injection — can import it without cycles or
optional-dependency gates.

* :mod:`dllama_tpu.obs.metrics` — the registry core and text exposition.
* :mod:`dllama_tpu.obs.instruments` — the dllama_* metrics catalog.
* :mod:`dllama_tpu.obs.trace` — request-flow span tracing: the bounded
  ring-buffer tracer + per-request flight recorder behind
  ``GET /debug/trace`` (Perfetto) and ``GET /debug/requests`` (CLI:
  ``--trace-buffer``).
* :func:`new_request_id` — per-request ids (``req_...``) minted at HTTP
  admission and propagated api -> scheduler -> engine; every response
  carries the id in ``X-Request-Id``, every request-scoped log line
  carries it as the ``request_id`` field, and every trace span carries it
  in its args — one id correlates all three.
"""

from __future__ import annotations

import re
import uuid

from dllama_tpu.obs import metrics, trace
from dllama_tpu.obs.metrics import REGISTRY

_REQ_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,128}$")


def new_request_id(client_supplied: str | None = None) -> str:
    """Mint a ``req_<hex>`` id — or adopt a well-formed client-supplied
    ``X-Request-Id`` verbatim so upstream traces correlate end to end (a
    malformed one is replaced, never echoed into headers/logs)."""
    if client_supplied and _REQ_ID_RE.match(client_supplied):
        return client_supplied
    return "req_" + uuid.uuid4().hex[:24]


__all__ = ["metrics", "trace", "REGISTRY", "new_request_id"]
