"""The serving-stack metrics catalog — every dllama_* series in one place.

One definition site so the README table, the scrape output, and the
instrumented code can't drift apart. Import the module and touch the
instruments directly::

    from dllama_tpu.obs import instruments as ins
    ins.TOKENS_GENERATED.inc(4)
    ins.REQUESTS_SHED.labels(reason="queue_full").inc()

Everything lives in the global :data:`dllama_tpu.obs.metrics.REGISTRY`
(what `GET /metrics` renders). Conventions: durations in SECONDS with a
``_seconds`` suffix (Prometheus idiom — the host code's ms values are
converted at the observation site), monotonic counts end in ``_total``.
"""

from __future__ import annotations

from dllama_tpu.obs import metrics

# ------------------------------------------------------------ request flow

REQUESTS_ADMITTED = metrics.counter(
    "dllama_requests_admitted_total",
    "Requests accepted into the scheduler admission queue")
REQUESTS_SHED = metrics.counter(
    "dllama_requests_shed_total",
    "Requests rejected at admission, by reason "
    "(queue_full=429, draining/unhealthy=503)",
    ("reason",))
REQUESTS_FINISHED = metrics.counter(
    "dllama_requests_finished_total",
    "Requests that reached a terminal state, by finish_reason "
    "(stop/length = success; cancelled, error, shutdown = not)",
    ("reason",))
HTTP_RESPONSES = metrics.counter(
    "dllama_http_responses_total",
    "HTTP responses sent, by normalized endpoint and status code "
    "(covers both serving tiers; streams count at header time)",
    ("endpoint", "code"))

# ------------------------------------------------------------------ tokens

TOKENS_GENERATED = metrics.counter(
    "dllama_tokens_generated_total",
    "Completion tokens emitted to clients (both serving tiers)")
PREFILL_TOKENS = metrics.counter(
    "dllama_prefill_tokens_total",
    "Prompt tokens whose KV rows were computed (cache reuse excluded)")
REUSED_PREFIX_TOKENS = metrics.counter(
    "dllama_reused_prefix_tokens_total",
    "Prompt tokens served from a cached KV prefix instead of prefill")

# ----------------------------------------------------------------- gauges

BUILD_INFO = metrics.gauge(
    "dllama_tpu_build_info",
    "Always 1; the labels carry what is running — package version, jax "
    "version, jax backend platform, and whether the overlapped decode "
    "pipeline is active (on/off, or n/a on the single-engine tier)",
    ("version", "jax", "backend", "overlap"))
QUEUE_DEPTH = metrics.gauge(
    "dllama_queue_depth", "Requests waiting in the admission queue")
BUSY_SLOTS = metrics.gauge(
    "dllama_busy_slots", "Cache slots actively decoding")
SLOTS_TOTAL = metrics.gauge(
    "dllama_slots_total", "Configured continuous-batching cache slots")
MODEL_PARAMS_BYTES = metrics.gauge(
    "dllama_model_params_bytes", "Model parameter bytes resident in HBM")
KV_CACHE_BYTES = metrics.gauge(
    "dllama_kv_cache_bytes", "KV-cache bytes resident in HBM")
KV_PAGES_TOTAL = metrics.gauge(
    "dllama_kv_pages_total",
    "Paged KV cache: usable pages in the global pool (0 = dense layout)")
KV_PAGES_USED = metrics.gauge(
    "dllama_kv_pages_used",
    "Paged KV cache: pages currently referenced by at least one slot")
KV_PAGES_SHARED = metrics.gauge(
    "dllama_kv_pages_shared",
    "Paged KV cache: pages referenced by more than one slot "
    "(copy-on-write prefix sharing)")

# ------------------------------------------------------------- histograms

TTFT_SECONDS = metrics.histogram(
    "dllama_ttft_seconds",
    "Time to first token, queueing + prefill included (per request)",
    buckets=metrics.LATENCY_BUCKETS_S)
ITL_SECONDS = metrics.histogram(
    "dllama_itl_seconds",
    "Mean inter-token latency after the first token (per request)",
    buckets=metrics.CHUNK_BUCKETS_S)
E2E_SECONDS = metrics.histogram(
    "dllama_e2e_latency_seconds",
    "Submit-to-finish request latency (per request)",
    buckets=metrics.LATENCY_BUCKETS_S)
PREFILL_CHUNK_SECONDS = metrics.histogram(
    "dllama_prefill_chunk_seconds",
    "Host wall time of one prefill chunk (dispatch only unless the caller "
    "syncs; the scheduler syncs whenever decoders would stall)",
    buckets=metrics.CHUNK_BUCKETS_S)
DECODE_CHUNK_SECONDS = metrics.histogram(
    "dllama_decode_chunk_seconds",
    "Wall time of ONE fused decode chunk, observed when its tokens "
    "materialize on host (device-real under the overlapped pipeline too: "
    "the clock starts at the later of the chunk's dispatch and the "
    "previous chunk's consumption, so a chunk dispatched while its "
    "predecessor still runs is not billed the predecessor's tail)",
    buckets=metrics.CHUNK_BUCKETS_S)
DECODE_HOST_GAP_SECONDS = metrics.histogram(
    "dllama_decode_host_gap_seconds",
    "Inter-chunk host gap: wall time from one decode chunk's tokens "
    "materializing to the next chunk's dispatch — the device-idle window "
    "host scheduling inserts; ~0 with --overlap on (the successor "
    "dispatches before the previous chunk is consumed)",
    buckets=metrics.CHUNK_BUCKETS_S)
BATCH_OCCUPANCY = metrics.histogram(
    "dllama_batch_occupancy",
    "Active slots per fused decode chunk (mean = _sum/_count)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
ADMISSION_STALL_SECONDS = metrics.histogram(
    "dllama_admission_stall_seconds",
    "Decode-to-decode gap inserted by admission work between fused chunks "
    "(what batch-mates' ITL degrades by during a join)",
    buckets=metrics.CHUNK_BUCKETS_S)
TOKEN_LATENCY_SECONDS = metrics.histogram(
    "dllama_token_latency_seconds",
    "Per-token host latency recorded by utils.profiling.TokenTimer "
    "(single-engine inference loop)",
    buckets=metrics.CHUNK_BUCKETS_S)

# ------------------------------------------------------------ supervision

FAULT_FIRES = metrics.counter(
    "dllama_fault_fires_total",
    "Armed fault-injection activations (utils/faults.py), by point/action",
    ("point", "action"))
WATCHDOG_STALLS = metrics.counter(
    "dllama_watchdog_stalls_total",
    "Watchdog verdicts: worker silent past --stall-deadline-s with work owed")
WATCHDOG_RECOVERIES = metrics.counter(
    "dllama_watchdog_recoveries_total",
    "Watchdog stall flags cleared after heartbeats resumed")
ENGINE_RESTARTS = metrics.counter(
    "dllama_engine_restarts_total",
    "Warm engine restarts after a worker crash: decode state + page pool "
    "rebuilt against resident weights (no model reload), --restart-max "
    "budgeted")
REQUESTS_RECOVERED = metrics.counter(
    "dllama_requests_recovered_total",
    "Requests that survived a warm restart and re-entered a slot (mid-"
    "stream resumes re-prefill prompt + emitted tokens; mid-prefill "
    "admissions restart their prefill)")
KV_AUDIT_FAILURES = metrics.counter(
    "dllama_kv_audit_failures_total",
    "PagePool.audit() invariant violations + double-release guards: any "
    "nonzero value means the paged KV allocator state was corrupt")
