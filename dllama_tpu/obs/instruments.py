"""The serving-stack metrics catalog — every dllama_* series in one place.

One definition site so the README table, the scrape output, and the
instrumented code can't drift apart. Import the module and touch the
instruments directly::

    from dllama_tpu.obs import instruments as ins
    ins.TOKENS_GENERATED.inc(4)
    ins.REQUESTS_SHED.labels(reason="queue_full").inc()

Everything lives in the global :data:`dllama_tpu.obs.metrics.REGISTRY`
(what `GET /metrics` renders). Conventions: durations in SECONDS with a
``_seconds`` suffix (Prometheus idiom — the host code's ms values are
converted at the observation site), monotonic counts end in ``_total``.
"""

from __future__ import annotations

import threading
import time

from dllama_tpu.obs import metrics

# ------------------------------------------------------------ request flow

REQUESTS_ADMITTED = metrics.counter(
    "dllama_requests_admitted_total",
    "Requests accepted into the scheduler admission queue")
REQUESTS_SHED = metrics.counter(
    "dllama_requests_shed_total",
    "Requests rejected at admission, by reason "
    "(queue_full=429, draining/unhealthy=503)",
    ("reason",))
REQUESTS_FINISHED = metrics.counter(
    "dllama_requests_finished_total",
    "Requests that reached a terminal state, by finish_reason "
    "(stop/length = success; cancelled, error, shutdown = not)",
    ("reason",))
HTTP_RESPONSES = metrics.counter(
    "dllama_http_responses_total",
    "HTTP responses sent, by normalized endpoint and status code "
    "(covers both serving tiers; streams count at header time)",
    ("endpoint", "code"))

# ------------------------------------------------------------------ tokens

TOKENS_GENERATED = metrics.counter(
    "dllama_tokens_generated_total",
    "Completion tokens emitted to clients (both serving tiers)")
PREFILL_TOKENS = metrics.counter(
    "dllama_prefill_tokens_total",
    "Prompt tokens whose KV rows were computed (cache reuse excluded)")
REUSED_PREFIX_TOKENS = metrics.counter(
    "dllama_reused_prefix_tokens_total",
    "Prompt tokens served from a cached KV prefix instead of prefill")

# ------------------------------------------------ speculative decoding

SPEC_CYCLES = metrics.counter(
    "dllama_spec_cycles_total",
    "Batched speculative verify cycles consumed by the serving tier (one "
    "K+1-wide forward each; emitted/cycles is the realized speedup)")
SPEC_TOKENS = metrics.counter(
    "dllama_spec_tokens_total",
    "Speculative-decoding token flow, by kind: drafted = n-gram draft "
    "tokens verified, accepted = drafts the model agreed with, emitted = "
    "all tokens spec cycles produced (incl. the bonus token and non-spec "
    "rows' single tokens)",
    ("kind",))
SPEC_ACCEPTED_LENGTH = metrics.histogram(
    "dllama_spec_accepted_length",
    "Accepted draft-prefix length per greedy speculative row per verify "
    "cycle (0 = only the bonus token emitted; mean = _sum/_count is the "
    "acceptance rate the spec speedup multiplies from)",
    buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16))

# ------------------------------------ hybrid prefill & preemption (ISSUE 12)

PREFILL_BUDGET = metrics.gauge(
    "dllama_prefill_budget_tokens",
    "Hybrid chunked prefill: prompt tokens the next fused decode chunk may "
    "co-process for an admitting request (--prefill-budget; 'auto' is "
    "steered online by the windowed ITL headroom against --slo-itl-ms; "
    "0 = legacy phase-split admission)")
PREEMPTIONS = metrics.counter(
    "dllama_preemptions_total",
    "Running requests suspended at a chunk boundary to make room for "
    "higher-priority work, by reason (slot = a higher-priority request "
    "needed the slot, capacity = it needed KV pages). The victim's pages "
    "stay referenced (radix tree / kept rows); it resumes later with its "
    "recorded PRNG key — byte-identical continuation, near-zero recompute",
    ("reason",))
RESUMED = metrics.counter(
    "dllama_resumed_total",
    "Preempted requests that re-entered a slot and continued their stream "
    "(companion of dllama_preemptions_total; a persistent gap between the "
    "two means preempted work is parked behind sustained higher-priority "
    "load)")

# -------------------------------------------------- radix prefix cache

RADIX_LOOKUPS = metrics.counter(
    "dllama_radix_lookups_total",
    "Radix prefix-tree walks at admission, by outcome (hit = at least one "
    "reusable row; retried admissions of a capacity-deferred request count "
    "each walk)",
    ("outcome",))
RADIX_HIT_TOKENS = metrics.counter(
    "dllama_radix_hit_tokens_total",
    "Prompt rows mapped from the radix prefix tree instead of prefilled "
    "(saved-prefill tokens; counted at commit, so aborted admissions "
    "never inflate it)")
RADIX_NODES = metrics.gauge(
    "dllama_radix_nodes",
    "Radix prefix tree: live nodes (page-granular edges; 0 when the cache "
    "is off or the layout is dense)")
RADIX_PAGES = metrics.gauge(
    "dllama_radix_pages",
    "Radix prefix tree: KV pool pages the tree holds references to "
    "(reclaimable by LRU eviction before admissions defer)")

# ------------------------------------- router & aio front-end (ISSUE 15)

ROUTER_REQUESTS = metrics.counter(
    "dllama_router_requests_total",
    "Router-proxied completion requests, by replica and outcome (ok = "
    "forwarded and answered, 4xx included — the replica spoke, the client "
    "erred; error = replica answered a 5xx, passed through; busy = "
    "replica shed 429/503, tried elsewhere; rerouted = replica failed "
    "before any response byte, request moved to a survivor; stream_error "
    "= replica died mid-stream, stream failed cleanly with "
    "finish_reason=error; client_gone = client hung up mid-stream; shed = "
    "no replica could take it, replica=none)",
    ("replica", "outcome"))
ROUTER_AFFINITY_HITS = metrics.counter(
    "dllama_router_affinity_hits_total",
    "Requests routed to the replica their prefix fingerprint was pinned "
    "to (the radix-cache-warm replica) — hits/requests is the warm-routing "
    "rate the router's TTFT win comes from")
REPLICA_HEALTHY = metrics.gauge(
    "dllama_replica_healthy",
    "Router's live view of each replica (1 = /health reports live; 0 = "
    "dead or unreachable — flips immediately on a failed proxy attempt, "
    "not a poll later)",
    ("replica",))
FRONTEND_CONNECTIONS = metrics.gauge(
    "dllama_frontend_connections",
    "Open client connections per aio event loop, labeled by the server's "
    "bound address (one process may host several loops: replica + router "
    "fronts). Threads do NOT scale with this — compare "
    "dllama_process_threads; the threads front-end does not move this "
    "gauge",
    ("server",))
REPLICA_CLOCK_OFFSET = metrics.gauge(
    "dllama_replica_clock_offset_seconds",
    "Router's NTP-lite estimate of each replica's monotonic-clock offset "
    "(replica clock minus router clock, min-RTT sample over the health-poll "
    "window) — what GET /router/trace shifts that replica's spans by to "
    "land them on the merged mesh timeline",
    ("replica",))
REPLICA_CLOCK_UNCERTAINTY = metrics.gauge(
    "dllama_replica_clock_uncertainty_seconds",
    "Error bound of the offset estimate (half the min round-trip of the "
    "window: the remote clock read can sit anywhere inside the round-trip) "
    "— merged-trace alignment is only trusted to this resolution",
    ("replica",))
FEDERATION_SCRAPE_SECONDS = metrics.histogram(
    "dllama_router_federation_scrape_seconds",
    "Wall time of one GET /router/metrics federation pass: concurrent "
    "scrape of every live replica + relabel/merge into one exposition "
    "(the router's own registry renders inside this window too)",
    buckets=metrics.LATENCY_BUCKETS_S)
FLEET_SCRAPE_AGE = metrics.gauge(
    "dllama_fleet_scrape_age_seconds",
    "Age of each replica's last SUCCESSFUL /metrics scrape at federation "
    "time — a dead replica's cached series keep federating (last-known "
    "values) while this gauge grows, so the fleet view reads STALE, never "
    "as zero traffic; alert on it instead of on vanishing series",
    ("replica",))
ROUTER_TTFT_SECONDS = metrics.histogram(
    "dllama_router_ttft_seconds",
    "CLIENT-perspective time to first token measured at the router "
    "(request arrival to the first content frame relayed; non-streamed "
    "requests observe their full proxy latency) — includes connect, "
    "routing, queueing, and any failover the replica-side "
    "dllama_ttft_seconds cannot see",
    buckets=metrics.LATENCY_BUCKETS_S)
ROUTER_ITL_SECONDS = metrics.histogram(
    "dllama_router_itl_seconds",
    "CLIENT-perspective mean inter-token latency per proxied stream "
    "(first to last content frame over frames-1, measured at the router) "
    "— a failover's backoff + resume gap lands here, invisible to any "
    "single replica's dllama_itl_seconds",
    buckets=metrics.CHUNK_BUCKETS_S)
ROUTER_SLO_ATTAINMENT = metrics.gauge(
    "dllama_router_slo_attainment",
    "Windowed fraction of proxied requests finishing inside every "
    "configured SLO (--slo-ttft-ms / --slo-itl-ms) as the CLIENT saw "
    "them, per serving replica plus the replica=\"fleet\" rollup; a gap "
    "vs the replicas' own dllama_slo_attainment is network/failover-"
    "induced violation the replicas cannot measure (refreshed at scrape)",
    ("replica",))
ROUTER_FAILOVERS = metrics.counter(
    "dllama_router_failovers_total",
    "Mid-stream cross-replica failovers, by outcome (resumed = the stream "
    "was resubmitted to a survivor and finished from its journal position; "
    "retried = one resume attempt was dispatched, whatever came of it; "
    "exhausted = the per-stream --failover-max budget ran out and the "
    "stream failed with today's exactly-once error; unresumable = no "
    "journal entry / terminal frame already relayed / journal ring full — "
    "same exactly-once error contract)",
    ("outcome",))

# ----------------------------------------------------------------- gauges

BUILD_INFO = metrics.gauge(
    "dllama_tpu_build_info",
    "Always 1; the labels carry what is running — package version, jax "
    "version, jax backend platform, whether the overlapped decode "
    "pipeline is active (on/off, or n/a on the single-engine tier), and "
    "the boot warmup mode (auto = the compiled-shape universe was "
    "precompiled before traffic; off; n/a on the single-engine tier)",
    ("version", "jax", "backend", "overlap", "warmup"))
QUEUE_DEPTH = metrics.gauge(
    "dllama_queue_depth", "Requests waiting in the admission queue")
BUSY_SLOTS = metrics.gauge(
    "dllama_busy_slots", "Cache slots actively decoding")
SLOTS_TOTAL = metrics.gauge(
    "dllama_slots_total", "Configured continuous-batching cache slots")
MODEL_PARAMS_BYTES = metrics.gauge(
    "dllama_model_params_bytes", "Model parameter bytes resident in HBM")
KV_CACHE_BYTES = metrics.gauge(
    "dllama_kv_cache_bytes", "KV-cache bytes resident in HBM")
KV_PAGES_TOTAL = metrics.gauge(
    "dllama_kv_pages_total",
    "Paged KV cache: usable pages in the global pool (0 = dense layout)")
KV_PAGES_USED = metrics.gauge(
    "dllama_kv_pages_used",
    "Paged KV cache: pages currently referenced by at least one slot")
KV_PAGES_SHARED = metrics.gauge(
    "dllama_kv_pages_shared",
    "Paged KV cache: pages with more than one referent — several slots, "
    "or a slot plus the radix prefix tree (copy-on-write prefix sharing)")
KV_HOST_PAGES_TOTAL = metrics.gauge(
    "dllama_kv_host_pages_total",
    "Host-RAM KV spill tier (--kv-host-pages): page slots in the pinned "
    "host buffer pool (0 = tier off; radix eviction discards cold pages)")
KV_HOST_PAGES_USED = metrics.gauge(
    "dllama_kv_host_pages_used",
    "Host-RAM KV spill tier: spilled pages currently resident on the "
    "host — restore-on-hit pops them back to the device at admission, "
    "LRU pressure drops the coldest")
KV_SPILL = metrics.counter(
    "dllama_kv_spill_total",
    "Host-tier page movements by direction (out = device page spilled "
    "d2h at a radix eviction instead of being discarded; in = host page "
    "restored h2d into the radix tree at an admission lookup)",
    ("direction",))

# ------------------------------------------------------------- histograms

TTFT_SECONDS = metrics.histogram(
    "dllama_ttft_seconds",
    "Time to first token, queueing + prefill included (per request)",
    buckets=metrics.LATENCY_BUCKETS_S)
ITL_SECONDS = metrics.histogram(
    "dllama_itl_seconds",
    "Mean inter-token latency after the first token (per request)",
    buckets=metrics.CHUNK_BUCKETS_S)
E2E_SECONDS = metrics.histogram(
    "dllama_e2e_latency_seconds",
    "Submit-to-finish request latency (per request)",
    buckets=metrics.LATENCY_BUCKETS_S)
PREFILL_CHUNK_SECONDS = metrics.histogram(
    "dllama_prefill_chunk_seconds",
    "Host wall time of one prefill chunk (dispatch only unless the caller "
    "syncs; the scheduler syncs whenever decoders would stall)",
    buckets=metrics.CHUNK_BUCKETS_S)
DECODE_CHUNK_SECONDS = metrics.histogram(
    "dllama_decode_chunk_seconds",
    "Wall time of ONE fused decode chunk, observed when its tokens "
    "materialize on host (device-real under the overlapped pipeline too: "
    "the clock starts at the later of the chunk's dispatch and the "
    "previous chunk's consumption, so a chunk dispatched while its "
    "predecessor still runs is not billed the predecessor's tail)",
    buckets=metrics.CHUNK_BUCKETS_S)
DECODE_HOST_GAP_SECONDS = metrics.histogram(
    "dllama_decode_host_gap_seconds",
    "Inter-chunk host gap: wall time from one decode chunk's tokens "
    "materializing to the next chunk's dispatch — the device-idle window "
    "host scheduling inserts; ~0 with --overlap on (the successor "
    "dispatches before the previous chunk is consumed)",
    buckets=metrics.CHUNK_BUCKETS_S)
BATCH_OCCUPANCY = metrics.histogram(
    "dllama_batch_occupancy",
    "Active slots per fused decode chunk (mean = _sum/_count)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
ADMISSION_STALL_SECONDS = metrics.histogram(
    "dllama_admission_stall_seconds",
    "Decode-to-decode gap inserted by admission work between fused chunks "
    "(what batch-mates' ITL degrades by during a join)",
    buckets=metrics.CHUNK_BUCKETS_S)
TOKEN_LATENCY_SECONDS = metrics.histogram(
    "dllama_token_latency_seconds",
    "Per-token host latency recorded by utils.profiling.TokenTimer "
    "(single-engine inference loop)",
    buckets=metrics.CHUNK_BUCKETS_S)

# ------------------------------------------------- SLO & saturation (perf)

SCHEDULER_TIME = metrics.counter(
    "dllama_scheduler_time_seconds_total",
    "Scheduler worker wall time attributed to exactly one exclusive state "
    "(obs/perf.TimeLedger): the per-state totals partition loop wall time "
    "by construction, so fractions answer 'what is the scheduler doing'",
    ("state",))
SLO_VIOLATIONS = metrics.counter(
    "dllama_slo_violations_total",
    "Terminal requests that missed a configured SLO target, by kind "
    "(ttft vs --slo-ttft-ms, itl vs --slo-itl-ms); burn-rate source",
    ("kind",))
SLO_ATTAINMENT = metrics.gauge(
    "dllama_slo_attainment",
    "Fraction of requests finishing inside every configured SLO over the "
    "sliding window (1.0 with no violations; refreshed at scrape time)")
LATENCY_WINDOW = metrics.gauge(
    "dllama_latency_window_seconds",
    "Sliding-window latency quantiles (obs/perf.WindowQuantiles) for "
    "metric=ttft|itl|e2e at quantile=p50|p95|p99 — the live-tail view the "
    "per-request histograms cannot give without a quantile-capable backend",
    ("metric", "quantile"))
BW_ATTAINMENT = metrics.gauge(
    "dllama_decode_bandwidth_attainment",
    "Windowed decode HBM-bandwidth attainment: priced chunk bytes "
    "(experiments/hbm_traffic.py's cost model, one definition site in "
    "obs/perf.decode_step_bytes) / measured device seconds / peak HBM GB/s")
THROUGHPUT = metrics.gauge(
    "dllama_throughput_tok_s",
    "Windowed completion-token rate over finished requests (scrape-time "
    "refresh; companion of the goodput gauge)")
GOODPUT = metrics.gauge(
    "dllama_goodput_tok_s",
    "Windowed GOODPUT token rate: only tokens of requests that finished "
    "stop/length within every configured SLO count (goodput/throughput is "
    "the useful-work fraction)")

# ------------------------------------- compile & device traffic (ISSUE 13)

JIT_COMPILES = metrics.counter(
    "dllama_jit_compiles_total",
    "Observed XLA jit traces/compiles, by dispatch-site function label "
    "(obs/compile.COMPILE_FNS; 'untracked' = compiles outside any "
    "instrumented site). Steady-state serving must not move this at all — "
    "a nonzero rate mid-traffic is a recompile storm stealing device time",
    ("fn",))
JIT_COMPILE_SECONDS = metrics.counter(
    "dllama_jit_compile_seconds_total",
    "Wall seconds spent tracing/lowering/compiling, by function label "
    "(the jax.monitoring /jax/core/compile/* durations, attributed by the "
    "compile ledger's dispatch-site scopes)",
    ("fn",))
JIT_UNEXPECTED_COMPILES = metrics.counter(
    "dllama_jit_unexpected_compiles_total",
    "Compiles whose shape-bucket key fell OUTSIDE the declared contract "
    "(obs/compile.ShapeContract): each one also logs a structured warning "
    "naming the offending shape. Any nonzero value means the bounded "
    "compiled-shape universe the perf work assumes has been violated",
    ("fn",))
TRANSFERS = metrics.counter(
    "dllama_transfers_total",
    "Host<->device transfers at the engine boundary, by direction "
    "(h2d/d2h) and site (obs/compile.TRANSFER_SITES): uploads happen at "
    "admission/commit/release boundaries only — a per-chunk h2d rate in "
    "steady-state decode is the PR 3 invariant breaking",
    ("direction", "site"))
TRANSFER_BYTES = metrics.counter(
    "dllama_transfer_bytes_total",
    "Bytes moved by the transfers dllama_transfers_total counts, same "
    "direction/site labels",
    ("direction", "site"))
DEVICE_LIVE_BUFFERS = metrics.gauge(
    "dllama_device_live_buffers",
    "Live jax arrays on the backend (jax.live_arrays), refreshed at "
    "scrape time — a monotone climb under steady traffic is a device-"
    "memory leak showing before the OOM does")
DEVICE_LIVE_BYTES = metrics.gauge(
    "dllama_device_live_bytes",
    "Bytes held by the live jax arrays (companion of "
    "dllama_device_live_buffers; params + KV + decode state + transients)")

# -------------------------------------------------- process self-metrics

PROCESS_UPTIME = metrics.gauge(
    "dllama_process_uptime_seconds",
    "Seconds since the serving process imported its metrics catalog "
    "(refreshed at scrape time)")
PROCESS_RSS = metrics.gauge(
    "dllama_process_rss_bytes",
    "Resident-set size of the serving process (/proc/self/statm; 0 when "
    "the platform exposes neither procfs nor resource.getrusage)")
PROCESS_THREADS = metrics.gauge(
    "dllama_process_threads",
    "Live Python threads (threading.active_count): worker + watchdog + "
    "HTTP handler threads; a leak here shows before the OOM does")

_PROC_START = time.monotonic()
_PAGE_SIZE = 4096
try:  # resource is stdlib but not on every platform
    import resource as _resource
except ImportError:  # pragma: no cover
    _resource = None
try:
    import os as _os

    _PAGE_SIZE = _os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):  # pragma: no cover
    pass


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    if _resource is not None:  # pragma: no cover - non-procfs fallback
        # ru_maxrss is the PEAK (not current) — still better than nothing
        # where /proc is absent. Unit is platform-defined: bytes on darwin,
        # kilobytes on linux/BSD (getrusage(2))
        import sys as _sys

        peak = _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
        return int(peak) * (1 if _sys.platform == "darwin" else 1024)
    return 0  # pragma: no cover


def refresh_process_gauges() -> dict:
    """Refresh + return the process self-metrics (uptime, RSS, threads).
    Called at scrape time (`/metrics`, `/health`, `/debug/perf`) rather
    than on a timer — gauges are as fresh as their last read."""
    up = time.monotonic() - _PROC_START
    rss = _rss_bytes()
    threads = threading.active_count()
    PROCESS_UPTIME.set(up)
    PROCESS_RSS.set(rss)
    PROCESS_THREADS.set(threads)
    return {"uptime_s": round(up, 3), "rss_bytes": rss, "threads": threads}


# ------------------------------------------------------------ supervision

FAULT_FIRES = metrics.counter(
    "dllama_fault_fires_total",
    "Armed fault-injection activations (utils/faults.py), by point/action",
    ("point", "action"))
WATCHDOG_STALLS = metrics.counter(
    "dllama_watchdog_stalls_total",
    "Watchdog verdicts: worker silent past --stall-deadline-s with work owed")
WATCHDOG_RECOVERIES = metrics.counter(
    "dllama_watchdog_recoveries_total",
    "Watchdog stall flags cleared after heartbeats resumed")
ENGINE_RESTARTS = metrics.counter(
    "dllama_engine_restarts_total",
    "Warm engine restarts after a worker crash: decode state + page pool "
    "rebuilt against resident weights (no model reload), --restart-max "
    "budgeted")
REQUESTS_RECOVERED = metrics.counter(
    "dllama_requests_recovered_total",
    "Requests that survived a warm restart and re-entered a slot (mid-"
    "stream resumes re-prefill prompt + emitted tokens; mid-prefill "
    "admissions restart their prefill)")
KV_AUDIT_FAILURES = metrics.counter(
    "dllama_kv_audit_failures_total",
    "PagePool.audit() invariant violations + double-release guards: any "
    "nonzero value means the paged KV allocator state was corrupt")
