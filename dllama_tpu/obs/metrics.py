"""Dependency-free metrics core: a thread-safe registry of counters, gauges
and fixed-bucket histograms (all with optional labels), rendered in the
Prometheus text exposition format (version 0.0.4) for `GET /metrics`.

Why hand-rolled: the container bakes no prometheus_client, and the serving
hot paths need exactly three instrument kinds — a few hundred lines of
stdlib beat an optional dependency every deploy target would have to
vendor. The exposition *grammar* is the real contract (scrapers parse it);
tests/test_metrics.py checks it line by line, including label escaping and
the `_bucket`/`_sum`/`_count` histogram invariants.

Usage::

    from dllama_tpu.obs import metrics
    REQS = metrics.counter("dllama_requests_admitted_total", "Requests admitted")
    SHED = metrics.counter("dllama_requests_shed_total", "Requests shed", ("reason",))
    SHED.labels(reason="queue_full").inc()
    text = metrics.REGISTRY.render()        # what GET /metrics serves

Instruments registered through the module-level helpers live in the global
``REGISTRY``; registration is idempotent (the same name returns the same
family — schedulers/engines are constructed many times per process in
tests). Tests needing isolation build private :class:`Registry` instances.
All mutating paths take the family lock, so request threads, the scheduler
worker, and the scrape handler can hit the same series concurrently.
"""

from __future__ import annotations

import bisect
import math
import re

from dllama_tpu.utils import locks

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default duration buckets (seconds): spans sub-ms CPU-test chunks through
#: minute-long cold starts
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
#: finer buckets for per-chunk / inter-token durations
CHUNK_BUCKETS_S = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(h: str) -> str:
    """HELP-line escaping: backslash and newline only (quotes are legal)."""
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    """Render a sample value: integers without a trailing .0, infinities as
    the +Inf/-Inf tokens the `le` label grammar requires."""
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Family:
    """A named metric with a fixed label-name tuple; `labels()` returns the
    per-label-value child carrying the actual value(s)."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames=()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r} for {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        # guards children dict AND child state. LEAF rank (utils/locks):
        # render/observe paths must never acquire anything under it
        self._lock = locks.make_lock("obs.metrics")
        self._children: dict[tuple, object] = {}

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            extra = set(kv) - set(self.labelnames)
            if extra:
                raise ValueError(f"unknown labels {sorted(extra)} for {self.name}")
            try:
                values = tuple(str(kv[k]) for k in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e.args[0]!r} for {self.name}") from None
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} wants labels {self.labelnames}, got {values!r}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._make_child()
        return child

    def _label_str(self, values, extra: str = "") -> str:
        parts = [f'{k}="{escape_label_value(v)}"'
                 for k, v in zip(self.labelnames, values)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self, out: list[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            for values in sorted(self._children):
                self._render_child(out, values, self._children[values])

    def _render_child(self, out, values, child) -> None:  # pragma: no cover
        raise NotImplementedError


class _ValueChild:
    __slots__ = ("_lock", "_value")

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0

    def value(self) -> float:
        with self._lock:
            return self._value


class _CounterChild(_ValueChild):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount


class _GaugeChild(_ValueChild):
    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum")

    def __init__(self, lock, buckets):
        self._lock = lock
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last slot = (last, +Inf]
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.sum += v
            # le is inclusive: bisect_left puts an exact boundary hit in
            # that boundary's own bucket
            self.counts[bisect.bisect_left(self.buckets, v)] += 1

    def observe_n(self, v: float, n: int) -> None:
        """Record the same value n times in one locked update — for hot
        paths that fold a batch of identical observations (e.g. a spec
        chunk's accepted-length counts via bincount) instead of paying a
        Python call per sample."""
        if n <= 0:
            return
        v = float(v)
        with self._lock:
            self.sum += v * n
            self.counts[bisect.bisect_left(self.buckets, v)] += n

    def count(self) -> int:
        with self._lock:
            return sum(self.counts)


class Counter(_Family):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        """No-label convenience (family with labelnames=())."""
        self.labels().inc(amount)

    def value(self) -> float:
        return self.labels().value()

    def _render_child(self, out, values, child) -> None:
        # caller holds self._lock (same lock guards child._value)
        out.append(f"{self.name}{self._label_str(values)} "
                   f"{format_value(child._value)}")


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def value(self) -> float:
        return self.labels().value()

    _render_child = Counter._render_child


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=LATENCY_BUCKETS_S):
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets or any(b != b or b == math.inf for b in buckets):
            raise ValueError(f"bad histogram buckets for {name}: {buckets!r}")
        self.buckets = buckets
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def observe_n(self, v: float, n: int) -> None:
        self.labels().observe_n(v, n)

    def _render_child(self, out, values, child) -> None:
        cum = 0
        for b, c in zip(self.buckets, child.counts):
            cum += c
            le = 'le="%s"' % format_value(b)
            out.append(f"{self.name}_bucket{self._label_str(values, le)} {cum}")
        cum += child.counts[-1]
        inf = self._label_str(values, 'le="+Inf"')
        out.append(f"{self.name}_bucket{inf} {cum}")
        out.append(f"{self.name}_sum{self._label_str(values)} "
                   f"{format_value(child.sum)}")
        out.append(f"{self.name}_count{self._label_str(values)} {cum}")


class Registry:
    """Name -> family map with idempotent registration and text rendering."""

    def __init__(self):
        self._lock = locks.make_lock("obs.metrics")
        self._families: dict[str, _Family] = {}

    def _register(self, cls, name, help, labelnames=(), **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                same = (type(fam) is cls and fam.labelnames == tuple(labelnames)
                        and (cls is not Histogram
                             or fam.buckets == tuple(sorted(float(b) for b in
                                                            kw.get("buckets", LATENCY_BUCKETS_S)))))
                if not same:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(fam).__name__}{fam.labelnames} — cannot re-register "
                        f"as {cls.__name__}{tuple(labelnames)}")
                return fam
            fam = cls(name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, help, labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help, labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name, help, labelnames=(),
                  buckets=LATENCY_BUCKETS_S) -> Histogram:
        return self._register(Histogram, name, help, labelnames, buckets=buckets)

    def names(self) -> list[str]:
        """Sorted names of every registered family (catalog drift checks —
        scripts/checks.sh compares this against the README table)."""
        with self._lock:
            return sorted(self._families)

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4) of every family."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        out: list[str] = []
        for fam in fams:
            fam.render(out)
        return "\n".join(out) + "\n" if out else ""

    def sample(self, name: str, labels: dict | None = None):
        """Introspection for tests/benches: the current value of one series
        (float for counter/gauge, {'count','sum'} for a histogram), or None
        when the series has never been touched."""
        with self._lock:
            fam = self._families.get(name)
        if fam is None:
            return None
        key = tuple(str((labels or {})[k]) for k in fam.labelnames
                    if k in (labels or {}))
        if len(key) != len(fam.labelnames):
            raise ValueError(f"{name} wants labels {fam.labelnames}")
        with fam._lock:
            child = fam._children.get(key)
            if child is None:
                return None
            if isinstance(child, _HistogramChild):
                return {"count": sum(child.counts), "sum": child.sum}
            return child._value

    def reset(self) -> None:
        """Zero every series, keeping registrations (bench warm-up resets)."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            with fam._lock:
                for child in fam._children.values():
                    if isinstance(child, _HistogramChild):
                        child.counts = [0] * len(child.counts)
                        child.sum = 0.0
                    else:
                        child._value = 0.0


#: the process-global registry `GET /metrics` exposes
REGISTRY = Registry()


def counter(name: str, help: str, labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str, labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str, labelnames=(),
              buckets=LATENCY_BUCKETS_S) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def render() -> str:
    return REGISTRY.render()
