"""Compile & device-traffic observability (ISSUE 13): the XLA layer as a
first-class observable.

The stack's perf wins all rest on two invariants nothing observed until
now: a BOUNDED universe of compiled shapes (pow2 prefill chunks, the
{1,chunk} decode scans, pow2 hybrid budgets — one stray shape recompiles
mid-traffic and steals seconds of device time) and NEAR-ZERO steady-state
host↔device traffic (PR 3's device-resident decode state — one stray
per-chunk upload serializes the overlapped pipeline). This module turns
both into gated, scrapeable contracts:

* :class:`CompileLedger` — every jit trace/compile is recorded: the
  process-global :data:`LEDGER` registers a ``jax.monitoring`` listener
  (the ``/jax/core/compile/*`` duration events fire exactly when a call
  really traces/lowers/compiles — a cached call fires nothing, so the
  record is ground truth, not a host-side shape model) and engine dispatch
  sites bracket their jitted calls in :meth:`CompileLedger.scope` so each
  compile is attributed to a function label and shape-bucket key. Feeds
  ``dllama_jit_compiles_total{fn}`` / ``dllama_jit_compile_seconds_total
  {fn}`` and a ``compile`` span per event (Perfetto shows compiles
  stealing device time mid-traffic). Compiles outside any scope land under
  ``fn="untracked"``.
* :class:`ShapeContract` — the declarative registry of the EXPECTED
  compiled-shape universe (BatchEngine.declare_serving_buckets enumerates
  it: decode scan at n∈{1..chunk}, spec verify ditto, pow2 hybrid budgets
  × the decode chunk, pow2 prefill buckets, the B=1 commit sample — each
  × {plain, penalized} sampling variants, with the {dense,paged} route in
  the bucket notes). Each recorded compile classifies expected /
  unexpected (``dllama_jit_unexpected_compiles_total{fn}`` + a structured
  warning naming the offending shape); functions with no declarations at
  all (direct library use, no serving contract) classify ``undeclared``
  and never warn. The contract also drives the ``--warmup auto``
  precompile pass (BatchEngine.warmup) so the first real request stops
  paying compile.
* **transfer accounting** — :func:`note_transfer` counts host↔device
  traffic at the engine-boundary sites (``dllama_transfers_total
  {direction,site}`` / ``dllama_transfer_bytes_total``), and
  :func:`h2d_guard` builds the ``--transfer-guard`` strict mode on
  ``jax.transfer_guard_host_to_device``: wrapped around the steady-state
  decode/spec jit calls (whose operands are all device-resident carries by
  construction), an unexpected implicit upload raises instead of silently
  serializing the pipeline — PR 3's "no per-chunk uploads" claim, enforced
  forever.
* **device-memory gauges** — :func:`refresh_device_gauges` publishes live
  buffer count/bytes (``jax.live_arrays``) alongside the existing
  param/KV gauges at scrape time.

Module import is stdlib-only (scripts/checks.sh imports this without jax
or a model); jax is imported lazily inside the functions that need it.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import trace
from dllama_tpu.utils import locks

log = logging.getLogger("dllama_tpu.obs")

#: ledger `fn` labels the engine dispatch sites bill compiles to — the
#: single definition site for the README "Shape-bucket contract" table
#: (scripts/checks.sh asserts the two stay identical, both directions).
#: Bucket-key grammar per fn is in each description.
COMPILE_FNS = {
    "prefill_chunk": "add_step's admission prefill, one pow2 chunk "
                     "(keys m{c}, c = pow2 <= --max-prefill-chunk; B=1 "
                     "slot prefill on unsharded/paged engines, masked "
                     "full-width on dp meshes)",
    "decode": "the fused n-step decode scan, plain sampling (keys n{n}; "
              "the scheduler dispatches n=chunk, row-limit clamps may "
              "shrink n toward 1 near the context edge)",
    "decode_pen": "the decode scan with repetition-penalty counts in the "
                  "carry (keys n{n})",
    "spec": "the fused speculative propose/verify chunk, plain sampling "
            "(keys n{n} = verify cycles per launch; {1, chunk})",
    "spec_pen": "the spec chunk with penalty counts in the cycle carry "
                "(keys n{n})",
    "hybrid": "the fused prefill-slice + decode-chunk launch (keys "
              "p{P}.n{n}: P = pow2 prefill-budget slice, n = decode "
              "steps)",
    "hybrid_pen": "the hybrid launch with penalty counts (keys p{P}.n{n})",
    "commit": "add_commit's first-token sample off the admission logits "
              "(key b1 — one [1, V] shape per engine)",
    "single_sample": "the single-engine Sampler's jitted sample off "
                     "prefill logits (keys b{B}; never contract-declared, "
                     "so it cannot classify unexpected)",
    "single_step": "the single-engine tier's jitted step "
                   "(InferenceEngine.step: pow2 prefill chunks and "
                   "decode_step; keys m{T} = token width)",
    "single_decode": "the single-engine fused n-step decode scans "
                     "(greedy, sampled and penalized variants; keys n{n})",
    "single_spec": "the single-engine prompt-lookup speculative decode "
                   "(keys n{n} = tokens requested from the chunk)",
    "boundary": "small boundary carry ops (history writes, cross-slot row "
                "copies, COW page clones, surgical .at row writes) — one-"
                "time per-process compiles; attributed so steady-state "
                "decode shows ZERO untracked compiles",
    "untracked": "compiles observed outside any instrumented dispatch "
                 "site (boundary eager ops, library use, other jits); "
                 "never classified unexpected, but counted — steady-state "
                 "decode must not produce ANY",
}

#: transfer-accounting site labels (bounded cardinality; the README
#: transfer table documents each)
TRANSFER_SITES = ("vectors", "prefill", "history", "commit",
                  "decode_tokens", "spec_counts", "nan_guard",
                  "kv_spill", "kv_restore")


def sig_of(*args, max_leaves: int = 12) -> str:
    """Abstract signature of call operands: dtype[shape] per array leaf,
    scalars by value — the ledger's record of WHAT shape compiled. Never
    raises (a ledger entry must not take down a dispatch)."""
    parts: list[str] = []
    try:
        for a in args:
            if len(parts) >= max_leaves:
                parts.append("...")
                break
            shape = getattr(a, "shape", None)
            dtype = getattr(a, "dtype", None)
            if shape is not None and dtype is not None:
                parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
            elif isinstance(a, (int, float, bool)):
                parts.append(repr(a))
            else:
                parts.append(type(a).__name__)
    except Exception:  # pragma: no cover - defensive
        parts.append("?")
    return ",".join(parts)


class ShapeContract:
    """Declarative registry of the expected compiled-shape universe.

    ``declare(fn, key)`` enumerates a bucket (optionally a warm target for
    the boot precompile pass); ``allow(fn, predicate)`` admits extra keys
    as expected without making them warm targets (e.g. the decode scan's
    row-limit clamp can produce any n in [1, chunk], but only {1, chunk}
    are worth precompiling). ``classify`` answers expected / unexpected /
    undeclared — a fn with no declarations at all has no contract to
    violate (direct library use), so it never classifies unexpected."""

    def __init__(self):
        self._lock = locks.make_lock("obs.contract")
        # fn -> {key: {"note": str, "warm": bool}}
        self._buckets: dict[str, dict[str, dict]] = {}
        # fn -> {range_key: predicate} — keyed so re-declaring the same
        # range (every Scheduler construction on a shared engine) replaces
        # instead of appending duplicate closures
        self._allow: dict[str, dict[str, object]] = {}

    def declare(self, fn: str, key: str, note: str = "",
                warm: bool = True) -> None:
        if fn not in COMPILE_FNS:
            raise ValueError(f"unknown compile fn {fn!r} "
                             f"(catalog: {sorted(COMPILE_FNS)})")
        with self._lock:
            self._buckets.setdefault(fn, {})[str(key)] = {
                "note": note, "warm": bool(warm)}

    def allow(self, fn: str, predicate, key: str = "default") -> None:
        """Admit keys matching ``predicate(key) -> bool`` as expected for
        ``fn`` without enumerating them as warm targets. ``key`` names the
        range: re-allowing under the same name REPLACES the predicate
        (declarations are re-run per scheduler on a shared engine and must
        stay idempotent), while distinct names union."""
        with self._lock:
            self._allow.setdefault(fn, {})[str(key)] = predicate

    def declared(self, fn: str) -> bool:
        with self._lock:
            return fn in self._buckets

    def classify(self, fn: str, key: str) -> str:
        """'expected' | 'unexpected' | 'undeclared'."""
        key = str(key)
        with self._lock:
            buckets = self._buckets.get(fn)
            if buckets is None:
                return "undeclared"
            if key in buckets:
                return "expected"
            preds = list(self._allow.get(fn, {}).values())
        for p in preds:
            try:
                if p(key):
                    return "expected"
            except Exception:  # pragma: no cover - a broken predicate
                continue      # must not crash a dispatch
        return "unexpected"

    def warm_targets(self) -> list[tuple[str, str, str]]:
        """(fn, key, note) of every declared warm-target bucket, in
        declaration order — the --warmup auto precompile worklist."""
        with self._lock:
            return [(fn, key, b["note"])
                    for fn, ks in self._buckets.items()
                    for key, b in ks.items() if b["warm"]]

    def coverage(self, seen: dict[str, set]) -> dict:
        """Per-fn bucket coverage against ``seen`` (fn -> compiled keys):
        declared/warm-target counts, which warm targets are still missing,
        and which seen keys fell outside the declaration — the
        `/debug/compile` contract view. ``full`` is True when every warm
        target has compiled (what `--warmup auto` must reach)."""
        with self._lock:
            buckets = {fn: dict(ks) for fn, ks in self._buckets.items()}
            preds = {fn: list(ps.values()) for fn, ps in self._allow.items()}
        out: dict = {"fns": {}, "full": True}
        for fn, ks in sorted(buckets.items()):
            got = {str(k) for k in seen.get(fn, set())}
            warm = [k for k, b in ks.items() if b["warm"]]
            missing = sorted(k for k in warm if k not in got)
            unexpected = sorted(
                k for k in got
                if k not in ks and not any(
                    self._safe(p, k) for p in preds.get(fn, ())))
            out["fns"][fn] = {
                "declared": len(ks),
                "warm_targets": len(warm),
                "compiled": len(got & set(ks)),
                "missing_warm": missing,
                "unexpected_seen": unexpected,
            }
            if missing:
                out["full"] = False
        return out

    @staticmethod
    def _safe(pred, key) -> bool:
        try:
            return bool(pred(key))
        except Exception:  # pragma: no cover
            return False


class _Scope:
    """One instrumented dispatch window (CompileLedger.scope): compile
    events firing on THIS thread inside the window are attributed to the
    scope's (fn, key). Cheap when nothing compiles: one threadlocal push/
    pop and a zero-check."""

    __slots__ = ("ledger", "fn", "key", "sig", "t0",
                 "trace_s", "lower_s", "compile_s", "n_backend")

    def __init__(self, ledger, fn, key, sig):
        self.ledger = ledger
        self.fn = fn
        self.key = str(key)
        self.sig = sig
        self.trace_s = self.lower_s = self.compile_s = 0.0
        self.n_backend = 0

    def __enter__(self):
        self.t0 = time.monotonic()
        self.ledger._push(self)
        return self

    def __exit__(self, *exc):
        self.ledger._pop(self)
        if self.trace_s or self.lower_s or self.compile_s:
            self.ledger._record(self, time.monotonic())
        return False


class CompileLedger:
    """Thread-safe record of every observed jit compile: bounded entry
    ring, per-fn totals, per-fn seen bucket keys, and the installed
    :class:`ShapeContract`. One process-global instance (:data:`LEDGER`),
    same lifecycle as the metrics registry."""

    def __init__(self, max_entries: int = 256):
        # _on_event bumps the untracked compile counter while holding this
        # (obs.ledger ranks below the obs.metrics leaf — rank-legal)
        self._lock = locks.make_lock("obs.ledger")
        self._tls = threading.local()
        self.max_entries = int(max_entries)
        self.entries: deque = deque(maxlen=self.max_entries)
        # fn -> {"compiles", "seconds", "unexpected"}
        self.totals: dict[str, dict] = {}
        # fn -> set of bucket keys that actually compiled
        self.seen: dict[str, set] = {}
        self.contract = ShapeContract()
        self.warmup_report: dict | None = None
        self._warmup_depth = 0  # >0: entries flag warmup=True
        self._seq = 0
        self._listener_installed = False
        self._t0 = time.monotonic()

    # ------------------------------------------------------------ listener

    def ensure_listener(self) -> None:
        """Register the jax.monitoring duration listener once per process
        (idempotent; lazily so this module imports without jax)."""
        if self._listener_installed:
            return
        with self._lock:
            if self._listener_installed:
                return
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                self._on_event)
            self._listener_installed = True

    def _on_event(self, event: str, duration: float, **kw) -> None:
        if not event.startswith("/jax/core/compile"):
            return
        sc = self._top()
        if sc is not None:
            if event.endswith("jaxpr_trace_duration"):
                sc.trace_s += duration
            elif event.endswith("backend_compile_duration"):
                sc.compile_s += duration
                sc.n_backend += 1
            else:
                sc.lower_s += duration
            return
        # no scope on this thread: boundary eager ops, other jits. Totals
        # only — one "compile" per backend event, seconds for everything.
        with self._lock:
            tot = self.totals.setdefault(
                "untracked", {"compiles": 0, "seconds": 0.0, "unexpected": 0})
            tot["seconds"] += duration
            if event.endswith("backend_compile_duration"):
                tot["compiles"] += 1
                ins.JIT_COMPILES.labels(fn="untracked").inc()
        ins.JIT_COMPILE_SECONDS.labels(fn="untracked").inc(duration)

    # -------------------------------------------------------------- scopes

    def scope(self, fn: str, key: str = "", sig=None) -> _Scope:
        """Bracket one jitted dispatch: ``with LEDGER.scope("decode",
        f"n{n}", sig=lambda: sig_of(*args)): ...``. ``sig`` is a lazy
        thunk — evaluated only when a compile actually happened."""
        self.ensure_listener()
        return _Scope(self, fn, key, sig)

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, sc) -> None:
        self._stack().append(sc)

    def _pop(self, sc) -> None:
        st = self._stack()
        if st and st[-1] is sc:
            st.pop()

    def _top(self):
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else None

    def warmup_phase(self):
        """Context manager flagging entries recorded inside it as warmup
        (boot precompile) rather than traffic-stealing compiles."""
        ledger = self

        class _Warm:
            def __enter__(self):
                with ledger._lock:
                    ledger._warmup_depth += 1

            def __exit__(self, *exc):
                with ledger._lock:
                    ledger._warmup_depth -= 1
                return False

        return _Warm()

    # ------------------------------------------------------------- record

    def _record(self, sc: _Scope, t1: float) -> None:
        total = sc.trace_s + sc.lower_s + sc.compile_s
        sig = ""
        if sc.sig is not None:
            try:
                sig = sc.sig() if callable(sc.sig) else str(sc.sig)
            except Exception:  # pragma: no cover - lazy sig must not raise
                sig = "?"
        verdict = self.contract.classify(sc.fn, sc.key)
        with self._lock:
            self._seq += 1
            warm = self._warmup_depth > 0
            entry = {
                "seq": self._seq,
                "at_ms": round((sc.t0 - self._t0) * 1000.0, 3),
                "fn": sc.fn,
                "key": sc.key,
                "sig": sig,
                "classification": verdict,
                "warmup": warm,
                "lowering_s": round(sc.trace_s + sc.lower_s, 6),
                "compile_s": round(sc.compile_s, 6),
                "total_s": round(total, 6),
                "wall_s": round(t1 - sc.t0, 6),
            }
            self.entries.append(entry)
            tot = self.totals.setdefault(
                sc.fn, {"compiles": 0, "seconds": 0.0, "unexpected": 0})
            tot["compiles"] += 1
            tot["seconds"] += total
            if verdict == "unexpected":
                tot["unexpected"] += 1
            self.seen.setdefault(sc.fn, set()).add(sc.key)
        ins.JIT_COMPILES.labels(fn=sc.fn).inc()
        ins.JIT_COMPILE_SECONDS.labels(fn=sc.fn).inc(total)
        if verdict == "unexpected":
            ins.JIT_UNEXPECTED_COMPILES.labels(fn=sc.fn).inc()
            # the structured contract-miss warning: names the offending
            # shape so "why did the fleet hiccup at 14:02" is one grep
            log.warning(
                "unexpected jit compile outside the shape-bucket contract: "
                "fn=%s key=%s sig=%s (%.3fs lowering + %.3fs compile) — "
                "declare the bucket or fix the caller's shape",
                sc.fn, sc.key, sig, sc.trace_s + sc.lower_s, sc.compile_s,
                extra={"compile_fn": sc.fn, "compile_key": sc.key})
        tr = trace.TRACER
        if tr.enabled:
            tr.span_at("compile", sc.t0, t1, cat="compile", track="compile",
                       fn=sc.fn, key=sc.key, warmup=warm,
                       classification=verdict, compile_s=round(total, 4))

    # ------------------------------------------------------------- reading

    def total_compiles(self) -> int:
        """Every observed compile, scoped AND untracked — the number a
        steady-state decode window must not move at all."""
        with self._lock:
            return sum(t["compiles"] for t in self.totals.values())

    def total_unexpected(self) -> int:
        with self._lock:
            return sum(t["unexpected"] for t in self.totals.values())

    def total_seconds(self) -> float:
        with self._lock:
            return sum(t["seconds"] for t in self.totals.values())

    def snapshot(self, entries: int = 64) -> dict:
        """The `/debug/compile` ledger body: per-fn totals, the most
        recent entries, per-fn seen bucket keys, and contract coverage."""
        n = max(0, int(entries))
        with self._lock:
            totals = {fn: dict(t) for fn, t in sorted(self.totals.items())}
            recent = list(self.entries)[-n:] if n else []
            seen = {fn: sorted(ks) for fn, ks in sorted(self.seen.items())}
            seen_sets = {fn: set(ks) for fn, ks in self.seen.items()}
        return {
            "totals": totals,
            "compiles": sum(t["compiles"] for t in totals.values()),
            "unexpected": sum(t["unexpected"] for t in totals.values()),
            "seconds": round(sum(t["seconds"] for t in totals.values()), 6),
            "entries": recent,
            "seen": seen,
            "contract": self.contract.coverage(seen_sets),
        }

    def summary(self) -> dict:
        """Compact join for latency_summary() / /health / /debug/perf."""
        with self._lock:
            totals = self.totals
            out = {
                "compiles": sum(t["compiles"] for t in totals.values()),
                "unexpected": sum(t["unexpected"] for t in totals.values()),
                "seconds": round(
                    sum(t["seconds"] for t in totals.values()), 3),
            }
        out["warmup"] = (None if self.warmup_report is None
                         else {k: self.warmup_report.get(k)
                               for k in ("mode", "buckets", "compiled",
                                         "seconds", "full_coverage")})
        return out

    def install_contract(self, contract: ShapeContract) -> None:
        """Adopt an engine's contract (last engine wins, like the serving
        gauges — one serving engine per process in production). Installing
        starts a fresh COVERAGE epoch: the per-fn seen-bucket record resets
        so `/debug/compile`'s coverage describes shapes observed under THIS
        contract, not whatever a previous engine in the process compiled
        (lifetime totals/entries stay — compiles really happened)."""
        with self._lock:
            self.contract = contract
            self.seen = {}

    def reset(self) -> None:
        """Drop entries/totals/seen and the warmup report, keeping the
        listener and contract (test isolation)."""
        with self._lock:
            self.entries.clear()
            self.totals = {}
            self.seen = {}
            self.warmup_report = None


#: the process-global compile ledger (what /debug/compile serves)
LEDGER = CompileLedger()


# ------------------------------------------------------------- transfers

_transfer_lock = locks.make_lock("obs.transfers")
# (direction, site) -> [count, bytes] — mirror of the counters so the
# /debug payload can enumerate label combos without registry introspection
_transfers: dict[tuple[str, str], list] = {}


def note_transfer(direction: str, site: str, nbytes: int) -> None:
    """Count one host↔device transfer at an engine-boundary site.
    ``direction`` is 'h2d' or 'd2h'; ``site`` one of TRANSFER_SITES."""
    ins.TRANSFERS.labels(direction=direction, site=site).inc()
    ins.TRANSFER_BYTES.labels(direction=direction, site=site).inc(
        max(0, int(nbytes)))
    with _transfer_lock:
        acc = _transfers.setdefault((direction, site), [0, 0])
        acc[0] += 1
        acc[1] += max(0, int(nbytes))


def transfer_snapshot() -> dict:
    """Per-site transfer tallies + h2d/d2h totals (the `/debug/compile`
    transfer view and the steady-state-gate's measurement surface)."""
    with _transfer_lock:
        items = {f"{d}.{s}": {"count": c, "bytes": b}
                 for (d, s), (c, b) in sorted(_transfers.items())}
        h2d = sum(b for (d, _), (_, b) in _transfers.items() if d == "h2d")
        d2h = sum(b for (d, _), (_, b) in _transfers.items() if d == "d2h")
        h2d_n = sum(c for (d, _), (c, _) in _transfers.items() if d == "h2d")
        d2h_n = sum(c for (d, _), (c, _) in _transfers.items() if d == "d2h")
    return {"sites": items,
            "h2d": {"count": h2d_n, "bytes": h2d},
            "d2h": {"count": d2h_n, "bytes": d2h}}


def reset_transfers() -> None:
    """Zero the host-side mirror (tests/benches; the registry counters
    keep their monotone lifetime totals)."""
    with _transfer_lock:
        _transfers.clear()


TRANSFER_GUARD_MODES = ("off", "log", "strict")


def h2d_guard(mode: str):
    """Context manager for the steady-state dispatch window: 'strict'
    turns any implicit host→device transfer inside it into an error
    (``jax.transfer_guard_host_to_device("disallow")``), 'log' logs them,
    'off' is a no-op. The engine wraps ONLY the steady-state decode/spec
    jit calls — whose operands are device-resident carries by construction
    — so boundary-legitimate uploads (vector refresh, prefill chunks)
    never trip it."""
    if mode == "off" or not mode:
        import contextlib

        return contextlib.nullcontext()
    if mode not in TRANSFER_GUARD_MODES:
        raise ValueError(
            f"transfer_guard must be one of {TRANSFER_GUARD_MODES}, "
            f"got {mode!r}")
    import jax

    return jax.transfer_guard_host_to_device(
        "disallow" if mode == "strict" else "log")


# --------------------------------------------------------- device memory

def refresh_device_gauges() -> dict:
    """Publish live device-buffer count/bytes (jax.live_arrays) — called
    at scrape time like the process self-metrics, never on the hot path.
    Answers {'buffers': None, 'bytes': None} where jax is unavailable."""
    try:
        import jax

        arrs = jax.live_arrays()
        n = len(arrs)
        total = 0
        for a in arrs:
            try:
                total += int(a.nbytes)
            except Exception:  # pragma: no cover - deleted mid-iteration
                continue
    except Exception:  # pragma: no cover - no backend
        return {"buffers": None, "bytes": None}
    ins.DEVICE_LIVE_BUFFERS.set(n)
    ins.DEVICE_LIVE_BYTES.set(total)
    return {"buffers": n, "bytes": total}


_UNSET = object()


def debug_payload(warmup_report=_UNSET, entries: int = 64) -> dict:
    """The GET /debug/compile document: ledger dump + bucket coverage +
    warmup report + transfer tallies + live device memory. Callers who
    KNOW their warmup state (the API tier) pass it explicitly — including
    an explicit None for a warmup-off boot, which must not fall back to a
    stale report some earlier engine left on the global ledger; omitting
    the argument keeps the ledger's own report (library use)."""
    out = LEDGER.snapshot(entries=entries)
    out["warmup"] = (LEDGER.warmup_report if warmup_report is _UNSET
                     else warmup_report)
    out["transfers"] = transfer_snapshot()
    out["device_memory"] = refresh_device_gauges()
    return out
