"""Request-flow span tracing: the serving stack's flight recorder.

Aggregate metrics (obs/metrics.py) answer "how slow is the fleet"; this
module answers "why was THIS request slow" and "did dispatch actually
overlap consume on THAT chunk".  Three pieces, all process-global like the
metrics registry:

* a thread-safe **span tracer** with a bounded ring buffer: the scheduler,
  engine, API tier, watchdog, and fault injector record spans (name, track,
  t0..t1, args) and instant events, keyed by the serving-tier ``req_id`` so
  traces, ``/metrics`` series, and structured log lines correlate on the
  same id;
* a **per-request flight recorder**: a bounded map req_id -> timeline
  (queue wait, prefill, TTFT, per-chunk token counts, finish reason) that
  survives ring eviction — ``GET /debug/requests[/{req_id}]`` serves it
  for postmortems;
* a **Chrome trace-event exporter** (:meth:`Tracer.export_chrome`): the
  JSON ``GET /debug/trace`` returns loads directly in Perfetto /
  chrome://tracing, with one named track per subsystem ("scheduler",
  "device", "requests") so the overlapped decode pipeline is *visible* as
  interleaved dispatch/consume/device spans.

Disabled mode (:func:`configure` with capacity 0, CLI ``--trace-buffer 0``)
swaps in a singleton no-op tracer: ``span()`` returns the same null span
every call (no allocation), every record call is a constant-time no-op —
the serving hot path pays one attribute load and an ``enabled`` test.

All timestamps are ``time.monotonic()`` (the scheduler's own mark clock),
exported as microseconds relative to the tracer's construction epoch.
Stdlib-only (threading + collections), like the rest of dllama_tpu.obs:
every layer can import it without cycles or optional-dependency gates.
"""

from __future__ import annotations

import time
import uuid
from collections import OrderedDict, deque

from dllama_tpu.utils import locks

#: the distributed-trace hop header (ISSUE 17): the router mints one trace
#: context per proxied request and stamps every upstream leg with
#: ``trace_id:parent_span:hop`` — the replica tags its flight-recorder
#: record (and, through the record, its exported spans) with the trace id,
#: so a failover's second replica leg joins the SAME trace
HOP_HEADER = "X-Dllama-Trace"


def new_trace_id() -> str:
    """A fresh 16-hex trace id (distinct from the request id: one trace may
    span several request legs across replicas)."""
    return uuid.uuid4().hex[:16]


def format_hop(trace_id: str, parent_span: str, hop: int) -> str:
    """Serialize a trace context for the hop header."""
    return f"{trace_id}:{parent_span}:{int(hop)}"


def parse_hop(value) -> tuple[str, str, int] | None:
    """Parse a hop-header value -> (trace_id, parent_span, hop), or None
    when absent/malformed (tracing is best-effort: a bad header must never
    fail the request carrying it)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.split(":")
    if len(parts) != 3 or not parts[0]:
        return None
    try:
        return parts[0], parts[1], int(parts[2])
    except ValueError:
        return None

#: span names the serving stack emits — the documented contract between the
#: instrumentation, the README trace-catalog table, and scripts/checks.sh's
#: drift check (adding an emit site means adding a catalog row)
SPAN_CATALOG = {
    "queue.wait": "admission queue wait: submit -> popped for admission (track: requests)",
    "prefill": "whole admission prefill: popped -> first token committed (track: requests)",
    "prefill.chunk": "one pumped prefill chunk, device-synced whenever decoders would stall (track: scheduler)",
    "request": "whole request lifetime: submit -> terminal state (track: requests)",
    "decode.dispatch": "host work to dispatch one fused decode chunk (track: scheduler)",
    "decode.consume": "blocking wait for a dispatched chunk's tokens (track: scheduler)",
    "decode.device": "chunk dispatch -> tokens materialized: the device-side window (track: device)",
    "decode.spec": "one batched speculative propose/verify cycle (track: device)",
    "emit.scan": "post-consume token emit + EOS/budget stop scan (track: scheduler)",
    "compile": "one jit trace/lower/compile attributed to a dispatch site (obs/compile ledger); args carry fn/key/classification — visible in Perfetto as compile stealing device time mid-traffic (track: compile)",
    "proxy": "router: one relay leg of a proxied SSE stream — headers to terminal frame or upstream death; args carry replica/verdict (track: router)",
    "connect": "router: connect + request + response headers of one upstream forwarding attempt; args carry replica/hop (track: router)",
    "poll": "router: one /health poll exchange against a replica — doubles as the NTP-lite clock sample; args carry replica/ok (track: poll)",
    "failover.attempt": "router: one mid-stream failover attempt — the jittered exponential backoff + survivor pick before a resume dispatch; args carry attempt (track: router)",
    "resume": "router: connect + resume request to a survivor replica, journal replay included; args carry replica/tokens (track: router)",
    "journal": "router: a proxied stream's failover-journal hold window, acquire to release; args carry valid (False = ring-capped, unresumable) + tokens journaled + retries (track: router)",
}

#: instant-event names (``ph: "i"`` in the export), same drift contract
EVENT_CATALOG = {
    "first_token": "a request's first token reached its client queue (track: requests)",
    "drain.begin": "graceful drain started: admission stopped (track: scheduler)",
    "drain.end": "graceful drain finished; args carry `clean` (track: scheduler)",
    "watchdog.stall": "watchdog flagged the worker silent past the deadline (track: scheduler)",
    "watchdog.recover": "worker heartbeats resumed; stall flag cleared (track: scheduler)",
    "fault.fire": "an armed fault injection activated; args carry point/action (track: scheduler)",
    "profile.start": "an on-demand jax.profiler capture started; args carry dir (track: profiler)",
    "profile.stop": "the on-demand capture stopped and wrote its files (track: profiler)",
    "engine.restart": "warm restart after a worker crash: decode state + page pool rebuilt, weights resident; args carry attempt/error (track: scheduler)",
    "request.recovered": "a request survived a warm restart and re-entered a slot; args carry resumed token count (track: requests)",
    "request.timeout": "a request hit its per-request deadline (timeout_s / X-Request-Timeout); args carry where (queued/prefill/decoding) (track: requests)",
    "request.preempted": "a running request was suspended at a chunk boundary for higher-priority work; its pages stay referenced and it resumes byte-identical later; args carry reason (slot/capacity) + emitted tokens (track: requests)",
    "request.resumed": "a preempted request re-entered a slot and its stream continued (track: requests)",
    "affinity.pick": "router: one routing decision; args carry replica/warm (affinity hit) — the warm-routing record a merged trace shows next to the replica's radix lookups (track: router)",
}


def _clean(v):
    """JSON-safe scalar: numpy ints/floats become Python scalars, anything
    exotic becomes its repr-ish string (export must never raise)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    item = getattr(v, "item", None)  # numpy scalar -> Python scalar
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(v)


class _Span:
    """A live span handle from :meth:`Tracer.span`: record it with
    :meth:`end` (extra args merge into the span's args) or use it as a
    context manager.  The span enters the ring only at end time."""

    __slots__ = ("_tr", "name", "cat", "track", "req_id", "t0", "args")

    def __init__(self, tr, name, cat, track, req_id, args):
        self._tr = tr
        self.name = name
        self.cat = cat
        self.track = track
        self.req_id = req_id
        self.args = args
        self.t0 = time.monotonic()

    def end(self, **extra) -> None:
        if extra:
            self.args.update(extra)
        self._tr._record(self.name, self.cat, self.track, self.req_id,
                         self.t0, time.monotonic(), self.args)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class _NullSpan:
    """The shared no-op span of the disabled tracer (never allocated per
    call — ``tracer.span(...) is tracer.span(...)``)."""

    __slots__ = ()

    def end(self, **extra) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class NullTracer:
    """The ``--trace-buffer 0`` fast path: the full :class:`Tracer` surface
    with every method a constant-time no-op and no per-call allocation.
    Hot-path call sites additionally guard on :attr:`enabled` so even the
    kwargs dicts for span args are never built."""

    enabled = False
    capacity = 0

    @staticmethod
    def now() -> float:
        return time.monotonic()

    def span(self, name, **kw):
        return NULL_SPAN

    def span_at(self, *a, **kw):
        pass

    def event(self, *a, **kw):
        pass

    def req_submit(self, *a, **kw):
        pass

    def req_admitted(self, *a, **kw):
        pass

    def req_prefill_done(self, *a, **kw):
        pass

    def req_first_token(self, *a, **kw):
        pass

    def req_chunk(self, *a, **kw):
        pass

    def req_mark(self, *a, **kw):
        pass

    def req_end(self, *a, **kw):
        pass

    def trace_of(self, req_id):
        return None

    def export_chrome(self) -> dict:
        return {"traceEvents": []}

    def requests_summary(self) -> list:
        return []

    def request_timeline(self, req_id):
        return None

    def stats(self) -> dict:
        return {"enabled": False, "capacity": 0, "events": 0, "dropped": 0,
                "requests": 0}

    def reset(self) -> None:
        pass


NULL_TRACER = NullTracer()

#: flight-recorder record template — the /debug/requests/{req_id} schema
#: (underscore keys are internal monotonic marks, stripped from responses)
_REC_TEMPLATE = {
    "req_id": "", "state": "queued", "prompt_tokens": 0,
    "submitted_at_ms": None, "queue_wait_ms": None, "slot": None,
    "reused_tokens": 0, "prefill": None, "ttft_ms": None, "e2e_ms": None,
    "decode_tokens": 0, "finish_reason": None, "chunks": None,
    "chunks_dropped": 0, "_t_submit": None, "_t_admitted": None,
}

#: summary keys for the /debug/requests list view (chunks collapses to a count)
_SUMMARY_KEYS = ("req_id", "state", "prompt_tokens", "submitted_at_ms",
                 "queue_wait_ms", "ttft_ms", "e2e_ms", "decode_tokens",
                 "finish_reason")


class Tracer:
    """Thread-safe span tracer + flight recorder over one bounded ring."""

    enabled = True

    def __init__(self, capacity: int = 2048, max_requests: int = 128,
                 max_chunks_per_request: int = 512):
        if capacity <= 0:
            raise ValueError("capacity must be > 0 (use NULL_TRACER / "
                             "configure(0) for the disabled fast path)")
        self.capacity = int(capacity)
        self.max_requests = int(max_requests)
        self.max_chunks = int(max_chunks_per_request)
        # LEAF rank (utils/locks): record paths do pure ring/dict work and
        # must never acquire anything under it
        self._lock = locks.make_lock("obs.tracer")
        self._events: deque = deque(maxlen=self.capacity)
        self._dropped = 0
        self._tracks: dict[str, int] = {}
        self._requests: OrderedDict[str, dict] = OrderedDict()
        self._epoch = time.monotonic()

    @staticmethod
    def now() -> float:
        return time.monotonic()

    @property
    def epoch(self) -> float:
        """The monotonic instant exported timestamps are relative to —
        published in the /health clock payload so a router can place this
        process's trace on the mesh timeline (ISSUE 17)."""
        return self._epoch

    def _rel_ms(self, t: float | None):
        return None if t is None else round((t - self._epoch) * 1000.0, 3)

    # ---------------------------------------------------------------- spans

    def span(self, name: str, *, cat: str = "", track: str = "scheduler",
             req_id: str = "", **args) -> _Span:
        """Open a span ending at ``end()`` / context-manager exit."""
        return _Span(self, name, cat, track, req_id, args)

    def span_at(self, name: str, t0: float, t1: float, *, cat: str = "",
                track: str = "scheduler", req_id: str = "", **args) -> None:
        """Record an already-finished span from explicit monotonic marks."""
        self._record(name, cat, track, req_id, t0, t1, args)

    def event(self, name: str, *, cat: str = "", track: str = "scheduler",
              req_id: str = "", **args) -> None:
        """Record an instant event (``ph: "i"``) at now."""
        self._record(name, cat, track, req_id, time.monotonic(), None, args)

    def _record(self, name, cat, track, req_id, t0, t1, args) -> None:
        a = {k: _clean(v) for k, v in args.items()} if args else {}
        with self._lock:
            tid = self._tracks.get(track)
            if tid is None:
                tid = self._tracks[track] = len(self._tracks) + 1
            if len(self._events) == self.capacity:
                self._dropped += 1  # deque maxlen evicts the oldest
            self._events.append((name, cat, tid, req_id, t0, t1, a))

    # ------------------------------------------------------ flight recorder

    def _rec(self, req_id: str) -> dict:
        """Get-or-create a request record (caller holds the lock)."""
        rec = self._requests.get(req_id)
        if rec is None:
            rec = dict(_REC_TEMPLATE)
            rec["req_id"] = req_id
            rec["chunks"] = []
            self._requests[req_id] = rec
            while len(self._requests) > self.max_requests:
                self._requests.popitem(last=False)
        return rec

    def req_submit(self, req_id: str, prompt_tokens: int = 0,
                   t: float | None = None) -> None:
        """A request entered the system (queue or single-engine lock wait)."""
        if not req_id:
            return
        t = time.monotonic() if t is None else t
        with self._lock:
            rec = self._rec(req_id)
            rec["_t_submit"] = t
            rec["submitted_at_ms"] = self._rel_ms(t)
            if prompt_tokens:
                rec["prompt_tokens"] = int(prompt_tokens)

    def req_admitted(self, req_id: str, slot: int | None = None,
                     reused_tokens: int = 0, t: float | None = None) -> None:
        """Popped for admission; emits the ``queue.wait`` span."""
        if not req_id:
            return
        t = time.monotonic() if t is None else t
        with self._lock:
            rec = self._rec(req_id)
            rec["_t_admitted"] = t
            rec["state"] = "prefill"
            if slot is not None:
                rec["slot"] = int(slot)
            if reused_tokens:
                rec["reused_tokens"] = int(reused_tokens)
            t0 = rec["_t_submit"]
            if t0 is not None:
                rec["queue_wait_ms"] = round((t - t0) * 1000.0, 3)
        if t0 is not None:
            self.span_at("queue.wait", t0, t, cat="queue", track="requests",
                         req_id=req_id)

    def req_prefill_done(self, req_id: str, tokens: int = 0, reused: int = 0,
                         t: float | None = None) -> None:
        """Admission committed; emits the whole-``prefill`` span."""
        if not req_id:
            return
        t = time.monotonic() if t is None else t
        with self._lock:
            rec = self._rec(req_id)
            rec["state"] = "decoding"
            t0 = rec["_t_admitted"]
            rec["prefill"] = {
                "tokens": int(tokens), "reused_tokens": int(reused),
                "ms": round((t - t0) * 1000.0, 3) if t0 is not None else None,
            }
        if t0 is not None:
            self.span_at("prefill", t0, t, cat="prefill", track="requests",
                         req_id=req_id, tokens=int(tokens), reused=int(reused))

    def req_first_token(self, req_id: str, t: float | None = None) -> None:
        if not req_id:
            return
        t = time.monotonic() if t is None else t
        with self._lock:
            rec = self._rec(req_id)
            rec["state"] = "decoding"
            t0 = rec["_t_submit"]
            if t0 is not None and rec["ttft_ms"] is None:
                rec["ttft_ms"] = round((t - t0) * 1000.0, 3)
        self._record("first_token", "request", "requests", req_id, t, None, {})

    def req_chunk(self, req_id: str, chunk: int, tokens: int,
                  t: float | None = None) -> None:
        """One consumed decode chunk contributed `tokens` rows to req_id."""
        if not req_id:
            return
        t = time.monotonic() if t is None else t
        with self._lock:
            rec = self._rec(req_id)
            ch = rec["chunks"]
            ch.append({"chunk": int(chunk), "tokens": int(tokens),
                       "at_ms": self._rel_ms(t)})
            if len(ch) > self.max_chunks:
                del ch[0]  # keep the tail: postmortems care how it ENDED
                rec["chunks_dropped"] += 1

    def req_mark(self, req_id: str, **fields) -> None:
        """Merge arbitrary (non-internal) fields into a request's record."""
        if not req_id:
            return
        with self._lock:
            rec = self._rec(req_id)
            for k, v in fields.items():
                if k.startswith("_") or k in ("req_id", "chunks"):
                    continue
                if isinstance(v, dict):
                    rec[k] = {kk: _clean(vv) for kk, vv in v.items()}
                else:
                    rec[k] = _clean(v)

    def req_end(self, req_id: str, finish_reason: str,
                t: float | None = None, **timings) -> None:
        """Terminal state; emits the whole-``request`` span.  `timings`
        (queue_wait_ms / ttft_ms / e2e_ms / decode_tokens, from the caller's
        own marks) override the tracer-derived values when not None."""
        if not req_id:
            return
        t = time.monotonic() if t is None else t
        with self._lock:
            rec = self._rec(req_id)
            rec["state"] = "finished"
            rec["finish_reason"] = str(finish_reason)
            t0 = rec["_t_submit"]
            if t0 is not None and rec["e2e_ms"] is None:
                rec["e2e_ms"] = round((t - t0) * 1000.0, 3)
            for k, v in timings.items():
                if v is not None and not k.startswith("_") and k != "chunks":
                    rec[k] = _clean(v)
        if t0 is not None:
            self.span_at("request", t0, t, cat="request", track="requests",
                         req_id=req_id, finish=str(finish_reason))

    def trace_of(self, req_id: str) -> str | None:
        """The distributed trace id a request was marked with (req_mark
        ``trace_id=...`` from the hop header), or None — the hook log
        lines use to carry trace_id next to request_id."""
        if not req_id:
            return None
        with self._lock:
            rec = self._requests.get(req_id)
            tid = None if rec is None else rec.get("trace_id")
        return tid if isinstance(tid, str) and tid else None

    # --------------------------------------------------------------- export

    def export_chrome(self) -> dict:
        """Chrome trace-event JSON (dict, ready for json.dumps): complete
        spans as ``ph:"X"``, instants as ``ph:"i"``, with thread_name
        metadata naming each track.  Events are sorted by start time (ties:
        longer span first, so nesting renders parent-before-child), which
        also guarantees non-decreasing ``ts`` per track."""
        with self._lock:
            events = list(self._events)
            tracks = dict(self._tracks)
            # distributed-trace tagging (ISSUE 17): events keyed by a req_id
            # whose flight-recorder record carries a trace_id export with it,
            # so a cross-replica merge can group legs under one trace
            traces = {rid: rec["trace_id"] for rid, rec in
                      self._requests.items()
                      if isinstance(rec.get("trace_id"), str)
                      and rec.get("trace_id")}
        meta = [{"ph": "M", "name": "process_name", "pid": 1,
                 "args": {"name": "dllama-tpu"}}]
        for name, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            meta.append({"ph": "M", "name": "thread_name", "pid": 1,
                         "tid": tid, "args": {"name": name}})
        body = []
        for name, cat, tid, req_id, t0, t1, args in events:
            ev = {"name": name, "cat": cat or "dllama", "pid": 1, "tid": tid,
                  "ts": round((t0 - self._epoch) * 1e6, 1),
                  "args": dict(args)}
            if req_id:
                ev["args"]["req_id"] = req_id
                tr_id = traces.get(req_id)
                if tr_id and "trace_id" not in ev["args"]:
                    ev["args"]["trace_id"] = tr_id
            if t1 is None:
                ev["ph"] = "i"
                ev["s"] = "t"  # thread-scoped instant
            else:
                ev["ph"] = "X"
                ev["dur"] = max(round((t1 - t0) * 1e6, 1), 0.0)
            body.append(ev)
        body.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        return {"traceEvents": meta + body, "displayTimeUnit": "ms"}

    def requests_summary(self) -> list[dict]:
        """Compact flight-recorder listing (oldest first) for
        ``GET /debug/requests``."""
        with self._lock:
            recs = [(dict(r), len(r["chunks"])) for r in self._requests.values()]
        return [dict({k: r[k] for k in _SUMMARY_KEYS}, chunks=n)
                for r, n in recs]

    def request_timeline(self, req_id: str) -> dict | None:
        """Full record for ``GET /debug/requests/{req_id}`` (None when the
        id was never seen or has been evicted)."""
        with self._lock:
            rec = self._requests.get(req_id)
            if rec is None:
                return None
            rec = dict(rec)
            rec["chunks"] = list(rec["chunks"])
        return {k: v for k, v in rec.items() if not k.startswith("_")}

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": True, "capacity": self.capacity,
                    "events": len(self._events), "dropped": self._dropped,
                    "requests": len(self._requests)}

    def reset(self) -> None:
        """Drop all recorded events and request records (tests/benches)."""
        with self._lock:
            self._events.clear()
            self._requests.clear()
            self._dropped = 0


def merge_chrome(parts: list[tuple[str, dict, float]]) -> dict:
    """Merge several Chrome trace exports onto ONE timeline (ISSUE 17).

    ``parts`` is ``[(label, export, shift_us), ...]`` — each export a
    :meth:`Tracer.export_chrome` dict, each ``shift_us`` the microseconds to
    ADD to that part's timestamps to land them on the merged clock (the
    router computes it from its NTP-lite per-replica offset estimate; the
    router's own part shifts by 0). Each part becomes one Perfetto process
    (pid = its 1-based position, process_name = its label) keeping its own
    thread tracks, so the merged file shows the router track above one
    process-track per replica. Events are re-sorted globally by (ts, -dur)
    — the same non-decreasing-per-track guarantee export_chrome gives."""
    meta: list[dict] = []
    body: list[dict] = []
    for pid, (label, export, shift_us) in enumerate(parts, start=1):
        for ev in (export or {}).get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    ev["args"] = {"name": label}
                meta.append(ev)
                continue
            try:
                ev["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 1)
            except (TypeError, ValueError):
                ev["ts"] = shift_us
            body.append(ev)
    body.sort(key=lambda e: (e.get("ts", 0.0), -e.get("dur", 0.0)))
    return {"traceEvents": meta + body, "displayTimeUnit": "ms"}


#: the process-global tracer (CLI: --trace-buffer; 0 installs NULL_TRACER).
#: Call sites read this attribute per use, so configure() can swap it live.
TRACER: Tracer | NullTracer = Tracer()


def configure(capacity: int, max_requests: int = 128,
              max_chunks_per_request: int = 512):
    """Swap the process-global tracer.  capacity <= 0 installs the no-op
    singleton (the ``--trace-buffer 0`` fast path).  Returns the tracer."""
    global TRACER
    if int(capacity) <= 0:
        TRACER = NULL_TRACER
    else:
        TRACER = Tracer(int(capacity), max_requests, max_chunks_per_request)
    return TRACER


def log_extra(req_id: str, **fields) -> dict:
    """Structured-log ``extra`` dict (ISSUE 17 logging parity): request_id,
    plus the mesh trace id when this request's flight record carries one (a
    router hop header put it there), plus any truthy caller fields — so
    ``--log-format json`` lines from router and replicas join on the same
    trace_id key."""
    x = {"request_id": req_id}
    tid = TRACER.trace_of(req_id)
    if tid:
        x["trace_id"] = tid
    for k, v in fields.items():
        if v:
            x[k] = v
    return x
