"""Q40 / Q80 block quantization as JAX-native array-of-struct-of-arrays.

Reproduces the numeric formats of the reference (nn-quants.hpp:51-67,
converter/writer.py:29-74) in an idiomatic-TPU layout:

* **Q40**: 32-element blocks, one fp16 scale per block, 4-bit codes with offset
  -8. File layout is row-major ``[out, in/32]`` blocks of ``{f16 scale, 16
  bytes}`` where byte ``j`` packs code ``j`` (low nibble) and code ``j+16``
  (high nibble). On device we store the transpose — ``packed: u8[in/2, out]``,
  ``scales: f16[in/32, out]`` — so that a matmul ``x @ W`` streams weight
  columns contiguously along the MXU lane dimension.
* **Q80**: 32-element blocks, fp16 scale, int8 codes (round-to-nearest). Used
  for the quantized activation exchange (the reference's ZQ pipe /
  ``--buffer-float-type q80``).

Quantize math (must match converter/writer.py:29-74 bit-for-bit so files
interoperate):
  Q40: delta = (signed value with max |.|) / -8 ; q = clip(floor(x/delta + 8.5), 0, 15)
  Q80: delta = absmax / 127              ; q = round(x/delta)
"""

from __future__ import annotations

import dataclasses
from enum import IntEnum

import jax
import jax.numpy as jnp
import numpy as np

Q_BLOCK = 32  # block size shared by Q40 and Q80 (nn-quants.hpp:59-67)


class FloatType(IntEnum):
    """Wire/file float-type ids (nn-quants.hpp:51-57, writer.py:6-10)."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3
    # dllama-tpu extension: bf16 on-device weights (not in reference format).
    BF16 = 100

    @property
    def bytes_per_block(self) -> int:
        return {
            FloatType.F32: 4 * Q_BLOCK,
            FloatType.F16: 2 * Q_BLOCK,
            FloatType.BF16: 2 * Q_BLOCK,
            FloatType.Q40: 2 + Q_BLOCK // 2,
            FloatType.Q80: 2 + Q_BLOCK,
        }[self]

    def nbytes(self, n_elements: int) -> int:
        assert n_elements % Q_BLOCK == 0 or self in (FloatType.F32, FloatType.F16)
        if self in (FloatType.F32, FloatType.BF16):
            return {FloatType.F32: 4, FloatType.BF16: 2}[self] * n_elements
        if self == FloatType.F16:
            return 2 * n_elements
        return (n_elements // Q_BLOCK) * self.bytes_per_block


def parse_float_type(name: str) -> FloatType:
    try:
        return FloatType[name.upper()]
    except KeyError:
        raise ValueError(f"unsupported float type: {name!r}") from None


# ---------------------------------------------------------------------------
# numpy side: file <-> array codecs (used by converters and the .m loader)
# ---------------------------------------------------------------------------


def quantize_q40_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f32[..., K] -> (packed u8[..., K/32, 16], scales f16[..., K/32]).

    Matches converter/writer.py:29-53 exactly (including the floor-after-+8.5
    rounding and the where(-min > max) tie-break).
    """
    shape = x.shape
    assert shape[-1] % Q_BLOCK == 0, shape
    g = x.astype(np.float32).reshape(*shape[:-1], -1, Q_BLOCK)
    gmax = g.max(axis=-1)
    gmin = g.min(axis=-1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    deltas16 = deltas.astype(np.float16)
    with np.errstate(divide="ignore"):
        inv = np.where(deltas != 0, 1.0 / deltas, 0.0)
    q = np.clip(g * inv[..., None] + 8.5, 0, 15).astype(np.uint8)
    packed = q[..., : Q_BLOCK // 2] | (q[..., Q_BLOCK // 2 :] << 4)
    return packed, deltas16


def dequantize_q40_np(packed: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """(packed u8[..., B, 16], scales f16[..., B]) -> f32[..., B*32]."""
    lo = (packed & 0x0F).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    codes = np.concatenate([lo, hi], axis=-1).astype(np.float32)
    out = codes * scales[..., None].astype(np.float32)
    return out.reshape(*packed.shape[:-2], packed.shape[-2] * Q_BLOCK)


def quantize_q80_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f32[..., K] -> (codes i8[..., K/32, 32], scales f16[..., K/32])."""
    shape = x.shape
    assert shape[-1] % Q_BLOCK == 0, shape
    g = x.astype(np.float32).reshape(*shape[:-1], -1, Q_BLOCK)
    absmax = np.abs(g).max(axis=-1)
    deltas = absmax / 127.0
    deltas16 = deltas.astype(np.float16)
    with np.errstate(divide="ignore"):
        inv = np.where(deltas != 0, 1.0 / deltas, 0.0)
    codes = np.round(g * inv[..., None]).astype(np.int8)
    return codes, deltas16


def dequantize_q80_np(codes: np.ndarray, scales: np.ndarray) -> np.ndarray:
    out = codes.astype(np.float32) * scales[..., None].astype(np.float32)
    return out.reshape(*codes.shape[:-2], codes.shape[-2] * Q_BLOCK)


def q40_to_bytes(packed: np.ndarray, scales: np.ndarray) -> bytes:
    """Serialize to the reference's on-disk block stream {f16 scale, 16 bytes}."""
    nb = packed.reshape(-1, Q_BLOCK // 2).shape[0]
    rec = np.zeros((nb, 2 + Q_BLOCK // 2), dtype=np.uint8)
    rec[:, :2] = scales.reshape(-1, 1).view(np.uint8).reshape(nb, 2)
    rec[:, 2:] = packed.reshape(nb, Q_BLOCK // 2)
    return rec.tobytes()


def q40_from_bytes(buf: bytes, n_elements: int) -> tuple[np.ndarray, np.ndarray]:
    nb = n_elements // Q_BLOCK
    rec = np.frombuffer(buf, dtype=np.uint8, count=nb * (2 + Q_BLOCK // 2)).reshape(
        nb, 2 + Q_BLOCK // 2
    )
    scales = rec[:, :2].copy().view(np.float16).reshape(nb)
    packed = rec[:, 2:].copy()
    return packed, scales


def q80_to_bytes(codes: np.ndarray, scales: np.ndarray) -> bytes:
    nb = codes.reshape(-1, Q_BLOCK).shape[0]
    rec = np.zeros((nb, 2 + Q_BLOCK), dtype=np.uint8)
    rec[:, :2] = scales.reshape(-1, 1).view(np.uint8).reshape(nb, 2)
    rec[:, 2:] = codes.reshape(nb, Q_BLOCK).view(np.uint8)
    return rec.tobytes()


def q80_from_bytes(buf: bytes, n_elements: int) -> tuple[np.ndarray, np.ndarray]:
    nb = n_elements // Q_BLOCK
    rec = np.frombuffer(buf, dtype=np.uint8, count=nb * (2 + Q_BLOCK)).reshape(
        nb, 2 + Q_BLOCK
    )
    scales = rec[:, :2].copy().view(np.float16).reshape(nb)
    codes = rec[:, 2:].copy().view(np.int8)
    return codes, scales


# ---------------------------------------------------------------------------
# device side: QTensor pytree (Q40 weight resident in HBM)
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """A Q40 2-D weight for ``x @ W`` with ``W: [k_in, n_out]`` logical shape.

    ``packed: u8[k/2, n]`` — row ``16*b + j`` packs codes for input dims
    ``32*b + j`` (low nibble) and ``32*b + j + 16`` (high nibble).
    ``scales: f16[k/32, n]``.

    The lane (last) dimension is the *output* dim, so Pallas kernels stream
    128-wide output tiles straight onto MXU lanes; the reference instead keeps
    per-output-row blocks (nn-quants.hpp:59-62) because its GEMV walks rows.
    """

    packed: jax.Array  # u8 [k//2, n]
    scales: jax.Array  # f16 [k//32, n] — the file's own scale dtype, kept
    # 2-byte in HBM so the decode kernels stream half the scale bytes (~10%
    # of Q40 weight traffic). XLA paths widen with .astype (exact — every f16
    # is representable in f32); the Pallas kernels take the scales bitcast to
    # u16 and widen in-register (exact exponent-scaling trick, q40_matmul.py).
    # f32 scales are still accepted everywhere for hand-built QTensors.

    def tree_flatten(self):
        return (self.packed, self.scales), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self) -> tuple[int, ...]:
        """Logical [..., k, n] (leading axes = layer/expert stacking)."""
        *lead, kh, n = self.packed.shape
        return (*lead, kh * 2, n)

    @property
    def k(self) -> int:
        return self.packed.shape[-2] * 2

    @property
    def n(self) -> int:
        return self.packed.shape[-1]

    @classmethod
    def quantize(cls, w) -> "QTensor":
        """f32[k, n] -> QTensor (numpy path; used by tests and converters)."""
        w = np.asarray(w, dtype=np.float32)
        packed, scales = quantize_q40_np(np.ascontiguousarray(w.T))  # [n, k/32, 16]
        k = w.shape[0]
        packed = np.transpose(packed, (1, 2, 0)).reshape(k // 2, w.shape[1])
        scales = np.ascontiguousarray(np.transpose(scales, (1, 0)))  # f16
        return cls(jnp.asarray(packed), jnp.asarray(scales))

    @classmethod
    def from_file_layout(cls, packed: np.ndarray, scales: np.ndarray, n_out: int, k_in: int,
                         device: bool = True) -> "QTensor":
        """Build from the `.m` on-disk layout: blocks row-major over [n_out, k_in].

        `device=False` keeps the leaves as host numpy arrays so the caller can
        place each shard directly (shard-direct weight loading)."""
        packed = packed.reshape(n_out, k_in // Q_BLOCK, Q_BLOCK // 2)
        scales = scales.reshape(n_out, k_in // Q_BLOCK)
        packed = np.ascontiguousarray(np.transpose(packed, (1, 2, 0))).reshape(k_in // 2, n_out)
        scales = np.ascontiguousarray(np.transpose(scales, (1, 0)), dtype=np.float16)
        if not device:
            return cls(packed, scales)
        return cls(jnp.asarray(packed), jnp.asarray(scales))

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        """Pure-jnp reference dequant -> [..., k, n] (the XLA fallback path)."""
        *lead, k, n = self.shape
        p = self.packed.reshape(*lead, k // Q_BLOCK, Q_BLOCK // 2, n)
        lo = (p & 0x0F).astype(jnp.int8) - 8
        hi = (p >> 4).astype(jnp.int8) - 8
        codes = jnp.concatenate([lo, hi], axis=-2).astype(jnp.float32)
        w = codes * self.scales.reshape(*lead, k // Q_BLOCK, 1, n).astype(jnp.float32)
        return w.reshape(*lead, k, n).astype(dtype)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Q8Tensor:
    """A Q80 2-D weight for ``x @ W`` with ``W: [k_in, n_out]`` logical shape.

    ``codes: i8[k, n]``, ``scales: f16[k/32, n]`` — same lane-major layout
    rationale as :class:`QTensor` (the output dim rides the 128-wide lanes).
    1.0625 bytes/weight in HBM vs bf16's 2 — the reference runs Q80-weight
    models natively (nn-quants.hpp Q80 rows); this keeps them packed on
    device instead of the dense-bf16 fallback."""

    codes: jax.Array  # i8 [(L,) k, n]
    scales: jax.Array  # f16 [(L,) k//32, n] (f32 accepted for hand-built)

    @classmethod
    def quantize(cls, w) -> "Q8Tensor":
        """f32[k, n] -> Q8Tensor (numpy path; tests/benches/converters — the
        one construction site, like QTensor.quantize)."""
        w = np.asarray(w, dtype=np.float32)
        n_out = w.shape[1]
        codes, scales = quantize_q80_np(np.ascontiguousarray(w.T).reshape(-1))
        return cls.from_file_layout(codes, scales, n_out, w.shape[0])

    def tree_flatten(self):
        return (self.codes, self.scales), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self) -> tuple[int, ...]:
        """Logical [..., k, n] (leading axes = layer/expert stacking)."""
        return tuple(self.codes.shape)

    @classmethod
    def from_file_layout(cls, codes: np.ndarray, scales: np.ndarray, n_out: int,
                         k_in: int, device: bool = True) -> "Q8Tensor":
        """Build from the `.m` on-disk layout: blocks row-major over
        [n_out, k_in] (mirrors QTensor.from_file_layout)."""
        codes = codes.reshape(n_out, k_in)
        scales = scales.reshape(n_out, k_in // Q_BLOCK)
        codes = np.ascontiguousarray(codes.T)
        scales = np.ascontiguousarray(scales.T, dtype=np.float16)
        if not device:
            return cls(codes, scales)
        return cls(jnp.asarray(codes), jnp.asarray(scales))

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        """Pure-jnp reference dequant -> [..., k, n] (the XLA fallback path)."""
        *lead, k, n = self.shape
        c = self.codes.astype(jnp.float32).reshape(*lead, k // Q_BLOCK, Q_BLOCK, n)
        w = c * self.scales.reshape(*lead, k // Q_BLOCK, 1, n).astype(jnp.float32)
        return w.reshape(*lead, k, n).astype(dtype)


def slice_leaf(w, li):
    """One layer's slice of a stacked weight leaf (QTensor/Q8Tensor or dense).

    The single place that knows how to index a stacked QTensor — callers that
    must materialize a per-layer slice (XLA matmul path, q80 col_fn, MoE
    expert stacks) go through here so a future QTensor layout change has one
    site to update."""
    if isinstance(w, QTensor):
        return QTensor(w.packed[li], w.scales[li])
    if isinstance(w, Q8Tensor):
        return Q8Tensor(w.codes[li], w.scales[li])
    return w[li]


def quantize_q80_jnp(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """On-device Q80 quantize of activations along the last dim.

    f32/bf16[..., d] -> (codes i8[..., d], scales f32[..., d/32]).
    Used by the quantized-collective path (parallel/collectives.py) — the
    TPU-native analog of the reference's Q80 ZQ exchange buffer.
    """
    shape = x.shape
    g = x.astype(jnp.float32).reshape(*shape[:-1], -1, Q_BLOCK)
    absmax = jnp.max(jnp.abs(g), axis=-1)
    deltas = absmax / 127.0
    inv = jnp.where(deltas != 0, 1.0 / deltas, 0.0)
    codes = jnp.round(g * inv[..., None]).astype(jnp.int8)
    return codes.reshape(shape), deltas


def dequantize_q80_jnp(codes: jax.Array, scales: jax.Array, dtype=jnp.float32) -> jax.Array:
    shape = codes.shape
    g = codes.astype(jnp.float32).reshape(*shape[:-1], -1, Q_BLOCK)
    out = g * scales[..., None]
    return out.reshape(shape).astype(dtype)
