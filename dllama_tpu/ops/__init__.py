from dllama_tpu.ops.quant import (  # noqa: F401
    FloatType,
    Q_BLOCK,
    QTensor,
    dequantize_q40_np,
    dequantize_q80_jnp,
    dequantize_q80_np,
    parse_float_type,
    quantize_q40_np,
    quantize_q80_jnp,
    quantize_q80_np,
)
