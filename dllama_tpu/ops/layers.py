"""Core model ops: RMSNorm, RoPE, GQA attention, activations.

jnp reference implementations — under jit XLA fuses these into the surrounding
matmuls; Pallas variants exist only where fusion isn't enough (see ops/pallas/).
Numerics follow the reference kernels (nn-cpu-ops.cpp): norms, softmax and
attention accumulate in f32 regardless of activation dtype.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.models.config import HiddenAct, LlamaConfig, RopeType


# 'jnp' lets XLA fuse the norm into neighbors (the right default); 'pallas'
# routes through ops/pallas/rms_norm — the single-pass fused kernel for the
# case where the norm feeds a Pallas matmul (an opaque call XLA won't fuse
# across). Measured via the ebench 'pallas-norm' row (VERDICT r3 weak #8);
# flip only with a recorded win.
RMS_NORM_IMPL = "jnp"


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    """y = x * w / rms(x) with f32 accumulation (nn-cpu-ops.cpp:108-183)."""
    if RMS_NORM_IMPL == "pallas":
        from dllama_tpu.ops.pallas.rms_norm import rms_norm as pallas_rms_norm

        return pallas_rms_norm(x, weight, eps,
                               interpret=jax.devices()[0].platform != "tpu")
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv * weight.astype(jnp.float32)).astype(x.dtype)


def activation(x: jax.Array, act: HiddenAct) -> jax.Array:
    if act == HiddenAct.SILU:
        return jax.nn.silu(x)
    return jax.nn.gelu(x, approximate=False)


def llama31_scale_freqs(freqs: np.ndarray, cfg: LlamaConfig) -> np.ndarray:
    """Llama-3.1 NTK-by-parts frequency scaling.

    Note: the reference applies this scaling to the *rotated output values*
    (nn-cpu-ops.cpp:1139-1153), which deviates from Meta's reference model
    (and from every HF checkpoint's training-time rope). We implement the
    correct frequency-domain scaling; SURVEY.md §7.4.3 flags this as a
    reference idiosyncrasy we chose to fix, not reproduce.
    """
    wavelen = 2.0 * math.pi / freqs
    high_freq_wavelen = cfg.rope_scaling_orig_max_seq_len / cfg.rope_scaling_high_freq_factor
    low_freq_wavelen = cfg.rope_scaling_orig_max_seq_len / cfg.rope_scaling_low_freq_factor
    scaled = freqs / cfg.rope_scaling_factor
    smooth = (cfg.rope_scaling_orig_max_seq_len / wavelen - cfg.rope_scaling_low_freq_factor) / (
        cfg.rope_scaling_high_freq_factor - cfg.rope_scaling_low_freq_factor
    )
    smoothed = (1 - smooth) * scaled + smooth * freqs
    out = np.where(wavelen < high_freq_wavelen, freqs, np.where(wavelen > low_freq_wavelen, scaled, smoothed))
    return out.astype(np.float32)


def build_rope_cache(cfg: LlamaConfig, seq_len: int | None = None) -> jax.Array:
    """Precomputed [seq_len, head_size/2, 2] (cos, sin) table, f32.

    The analog of the reference's per-node rope_cache buffer
    (nn-cpu-ops.cpp:1082-1102), computed for the *interleaved-pair* layout the
    `.m` format stores Q/K in (converter permutation, convert-hf.py:11-14).
    """
    seq_len = seq_len or cfg.seq_len
    half = cfg.head_size // 2
    freqs = 1.0 / (cfg.rope_theta ** (np.arange(half, dtype=np.float64) * 2.0 / cfg.head_size))
    freqs = freqs.astype(np.float32)
    if cfg.rope_type == RopeType.LLAMA3_1 and cfg.rope_scaling_factor != 1.0:
        freqs = llama31_scale_freqs(freqs, cfg)
    t = np.arange(seq_len, dtype=np.float32)
    angles = np.outer(t, freqs)  # [S, half]
    cache = np.stack([np.cos(angles), np.sin(angles)], axis=-1)
    return jnp.asarray(cache, dtype=jnp.float32)


def apply_rope(x: jax.Array, rope: jax.Array) -> jax.Array:
    """Rotate interleaved pairs: x[..., 2i], x[..., 2i+1] by angle pos*freq_i.

    x: [B, T, H, head_size]; rope: [T, head_size/2, 2] rows already gathered
    for the absolute positions of the T tokens — or [B, T, head_size/2, 2]
    when rows differ per sequence (continuous batching: per-slot positions).
    """
    b, t, h, hs = x.shape
    xf = x.astype(jnp.float32).reshape(b, t, h, hs // 2, 2)
    if rope.ndim == 4:  # per-row rope rows
        cos = rope[:, :, None, :, 0]
        sin = rope[:, :, None, :, 1]
    else:
        cos = rope[None, :, None, :, 0]
        sin = rope[None, :, None, :, 1]
    x0, x1 = xf[..., 0], xf[..., 1]
    r0 = x0 * cos - x1 * sin
    r1 = x0 * sin + x1 * cos
    return jnp.stack([r0, r1], axis=-1).reshape(b, t, h, hs).astype(x.dtype)


def _dense_w(w, dtype):
    from dllama_tpu.ops.quant import QTensor

    return w.dequantize(dtype) if isinstance(w, QTensor) else w.astype(dtype)


def moe_ffn(
    cfg: LlamaConfig,
    h: jax.Array,  # [B, T, D] (already rms-normed)
    gate: jax.Array,  # router [D, E] f32
    w1, w2, w3,  # expert stacks: [E, D, F], [E, F, D], [E, D, F] (QTensor or dense)
    impl: str = "auto",  # 'auto' | 'dispatch' | 'sort' | 'dense'
    capacity_factor: float = 2.0,
) -> jax.Array:
    """Mixtral-style sparse MoE FFN: top-k router (softmax over the top-k
    logits), SwiGLU experts, probability-weighted combine.

    The reference *parses* N_EXPERTS from the header and its converter emits
    expert tensors, but the runtime has no MoE graph (SURVEY.md §2.4 — EP row);
    this is the capability it never shipped.

    Three compute schemes:
    * ``sort`` (default for T*B >= E): MegaBlocks-style grouped GEMM — sort
      the N*k (token, choice) rows by expert id (argsort + gathers, no
      scatters) and run ragged segment matmuls (``lax.ragged_dot``). Exact
      like dense (no capacity drops), O(k/E) FLOPs like dispatch, and none
      of dispatch's scatter risk on TPU.
    * ``dispatch``: GShard-style capacity-bucketed dispatch — each expert
      processes a fixed buffer of C = ~cf*k*N/E token rows (static shapes),
      so FLOPs are O(k/E) of dense. Tokens over an expert's capacity lose
      that expert's contribution (standard switch-transformer semantics;
      cf=2 makes drops rare), and the ``.at[].add`` combine may serialize
      on TPU (VERDICT r3 weak #6) — kept for the window A/B.
    * ``dense``: every expert runs on every token, combine weights zero the
      unrouted ones. Exact (no capacity drops) and gather-free — the
      correctness reference, and the cheaper choice for tiny batches where
      capacity C would equal N anyway.
    """
    e, k = cfg.n_experts, cfg.n_active_experts
    b, t, d = h.shape
    n = b * t
    if impl == "auto":
        # sort over dispatch: exact (no capacity drops), scatter-free (the
        # .at[].add scatters VERDICT r3 weak #6 suspects serialize on TPU),
        # 2.3x faster on CPU, and AOT-accepted for v5e/v6e (MOSAIC_AOT.md);
        # bench_moe's window A/B re-decides this with hardware numbers
        impl = "sort" if n >= e else "dense"
    logits = jnp.einsum(
        "btd,de->bte", h.astype(jnp.float32), gate.astype(jnp.float32)
    )
    topv, topi = jax.lax.top_k(logits, k)
    probs = jax.nn.softmax(topv, axis=-1)  # [B, T, k]

    if impl == "sort":
        hf = h.reshape(n, d)
        assign = topi.reshape(-1)  # [N*k] expert ids, token-major
        order = jnp.argsort(assign)  # stable: segments stay token-ordered
        inv = jnp.argsort(order)
        tok = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
        xs = hf[tok[order]]  # [N*k, D] rows grouped by expert
        group_sizes = jnp.bincount(assign, length=e).astype(jnp.int32)
        g = jax.lax.ragged_dot(xs, _dense_w(w1, h.dtype), group_sizes,
                               preferred_element_type=jnp.float32)
        up = jax.lax.ragged_dot(xs, _dense_w(w3, h.dtype), group_sizes,
                                preferred_element_type=jnp.float32)
        act = activation(g, cfg.hidden_act).astype(h.dtype)
        y = jax.lax.ragged_dot(act * up.astype(h.dtype), _dense_w(w2, h.dtype),
                               group_sizes, preferred_element_type=jnp.float32)
        # un-sort (gather by the inverse permutation — still no scatter),
        # then the k choices of each token sit contiguous: weighted-sum them
        y = y[inv].reshape(n, k, d)
        out = jnp.sum(y * probs.reshape(n, k)[..., None], axis=1)
        return out.reshape(b, t, d).astype(h.dtype)

    if impl == "dispatch":
        import math

        c = min(n, max(1, math.ceil(capacity_factor * k * n / e)))
        if c > 8:
            c = min(n, -(-c // 8) * 8)  # round up to the f32 sublane
        hf = h.reshape(n, d)
        assign = topi.reshape(-1)  # [N*k] expert ids, token-major
        onehot = jax.nn.one_hot(assign, e, dtype=jnp.int32)
        # arrival rank of each (token, choice) within its expert's buffer
        rank = jnp.sum(onehot * (jnp.cumsum(onehot, axis=0) - onehot), axis=-1)
        keep = rank < c
        tok = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
        ei = jnp.where(keep, assign, 0)
        ri = jnp.where(keep, rank, 0)
        # scatter token rows into [E, C, D] buffers; (ei, ri) pairs are unique
        # among kept rows, dropped rows contribute zeros at (0, 0)
        contrib = jnp.where(keep[:, None], hf[tok], 0).astype(h.dtype)
        buf = jnp.zeros((e, c, d), h.dtype).at[ei, ri].add(contrib)
        g = jnp.einsum("ecd,edf->ecf", buf, _dense_w(w1, h.dtype))
        up = jnp.einsum("ecd,edf->ecf", buf, _dense_w(w3, h.dtype))
        act = activation(g.astype(jnp.float32), cfg.hidden_act).astype(h.dtype)
        y = jnp.einsum("ecf,efd->ecd", act * up, _dense_w(w2, h.dtype))  # [E, C, D]
        y_tok = y[ei, ri].astype(jnp.float32)  # [N*k, D]
        wgt = probs.reshape(-1) * keep  # dropped choices contribute nothing
        out = jnp.zeros((n, d), jnp.float32).at[tok].add(y_tok * wgt[:, None])
        return out.reshape(b, t, d).astype(h.dtype)

    weights = jnp.sum(
        jax.nn.one_hot(topi, e, dtype=probs.dtype) * probs[..., None], axis=-2
    )  # [B, T, E]
    g = jnp.einsum("btd,edf->btef", h, _dense_w(w1, h.dtype))
    up = jnp.einsum("btd,edf->btef", h, _dense_w(w3, h.dtype))
    act = activation(g.astype(jnp.float32), cfg.hidden_act).astype(h.dtype)
    y = jnp.einsum("btef,efd->bted", act * up, _dense_w(w2, h.dtype))
    out = jnp.einsum("bted,bte->btd", y.astype(jnp.float32), weights)
    return out.astype(h.dtype)


def gqa_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k_cache: jax.Array,  # [B, Hkv, S, hd]
    v_cache: jax.Array,  # [B, Hkv, S, hd]
    pos_base: jax.Array,  # i32 scalar, or [B] per-sequence positions
) -> jax.Array:
    """Causal GQA over the full KV cache (nn-cpu-ops.cpp:752-787 equivalent).

    Query t attends to cache slots s <= pos_base + t; unwritten future slots
    are masked out, so the cache can stay a fixed [S]-sized ring without
    dynamic shapes (XLA needs static shapes; the mask replaces the
    reference's `t = 0..pos` loop bound). A vector pos_base gives each batch
    row its own position (continuous batching).
    """
    b, t, hq, hd = q.shape
    hkv, s = k_cache.shape[1], k_cache.shape[2]
    g = hq // hkv
    qf = q.astype(jnp.float32).reshape(b, t, hkv, g, hd)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    scores = jnp.einsum("bthgd,bhsd->bhgts", qf, kf) / math.sqrt(hd)
    spans = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1)
    qoff = jax.lax.broadcasted_iota(jnp.int32, (t, s), 0)
    pos_base = jnp.asarray(pos_base, jnp.int32)
    if pos_base.ndim == 1:
        mask = spans[None] <= pos_base[:, None, None] + qoff[None]  # [B, t, s]
        mask = mask[:, None, None]
    else:
        mask = (spans <= pos_base + qoff)[None, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgts,bhsd->bthgd", probs, vf)
    return out.reshape(b, t, hq, hd).astype(q.dtype)


def paged_view(pool: jax.Array, tables: jax.Array) -> jax.Array:
    """Gather a [B, Hkv, max_blocks*page, hd] contiguous cache view from a
    [P, Hkv, page, hd] page pool through [B, max_blocks] block tables —
    logical row r of slot b reads pool[tables[b, r // page], :, r % page].
    Rows behind unallocated table entries surface stale page contents; the
    caller's causal mask assigns them probability exactly 0.0 (pool values
    are always finite), so a view-based attention is bit-exact vs dense."""
    b, nb = tables.shape
    p, hkv, page, hd = pool.shape
    kv = pool[tables]  # [B, nb, Hkv, page, hd]
    return kv.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nb * page, hd)


def paged_write_targets(tables: jax.Array, pos_base: jax.Array, t: int,
                        page: int, n_pool: int,
                        active: jax.Array | None) -> tuple[jax.Array, jax.Array]:
    """(pages, offsets) i32[B, T] for writing T new KV rows at block-table
    positions — THE single definition of paged write addressing: logical
    row pos+tt of slot b lands in pool page tables[b, (pos+tt) // page] at
    offset (pos+tt) % page, block index clipped to the table width, and
    rows of inactive slots routed to the trash page (n_pool - 1, never
    allocated). Shared by models/llama._paged_cache_update (the XLA
    scatter) and ops/pallas/paged_attention (the fused in-kernel scatter),
    so the two write paths cannot drift apart."""
    b, nb = tables.shape
    pos = jnp.broadcast_to(jnp.asarray(pos_base, jnp.int32), (b,))
    rows = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None]  # [B, T]
    blk = jnp.clip(rows // page, 0, nb - 1)
    off = rows % page
    pages = jnp.take_along_axis(tables, blk, axis=1)  # [B, T]
    if active is not None:
        pages = jnp.where(active[:, None], pages, n_pool - 1)
    return pages.astype(jnp.int32), off.astype(jnp.int32)


def paged_gqa_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k_pool: jax.Array,  # [P, Hkv, page, hd] (one layer's pool slice)
    v_pool: jax.Array,
    tables: jax.Array,  # i32 [B, max_blocks]
    pos_base: jax.Array,  # i32 scalar, or [B] per-sequence positions
) -> jax.Array:
    """Causal GQA over the paged KV cache: the jnp reference/fallback path —
    gather the block-table view, then run the dense attention math unchanged.
    This re-materializes the ENTIRE view through XLA every step; the routed
    production path (`kernel_select` route 'paged_kernel') is the
    flash-decode kernel in ops/pallas/paged_attention.py, which DMA-walks
    pages via scalar-prefetched tables instead — this gather stays the
    bit-for-bit correctness reference and serves attn_impl='jnp', f8 pools,
    and non-sublane-aligned page sizes."""
    return gqa_attention(q, paged_view(k_pool, tables),
                         paged_view(v_pool, tables), pos_base)
