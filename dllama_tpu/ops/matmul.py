"""Quantized matmul dispatch: Pallas TPU kernel or XLA fallback.

The reference routes each matmul through a per-(op, quant-triple) kernel table
(nn-cpu-ops.cpp:1296-1355, llamafile sgemm for batch>1). Here the "dispatch
table" is two backends:

* ``xla``    — dequantize-then-dot in one jit; XLA fuses the dequant into the
               matmul epilogue. Correctness reference, and the only path on CPU.
* ``pallas`` — fused Q40 dequant-matmul kernels (ops/pallas/q40_matmul.py)
               that stream packed nibbles HBM->VMEM, i.e. ~3.5x less HBM
               traffic than bf16 weights — the decode hot loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dllama_tpu.ops.quant import QTensor

# module-level backend switch; engine sets this once at startup.
BACKEND = "auto"


def _use_pallas() -> bool:
    if BACKEND == "xla":
        return False
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:
        return False
    if BACKEND == "pallas":
        return True
    return platform == "tpu"


def matmul(x: jax.Array, w) -> jax.Array:
    """``x @ w`` where ``w`` is a QTensor or a dense [k, n] array.

    x: [..., k] activations (bf16/f32); returns [..., n] in x.dtype.
    """
    if isinstance(w, QTensor):
        if _use_pallas():
            from dllama_tpu.ops.pallas.q40_matmul import q40_matmul, supported

            if supported(x.shape, w):
                return q40_matmul(x, w)
        wd = w.dequantize(x.dtype)
    else:
        wd = w.astype(x.dtype)
    return jnp.dot(x, wd, preferred_element_type=jnp.float32).astype(x.dtype)
