"""Quantized matmul dispatch: Pallas TPU kernels or XLA fallback.

The reference routes each matmul through a per-(op, quant-triple) kernel table
(nn-cpu-ops.cpp:1296-1355, llamafile sgemm for batch>1). Here the "dispatch
table" is two backends:

* ``xla``    — dequantize-then-dot in one jit; XLA fuses the dequant into the
               matmul epilogue. Correctness reference, and the only path on
               CPU and on sharded (GSPMD) engines: ``pallas_call`` has no
               partitioning rule, so under a mesh the Pallas path would
               all-gather sharded weights per call.
* ``pallas`` — fused Q40 dequant-matmul kernels (ops/pallas/q40_matmul.py)
               that stream packed nibbles HBM->VMEM (~3x less HBM traffic
               than bf16 weights) and address layer-stacked weights by
               scalar-prefetch index (no per-layer slice copies). Inside, a
               decode-shaped (m<=16) and a prefill-shaped (m>16) kernel split
               mirrors the reference's GEMV/sgemm tiering.

Backend resolution: an explicit ``backend=`` argument wins (the engine passes
one resolved at construction — per-engine, not global), then the module-level
``BACKEND`` switch (CLI ``--kernels``), then platform auto-detection.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dllama_tpu.ops.quant import Q8Tensor, QTensor, slice_leaf

# module-level backend switch; the CLI sets this once at startup.
BACKEND = "auto"

# prefill GEMM routing (VERDICT r2 #4 / reference's llamafile sgemm tier,
# nn-cpu-ops.cpp:1003-1019): at or above this flattened batch*seq, a Pallas-
# backed matmul routes to the XLA dequant-dot instead — prefill is FLOPs-bound
# and the plain MXU GEMM beats in-kernel unpacking once the packed-bytes
# saving stops mattering. None = always fused (the pre-measurement default);
# bench.py overrides via BENCH_XLA_PREFILL_M to A/B it on hardware.
XLA_PREFILL_MIN_M: int | None = None

# Pallas interpret-mode override: None = auto (interpret off-TPU, the normal
# rule). experiments/aot_check.py sets False while AOT-compiling for a TPU
# topology from a CPU host — the platform check would otherwise bake
# interpret=True into the trace and Mosaic would never see the kernel.
INTERPRET: bool | None = None


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except RuntimeError:
        return "cpu"


def resolve_backend(backend: str | None = None, sharded: bool = False) -> str:
    """'pallas' or 'xla'. Sharded engines force 'xla' unless explicitly
    overridden (pallas_call under GSPMD would gather the sharded weights)."""
    b = backend or BACKEND
    if b == "auto":
        if sharded:
            return "xla"
        return "pallas" if _platform() == "tpu" else "xla"
    return b


def engine_matmul(kernels: str, shardings) -> "functools.partial":
    """The single place engines turn their (kernels flag, shardings) pair
    into a bound matmul — InferenceEngine and BatchEngine share this so the
    resolution rule can never diverge between tiers."""
    import functools

    backend = resolve_backend(
        None if kernels == "auto" else kernels, sharded=shardings is not None
    )
    return functools.partial(matmul, backend=backend)


def _route_xla_prefill(x: jax.Array) -> bool:
    """Prefill-GEMM routing rule, shared by the Q40 and Q80 fused paths.

    Prefill-shaped only (ADVICE r3): model activations are [b, t, d], so
    t > 1 distinguishes prefill from batched decode — a 64-slot decode step
    must NOT lose the packed-weights bandwidth win just because its
    flattened m crosses the threshold. 2-D calls (no seq axis) are
    decode-shaped by construction."""
    if XLA_PREFILL_MIN_M is None or not (x.ndim >= 3 and x.shape[-2] > 1):
        return False
    m = 1
    for d in x.shape[:-1]:
        m *= d
    return m >= XLA_PREFILL_MIN_M


def matmul(x: jax.Array, w, layer=None, backend: str | None = None) -> jax.Array:
    """``x @ w`` (or ``x @ w[layer]``) where ``w`` is a QTensor/Q8Tensor or
    dense array.

    x: [..., k] activations (bf16/f32); returns [..., n] in x.dtype.
    ``layer``: traced index into a layer-stacked weight ([L, k, n] logical) —
    the Pallas path indexes the stack via DMA, the XLA path slices it.
    """
    if isinstance(w, (QTensor, Q8Tensor)):
        if resolve_backend(backend) == "pallas":
            # Q80 gets the same fused treatment as Q40 (1.0625 B/weight
            # streamed vs 2 for the dense-bf16 fallback), same routing rule
            if isinstance(w, QTensor):
                from dllama_tpu.ops.pallas.q40_matmul import q40_matmul as kernel
                from dllama_tpu.ops.pallas.q40_matmul import supported
            else:
                from dllama_tpu.ops.pallas.q80_matmul import q80_matmul as kernel
                from dllama_tpu.ops.pallas.q80_matmul import supported

            if supported(x.shape, w) and not _route_xla_prefill(x):
                interp = INTERPRET if INTERPRET is not None else _platform() != "tpu"
                return kernel(x, w, layer, interpret=interp)
        if layer is not None and len(w.shape) == 3:
            w = slice_leaf(w, layer)
        wd = w.dequantize(x.dtype)
    else:
        if layer is not None and jnp.ndim(w) == 3:
            w = slice_leaf(w, layer)
        wd = w.astype(x.dtype)
    return jnp.dot(x, wd, preferred_element_type=jnp.float32).astype(x.dtype)
