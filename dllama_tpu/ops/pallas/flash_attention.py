"""Flash-style causal GQA attention over the KV cache — Pallas TPU kernel.

The reference computes attention per head with an explicit scores buffer of
size seqLen (multiheadAtt_F32, nn-cpu-ops.cpp:752-787): scores → softmax →
weighted sum, all materialized. On TPU that buffer would round-trip HBM; this
kernel is the online-softmax (flash) formulation instead — the KV cache is
streamed tile-by-tile through VMEM while a running (max, sum, acc) state stays
resident, so nothing of size S ever leaves the chip.

Layout: queries are folded to [B*Hkv, T*group, hd] — one program per KV
head, with that head's `group` query heads interleaved t-major into the row
axis (row = t*group + g) — and the grid walks (kv_head, q_tile, kv_tile)
with the kv sweep innermost ("arbitrary" — it carries the accumulator). One
kv sweep serves the WHOLE query group: folding per *query* head instead
(the naive layout) re-DMAs every KV tile `group` times, which at decode
makes cache traffic group x larger than the cache (GQA group is 4 on the
llama 3 models; at 8 Ki context that redundancy costs more than the weight
stream). No materialized repeat_kv either way.

Causality follows gqa_attention's fixed-size-cache masking (ops/layers.py):
query t sees cache slots s <= pos_base + t, which also masks the unwritten
tail of the ring buffer.

KV-tile pruning: the cache is a fixed [S] ring (static shapes for XLA), but a
decode step at position p only has p+1 live rows. `pos` rides as a
scalar-prefetch argument so the k/v index maps can clamp the kv-tile index to
the last live tile — Pallas elides the DMA when consecutive grid steps map to
the same block — and the kernel skips the masked tiles' compute entirely.
Decode cost then scales with the *live* cache, not S (the reference's
`t = 0..pos` loop bound, nn-cpu-ops.cpp:752-787, recovered without dynamic
shapes).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dllama_tpu.ops.pallas.tiling import COMPILER_PARAMS, pick_tile as _pick_tile

_NEG_INF = -1e30  # large-finite: keeps fully-masked tiles NaN-free


def _kernel(pos_ref, q_ref, k_ref, v_ref, out_ref, acc_ref, m_ref, l_ref, *, scale, tq, ts, hkv, group, rows_live):
    iq = pl.program_id(1)
    ks = pl.program_id(2)

    @pl.when(ks == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # query-row absolute positions: row r holds (t, g) = divmod(iq*tq + r,
    # group) interleaved t-major, so its token offset is (iq*tq + r) // group
    # (b = this program's batch row; padded tail rows are discarded by the
    # wrapper) — computed OUTSIDE the pl.when (program_id can't lower inside
    # its branch in interpret mode). The row index is clamped to the last REAL
    # row (ADVICE r3): sublane-pad rows would otherwise map past the true last
    # token and admit one extra live KV tile per decode step when group < 8.
    pos_b = pos_ref[pl.program_id(0) // hkv]
    qpos_max = pos_b + jnp.minimum(iq * tq + tq - 1, rows_live - 1) // group

    # kv tiles fully past the last visible position are dead (their DMA was
    # elided by the clamped index map too): skip their compute
    @pl.when(ks * ts <= qpos_max)
    def _():
        q = q_ref[:].astype(jnp.float32)  # [tq, hd]
        k = k_ref[:].astype(jnp.float32)  # [ts, hd]
        v = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        s = s * scale  # [tq, ts]

        # causal mask against absolute cache positions
        row = jax.lax.broadcasted_iota(jnp.int32, (tq, ts), 0)
        qpos = pos_b + (iq * tq + row) // group
        span = ks * ts + jax.lax.broadcasted_iota(jnp.int32, (tq, ts), 1)
        mask = span <= qpos
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:][:, :1]  # replicated across lanes; take one
        l_prev = l_ref[:][:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.where(mask, jnp.exp(s - m_cur), 0.0)  # [tq, ts]
        l_cur = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_cur, l_ref.shape)

    @pl.when(ks == pl.num_programs(2) - 1)
    def _():
        l = l_ref[:][:, :1]
        out_ref[:] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("group", "hkv", "interpret", "rows_live"))
def _flash_folded(q, k, v, pos, *, group: int, hkv: int, interpret: bool,
                  rows_live: int | None = None):
    """q[BHkv, Tp*group, hd] x cache[BHkv, S, hd] -> [BHkv, Tp*group, hd] f32.
    Query rows are t-major interleaved over the GQA group (row = t*group + g)
    so one kv sweep serves the whole group. pos: i32[B] per-row base
    positions (replicated for the scalar case). rows_live: real (pre-padding)
    row count — pad rows are excluded from the live-KV-tile horizon."""
    bhkv, rows, hd = q.shape
    s = k.shape[1]
    rows_live = rows_live or rows
    tq = _pick_tile(rows, (128, 64, 32, 16, 8))
    ts = _pick_tile(s, (512, 256, 128, 64))
    grid = (bhkv, rows // tq, s // ts)

    def kv_index(h, i, ks, pos):
        # clamp dead kv tiles to the last LIVE tile: the repeated block index
        # makes Pallas skip the DMA, and the kernel skips their compute (the
        # row index clamp mirrors the kernel's qpos_max — pad rows must not
        # widen the horizon)
        last_row = jnp.minimum(i * tq + tq - 1, rows_live - 1)
        last_live = (pos[h // hkv] + last_row // group) // ts
        return (h, jnp.minimum(ks, last_live), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # pos: i32[B]
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, tq, hd), lambda h, i, ks, pos: (h, i, 0)),
            pl.BlockSpec((None, ts, hd), kv_index),
            pl.BlockSpec((None, ts, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((None, tq, hd), lambda h, i, ks, pos: (h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((tq, hd), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, scale=1.0 / math.sqrt(hd), tq=tq, ts=ts,
                          hkv=hkv, group=group, rows_live=rows_live),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bhkv, rows, hd), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * bhkv * rows * s * hd,
            bytes_accessed=(bhkv * rows * hd * 2) * q.dtype.itemsize
            + 2 * bhkv * s * hd * k.dtype.itemsize,
            transcendentals=bhkv * rows * s,
        ),
        interpret=interpret,
    )(pos, q, k, v)


def _s_buckets(s: int) -> tuple[int, ...]:
    """Ascending static cache-view lengths for the bucketed grid: powers of
    two from 512 up to S (each tileable per `supported`), always ending at S.
    Empty when the cache is too short to bucket. Valid for decode AND prefill
    chunks: the dispatch horizon is max(pos) + t, so a chunk ending inside
    bucket k rides bucket k's view and the causal mask handles the rest."""
    if s <= 512:
        return ()
    out = []
    b = 512
    while b < s:
        out.append(b)
        b *= 2
    out.append(s)
    return tuple(out)


def flash_gqa_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k_cache: jax.Array,  # [B, Hkv, S, hd]
    v_cache: jax.Array,  # [B, Hkv, S, hd]
    pos_base: jax.Array,  # i32 scalar or [B] per-row positions
    *,
    interpret: bool = False,
    s_buckets: bool = False,
) -> jax.Array:
    """Drop-in for ops.layers.gqa_attention (same signature/semantics).

    s_buckets: bucket the kv grid by live-context length. The KV-tile pruning
    already elides dead tiles' DMA and compute, but the grid itself is static
    in S — at 8 Ki context and small pos the kernel still issues ~S/ts no-op
    grid steps per head per layer. With bucketing, the call dispatches
    (lax.switch) to a kernel instance whose cache view is the smallest
    power-of-two bucket covering max(pos)+t, so the walked grid tracks the
    live context — for decode steps and for the early chunks of a long
    chunked prefill alike. Off by default until the depth sweep (kbench
    flash) shows the no-op steps cost real time; flip via
    DLLAMA_FLASH_BUCKETS=1."""
    b, t, hq, hd = q.shape
    hkv, s = k_cache.shape[1], k_cache.shape[2]
    group = hq // hkv
    # fold the GQA group into the row axis, t-major: q head h = kv*group + g
    # lands at row t*group + g of kv head kv (see module docstring)
    qf = (
        q.reshape(b, t, hkv, group, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b * hkv, t * group, hd)
    )
    rows = t * group
    pad = (-rows) % 8
    if pad:
        qf = jnp.pad(qf, ((0, 0), (0, pad), (0, 0)))
    pos = jnp.broadcast_to(jnp.atleast_1d(jnp.asarray(pos_base, jnp.int32)), (b,))
    kf = k_cache.reshape(b * hkv, s, hd)
    vf = v_cache.reshape(b * hkv, s, hd)
    call = functools.partial(_flash_folded, group=group, hkv=hkv,
                             interpret=interpret, rows_live=rows)

    buckets = _s_buckets(s) if s_buckets else ()
    if len(buckets) > 1:
        # every query row sees cache slots <= max(pos) + t - 1; the branch's
        # static view must cover that horizon
        horizon = jnp.max(pos) + t
        idx = sum((horizon > be).astype(jnp.int32) for be in buckets[:-1])
        out = jax.lax.switch(
            idx,
            [functools.partial(lambda se, qq, kk, vv, pp: call(
                qq, kk[:, :se], vv[:, :se], pp), se) for se in buckets],
            qf, kf, vf, pos,
        )
    else:
        out = call(qf, kf, vf, pos)
    if pad:
        out = out[:, :rows]
    return (
        out.reshape(b, hkv, t, group, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, t, hq, hd)
        .astype(q.dtype)
    )


def supported(q_shape: tuple[int, ...], cache_seq_len: int) -> bool:
    """Tileability check for the engine's attention dispatcher."""
    return cache_seq_len % 64 == 0 and q_shape[-1] >= 8


# --------------------------------------------------------------- paged cache
#
# LEGACY block-spec-pipelined paged variant: requires a page to hold whole
# 64-row kv tiles (`paged_supported`), which is why the serving tier no
# longer routes it — engine/kernel_select resolves the paged layout to the
# GENERAL any-page-size kernel in ops/pallas/paged_attention.py (manual
# double-buffered page DMA + fused KV scatter). Kept as the pipelined
# reference/A/B variant for tileable pages; tests/test_paged_kv.py still
# pins it against the jnp gather.


def _paged_kernel(pos_ref, tables_ref, *args, **kw):
    """The paged grid prefetches (pos, tables); the flash math itself is
    identical — masking is by LOGICAL position, which the index maps (not
    the kernel body) translate to pool pages."""
    return _kernel(pos_ref, *args, **kw)


@functools.partial(jax.jit, static_argnames=("group", "hkv", "interpret",
                                             "rows_live"))
def _flash_paged_folded(q, k_pool, v_pool, pos, tables, *, group: int,
                        hkv: int, interpret: bool, rows_live: int | None = None):
    """q[BHkv, Tp*group, hd] x pool[P, Hkv, page, hd] -> [BHkv, rows, hd] f32.

    Same folded layout and online-softmax state as _flash_folded; the kv
    BlockSpecs index the PAGE POOL through the block tables (scalar-prefetch
    arg #2), so each kv grid step DMAs one page tile — the kernel never sees
    a materialized contiguous cache. The live-tile clamp carries over: dead
    logical tiles map to the last live tile's page (repeated block index =>
    Pallas elides the DMA) and their compute is skipped by the kernel."""
    bhkv, rows, hd = q.shape
    page = k_pool.shape[2]
    nb = tables.shape[1]
    s = nb * page  # logical cache view length
    rows_live = rows_live or rows
    tq = _pick_tile(rows, (128, 64, 32, 16, 8))
    ts = _pick_tile(page, (512, 256, 128, 64))
    tiles_per_page = page // ts
    grid = (bhkv, rows // tq, s // ts)

    def kv_index(h, i, ks, pos, tables):
        # clamp dead LOGICAL tiles to the last live one (mirrors _flash_folded),
        # then translate the logical tile to (pool page, tile-within-page)
        last_row = jnp.minimum(i * tq + tq - 1, rows_live - 1)
        last_live = (pos[h // hkv] + last_row // group) // ts
        lk = jnp.minimum(ks, last_live)
        pg = tables[h // hkv, lk // tiles_per_page]
        return (pg, h % hkv, lk % tiles_per_page, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # pos: i32[B], tables: i32[B, nb]
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, tq, hd), lambda h, i, ks, pos, tables: (h, i, 0)),
            pl.BlockSpec((None, None, ts, hd), kv_index),
            pl.BlockSpec((None, None, ts, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((None, tq, hd),
                               lambda h, i, ks, pos, tables: (h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((tq, hd), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale=1.0 / math.sqrt(hd), tq=tq,
                          ts=ts, hkv=hkv, group=group, rows_live=rows_live),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bhkv, rows, hd), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * bhkv * rows * s * hd,
            bytes_accessed=(bhkv * rows * hd * 2) * q.dtype.itemsize
            + 2 * bhkv * s * hd * k_pool.dtype.itemsize,
            transcendentals=bhkv * rows * s,
        ),
        interpret=interpret,
    )(pos, tables, q, k_pool, v_pool)


def paged_flash_gqa_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k_pool: jax.Array,  # [P, Hkv, page, hd] (one layer's pool slice)
    v_pool: jax.Array,
    tables: jax.Array,  # i32 [B, max_blocks]
    pos_base: jax.Array,  # i32 scalar or [B] per-row positions
    *,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for ops.layers.paged_gqa_attention (same signature/semantics):
    block-table-indexed flash attention — the kv sweep walks pool pages via
    the prefetched tables, so the paged layout pays no gather materialization
    and keeps the dense kernel's live-tile DMA pruning."""
    b, t, hq, hd = q.shape
    hkv = k_pool.shape[1]
    group = hq // hkv
    qf = (
        q.reshape(b, t, hkv, group, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b * hkv, t * group, hd)
    )
    rows = t * group
    pad = (-rows) % 8
    if pad:
        qf = jnp.pad(qf, ((0, 0), (0, pad), (0, 0)))
    pos = jnp.broadcast_to(jnp.atleast_1d(jnp.asarray(pos_base, jnp.int32)), (b,))
    out = _flash_paged_folded(qf, k_pool, v_pool, pos,
                              jnp.asarray(tables, jnp.int32),
                              group=group, hkv=hkv, interpret=interpret,
                              rows_live=rows)
    if pad:
        out = out[:, :rows]
    return (
        out.reshape(b, hkv, t, group, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, t, hq, hd)
        .astype(q.dtype)
    )


def paged_supported(q_shape: tuple[int, ...], page_size: int) -> bool:
    """Tileability check for the paged dispatcher: a page must hold a whole
    number of 64-wide kv tiles (the tile never spans a page boundary)."""
    return page_size % 64 == 0 and q_shape[-1] >= 8
