"""General paged flash-decode attention — any-page-size Pallas TPU kernel.

PR 5's block-table flash variant (ops/pallas/flash_attention.paged_*) rides
the automatic BlockSpec pipeline, which constrains a page to hold whole
64-row kv tiles (`page_size % 64 == 0`); every other page size fell back to
``ops/layers.paged_gqa_attention`` — a jnp gather that re-materializes the
ENTIRE paged KV through XLA every step, the exact memory-traffic blowup
PagedAttention (Kwon et al., 2023, vLLM) exists to avoid.

This kernel drops the tileability requirement by driving the KV pipeline
manually (the jax-ml TPU paged_attention pattern, exemplified in
SNIPPETS.md [1]/[2]'s `pltpu.PrefetchScalarGridSpec` scalar-prefetch idiom):

* the page pools stay in HBM (``memory_space=ANY``) — the kernel, not the
  BlockSpec machinery, owns their movement;
* ``(pos, tables)`` ride as scalar-prefetch arguments, so the kernel walks
  each slot's block table and issues double-buffered ``make_async_copy``
  DMAs of one PAGE at a time into VMEM (page i+1's copy is in flight while
  page i is in the MXU) — any page size, the partial last page masked by
  the same absolute-position causal mask the dense kernel uses;
* the grid is one step per (slot, kv_head, q_tile); the page run is a
  dynamic ``fori_loop`` bounded by the slot's LIVE page count (``pos``-
  derived), so decode cost scales with the live context exactly like the
  dense kernel's tile pruning;
* the new token's KV rows are scatter-written into the pool INSIDE the same
  launch (``input_output_aliases`` keeps the pool update in place): the
  separate `_paged_cache_update` dispatch decode used to pay per layer is
  gone, and the attention sweep reads the row it just wrote.

Numerics are the same online-softmax (flash) formulation as
``flash_attention._kernel``: f32 accumulation, large-finite mask fill, one
running (m, l, acc) state per q tile.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dllama_tpu.ops.pallas.tiling import COMPILER_PARAMS, pick_tile as _pick_tile

_NEG_INF = -1e30  # large-finite: keeps fully-masked pages NaN-free

#: Chunks longer than this scatter their KV rows with a single XLA scatter
#: before the kernel launches instead of fusing per-row DMAs into it — a
#: 256-token prefill chunk would otherwise serialize 2*T row copies per
#: (slot, head) program. Decode (t=1) and batched spec verify (t=k+1) sit
#: far below it and always fuse.
FUSED_SCATTER_MAX_T = 16

#: VMEM budget for the double-buffered page landing zones (2 pages x (k, v)
#: live at once). Pages above it route to the gather fallback instead of
#: risking a Mosaic VMEM overflow at compile time.
_PAGE_VMEM_BYTES = 4 * 1024 * 1024


def paged_decode_supported(q_shape: tuple[int, ...], page_size: int,
                           kv_dtype=jnp.bfloat16) -> bool:
    """Capability check for the engine's paged-attention dispatcher — the
    explicit (dtype / head-dim / page-geometry) contract that replaced the
    old `paged_supported` whole-64-row-tile gate:

    * any page size that is a whole number of 8-row sublanes (the DMA
      granularity of the VMEM landing buffers); no power-of-two or 64-row
      requirement — 8, 24, 120 all route to the kernel;
    * head_size >= 8 (same floor as the dense flash kernel);
    * 16- or 32-bit kv elements (bf16 / f32 pools). f8 pools route to the
      gather fallback: Mosaic rejects the f8->f32 in-register extension
      (`arith.extf` is 16->32-bit only — the same rejection the DENSE f8
      flash path now hits in the AOT gate, a libtpu-level pre-existing
      condition, so paged matches dense f8 behavior rather than extending
      the breakage);
    * double-buffering two (k, v) page pairs must fit the VMEM budget.

    Ragged tables need no capability: unallocated entries are clamped to
    the last live page by the kernel and masked by position, so any
    ``max_blocks`` works.
    """
    hd = q_shape[-1]
    el = jnp.dtype(kv_dtype).itemsize
    return (
        page_size >= 8
        and page_size % 8 == 0
        and hd >= 8
        and el in (2, 4)
        and 4 * page_size * hd * el <= _PAGE_VMEM_BYTES
    )


def _kernel(pos_ref, tables_ref, wpages_ref, woffs_ref,  # scalar prefetch
            q_ref, newk_ref, newv_ref,  # VMEM blocks
            kpool_in, vpool_in,  # HBM (ANY) — aliased to outputs
            out_ref, kpool_ref, vpool_ref,  # out block + aliased pools
            kbuf, vbuf, acc_ref, m_ref, l_ref, copy_sems, write_sem,
            *, scale, page, group, t, tq, rows_live, nb, fused):
    b = pl.program_id(0)
    h = pl.program_id(1)
    iq = pl.program_id(2)

    # ---- fused KV scatter: the new token rows land in the pool before this
    # (slot, head)'s sweep starts. Mosaic cannot DMA a dynamically-offset
    # single sublane row, so each write is a whole-page read-modify-write:
    # DMA the target page into the (not-yet-used) double buffer, blend the
    # row at its offset (f32 blend — sub-32-bit sublane broadcasts don't
    # lower; bf16<->f32 round-trips exactly), DMA the page back. One page
    # round-trip per row per pool — trivial against the decode sweep, and
    # t is capped at FUSED_SCATTER_MAX_T (prefill pre-scatters via XLA).
    # Only the first q tile of each (slot, head) writes; rows are blended
    # in order, so a duplicate (page, offset) target — only possible for
    # trash-page collisions when t > page_size — resolves last-row-wins.
    if fused:
        @pl.when(iq == 0)
        def _():
            for tt in range(t):  # static unroll: t is a trace-time int
                pg = wpages_ref[b, tt]
                off = woffs_ref[b, tt]
                sel = jax.lax.broadcasted_iota(
                    jnp.int32, (page, newk_ref.shape[-1]), 0) == off
                for src, pool, buf in ((newk_ref, kpool_ref, kbuf),
                                       (newv_ref, vpool_ref, vbuf)):
                    cp = pltpu.make_async_copy(
                        pool.at[pg, h], buf.at[0], write_sem)
                    cp.start()
                    cp.wait()
                    row = src[tt].astype(jnp.float32)[None, :]
                    buf[0] = jnp.where(
                        sel, jnp.broadcast_to(row, sel.shape),
                        buf[0].astype(jnp.float32)).astype(buf.dtype)
                    cp = pltpu.make_async_copy(
                        buf.at[0], pool.at[pg, h], write_sem)
                    cp.start()
                    cp.wait()

    # ---- live-page horizon for this q tile (mirrors flash_attention's
    # kv-tile clamp: pad rows must not widen it)
    pos_b = pos_ref[b]
    last_row = jnp.minimum(iq * tq + tq - 1, rows_live - 1)
    qpos_max = pos_b + last_row // group
    # clamp to the table capacity: the logical view is exactly nb*page rows
    # (a horizon past it reads nothing, same as the gather reference's view)
    npages = jnp.minimum(qpos_max // page + 1, nb)

    q = q_ref[...].astype(jnp.float32)  # [tq, hd]
    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)

    def start_copy(i, slot):
        # defensive clamp like _paged_cache_update: a horizon past the
        # allocated table reads the last entry (its rows are masked anyway)
        pg = tables_ref[b, jnp.minimum(i, nb - 1)]
        ck = pltpu.make_async_copy(
            kpool_ref.at[pg, h], kbuf.at[slot], copy_sems.at[slot, 0])
        cv = pltpu.make_async_copy(
            vpool_ref.at[pg, h], vbuf.at[slot], copy_sems.at[slot, 1])
        return ck, cv

    ck0, cv0 = start_copy(0, 0)
    ck0.start()
    cv0.start()

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < npages)
        def _():
            ck, cv = start_copy(i + 1, jax.lax.rem(i + 1, 2))
            ck.start()
            cv.start()

        ck, cv = start_copy(i, slot)
        ck.wait()
        cv.wait()
        k = kbuf[slot].astype(jnp.float32)  # [page, hd]
        v = vbuf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale  # [tq, page]

        # causal mask against absolute cache positions: query row r of tile
        # iq holds token offset (iq*tq + r) // group (t-major GQA fold)
        row = jax.lax.broadcasted_iota(jnp.int32, (tq, page), 0)
        qpos = pos_b + (iq * tq + row) // group
        span = i * page + jax.lax.broadcasted_iota(jnp.int32, (tq, page), 1)
        mask = span <= qpos
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...][:, :1]
        l_prev = l_ref[...][:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.where(mask, jnp.exp(s - m_cur), 0.0)
        l_cur = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur, l_ref.shape)
        return 0

    jax.lax.fori_loop(0, npages, body, 0)
    l = l_ref[...][:, :1]
    out_ref[...] = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)


@functools.partial(jax.jit, static_argnames=("group", "interpret",
                                             "rows_live", "fused"))
def _paged_folded(qf, k_pool, v_pool, pos, tables, wpages, woffs, new_k,
                  new_v, *, group: int, interpret: bool, rows_live: int,
                  fused: bool):
    """qf[B, Hkv, rows_pad, hd] x pool[P, Hkv, page, hd] ->
    (out f32 [B, Hkv, rows_pad, hd], k_pool, v_pool).

    The pools ride in HBM (ANY memory space) and alias their outputs, so the
    fused scatter is an in-place update at the XLA level; the kernel DMA-
    walks them through the prefetched block tables."""
    b, hkv, rows, hd = qf.shape
    npool, _, page, _ = k_pool.shape
    nb = tables.shape[1]
    t = new_k.shape[2]
    tq = _pick_tile(rows, (128, 64, 32, 16, 8))
    grid = (b, hkv, rows // tq)
    any_spec = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # pos[B], tables[B, nb], wpages/woffs[B, t]
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, tq, hd), lambda b, h, iq, *_: (b, h, iq, 0)),
            pl.BlockSpec((None, None, t, hd), lambda b, h, iq, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, t, hd), lambda b, h, iq, *_: (b, h, 0, 0)),
            any_spec,  # k pool (HBM)
            any_spec,  # v pool (HBM)
        ],
        out_specs=[
            pl.BlockSpec((None, None, tq, hd), lambda b, h, iq, *_: (b, h, iq, 0)),
            any_spec,
            any_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((2, page, hd), k_pool.dtype),  # double-buffered k pages
            pltpu.VMEM((2, page, hd), v_pool.dtype),
            pltpu.VMEM((tq, hd), jnp.float32),  # acc
            pltpu.VMEM((tq, 128), jnp.float32),  # m
            pltpu.VMEM((tq, 128), jnp.float32),  # l
            pltpu.SemaphoreType.DMA((2, 2)),  # (buffer slot, k/v) copies
            pltpu.SemaphoreType.DMA(()),  # scatter writes
        ],
    )
    out, k_pool, v_pool = pl.pallas_call(
        functools.partial(_kernel, scale=1.0 / math.sqrt(hd), page=page,
                          group=group, t=t, tq=tq, rows_live=rows_live,
                          nb=nb, fused=fused),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, rows, hd), jnp.float32),
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
        ],
        # after the 4 scalar-prefetch args: qf=4, newk=5, newv=6, kpool=7,
        # vpool=8; the pools alias outputs 1 and 2 (in-place update)
        input_output_aliases={7: 1, 8: 2},
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * hkv * rows * nb * page * hd,
            bytes_accessed=(b * hkv * rows * hd * 2) * qf.dtype.itemsize
            + 2 * b * hkv * nb * page * hd * k_pool.dtype.itemsize,
            transcendentals=b * hkv * rows * nb * page,
        ),
        interpret=interpret,
    )(pos, tables, wpages, woffs, qf, new_k, new_v, k_pool, v_pool)
    return out, k_pool, v_pool


def paged_decode_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k_pool: jax.Array,  # [P, Hkv, page, hd] (one layer's pool slice)
    v_pool: jax.Array,
    tables: jax.Array,  # i32 [B, max_blocks]
    pos_base: jax.Array,  # i32 scalar or [B] per-row positions
    new_k: jax.Array | None = None,  # [B, Hkv, T, hd] rows to scatter first
    new_v: jax.Array | None = None,
    active: jax.Array | None = None,  # [B] bool: inactive rows -> trash page
    *,
    interpret: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array, jax.Array]:
    """Block-table paged attention over the HBM page pool, any page size.

    Without ``new_k``/``new_v`` this is a drop-in for
    ``ops.layers.paged_gqa_attention`` (returns the [B, T, Hq, hd] output
    only). With them, the call is the FUSED decode step: the new rows are
    scatter-written at their block-table positions (``active=False`` rows
    to the trash page) and the attention sweep reads them — returns
    ``(out, k_pool, v_pool)`` with the pools updated in place
    (input/output aliased). Chunks longer than ``FUSED_SCATTER_MAX_T``
    scatter via XLA before the launch instead (identical result; prefill
    chunks should not serialize per-row DMAs)."""
    b, t, hq, hd = q.shape
    n_pool, hkv, page, _ = k_pool.shape
    group = hq // hkv
    qf = (
        q.reshape(b, t, hkv, group, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, hkv, t * group, hd)
    )
    rows = t * group
    pad = (-rows) % 8
    if pad:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    pos = jnp.broadcast_to(jnp.atleast_1d(jnp.asarray(pos_base, jnp.int32)),
                           (b,))
    tables = jnp.asarray(tables, jnp.int32)

    write = new_k is not None
    if write:
        # the ONE definition of paged write addressing (shared with
        # _paged_cache_update — the fused scatter is write-for-write
        # identical to the separate dispatch it replaces)
        from dllama_tpu.ops.layers import paged_write_targets

        wpages, woffs = paged_write_targets(tables, pos, t, page, n_pool,
                                            active)
        if t > FUSED_SCATTER_MAX_T:
            # prefill-sized chunk: one XLA scatter, then a read-only sweep
            k_pool = k_pool.at[wpages, :, woffs, :].set(
                new_k.transpose(0, 2, 1, 3).astype(k_pool.dtype))
            v_pool = v_pool.at[wpages, :, woffs, :].set(
                new_v.transpose(0, 2, 1, 3).astype(v_pool.dtype))
            write = False
    if not write:
        # dummy single-row write of what the trash page already gets —
        # the kernel skips the scatter entirely (fused=False)
        wpages = jnp.zeros((b, 1), jnp.int32)
        woffs = jnp.zeros((b, 1), jnp.int32)
        nk = jnp.zeros((b, hkv, 1, hd), k_pool.dtype)
        nv = jnp.zeros((b, hkv, 1, hd), v_pool.dtype)
    else:
        nk = new_k.astype(k_pool.dtype)
        nv = new_v.astype(v_pool.dtype)

    out, k_pool, v_pool = _paged_folded(
        qf, k_pool, v_pool, pos, tables, wpages, woffs, nk, nv,
        group=group, interpret=interpret, rows_live=rows, fused=write)
    if pad:
        out = out[:, :, :rows]
    out = (
        out.reshape(b, hkv, t, group, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, t, hq, hd)
        .astype(q.dtype)
    )
    if new_k is None:
        return out
    return out, k_pool, v_pool
