"""Fused Q80 dequant-matmul Pallas kernels.

The reference runs Q80-weight models through the same kernel table as Q40
(matmul_Q80_Q80 rows, nn-cpu-ops.cpp:448-540); here the win is again HBM
bandwidth: int8 codes + f16 block scales stream 1.0625 bytes/weight from
HBM — ~1.9x less than the dense-bf16 fallback Q80 files previously loaded
as. Structure mirrors ops/pallas/q40_matmul.py (layer-stacked weights via
scalar-prefetch indexing, (m, n, k)/(n, k) grids with the k sweep
innermost, f32 VMEM accumulator), minus the nibble unpack — int8 codes
convert exactly to the activation dtype (|q| <= 127 is integral and exact
even in bf16), so the decode scheme is the same scale-the-partials
blockdot: y[kb] = x_kb @ codes_kb on the MXU, out = sum_kb s[kb] * y[kb].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dllama_tpu.ops.pallas.q40_matmul import _scales_f32
from dllama_tpu.ops.pallas.tiling import COMPILER_PARAMS, pick_tile as _pick_tile
from dllama_tpu.ops.quant import Q_BLOCK, Q8Tensor


def _deq_kernel(layer_ref, x_ref, codes_ref, scales_ref, out_ref, acc_ref, *, tk, tn):
    del layer_ref
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    c = codes_ref[:].astype(jnp.float32).reshape(tk // Q_BLOCK, Q_BLOCK, tn)
    s = _scales_f32(scales_ref[:])[:, None, :]
    w = (c * s).reshape(tk, tn).astype(x_ref.dtype)
    acc_ref[:] += jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _blockdot_kernel(layer_ref, xb_ref, codes_ref, scales_ref, out_ref, acc_ref, *, tk, tn):
    del layer_ref
    kb = pl.program_id(1)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # int8 codes are exact in the activation dtype; per-weight VPU work is
    # one convert, the f32 scales touch only the [nb, m, tn] partials
    c = codes_ref[:].astype(xb_ref.dtype).reshape(tk // Q_BLOCK, Q_BLOCK, tn)
    y = jax.lax.dot_general(
        xb_ref[:], c, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )  # [nb, m, tn]
    acc_ref[:] += jnp.sum(y * _scales_f32(scales_ref[:])[:, None, :], axis=0)

    @pl.when(kb == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _deq_call(layer, x, codes, scales, *, interpret: bool = False):
    m, k = x.shape
    n = codes.shape[-1]
    tm = _pick_tile(m, (512, 256, 128, 64, 32, 16, 8))
    tn = _pick_tile(n, (512, 256, 128))
    tk = _pick_tile(k, (512, 256, 128, 64, 32))
    grid = (m // tm, n // tn, k // tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, tk), lambda i, j, kb, L: (i, kb)),
            pl.BlockSpec((None, tk, tn), lambda i, j, kb, L: (L[0], kb, j)),
            pl.BlockSpec((None, tk // Q_BLOCK, tn), lambda i, j, kb, L: (L[0], kb, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, kb, L: (i, j)),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_deq_kernel, tk=tk, tn=tn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * x.dtype.itemsize + k * n
            + (k // Q_BLOCK) * n * scales.dtype.itemsize + m * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(layer, x, codes, scales)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _blockdot_call(layer, x, codes, scales, *, interpret: bool = False):
    m, k = x.shape
    n = codes.shape[-1]
    tn = _pick_tile(n, (1024, 512, 256, 128))
    tk = _pick_tile(k, (2048, 1024, 512, 256, 128, 64, 32))
    nb = tk // Q_BLOCK
    # x pre-blocked [nb_total, m, 32]: block b of the k axis sits at row b
    xb = x.reshape(m, k // Q_BLOCK, Q_BLOCK).transpose(1, 0, 2)
    grid = (n // tn, k // tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, m, Q_BLOCK), lambda j, kb, L: (kb, 0, 0)),
            pl.BlockSpec((None, tk, tn), lambda j, kb, L: (L[0], kb, j)),
            pl.BlockSpec((None, nb, tn), lambda j, kb, L: (L[0], kb, j)),
        ],
        out_specs=pl.BlockSpec((m, tn), lambda j, kb, L: (0, j)),
        scratch_shapes=[pltpu.VMEM((m, tn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_blockdot_kernel, tk=tk, tn=tn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * x.dtype.itemsize + k * n
            + (k // Q_BLOCK) * n * scales.dtype.itemsize + m * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(layer, xb, codes, scales)


def supported(x_shape: tuple[int, ...], w: Q8Tensor) -> bool:
    """Tileability gate, mirroring q40_matmul.supported."""
    k, n = w.shape[-2], w.shape[-1]
    return x_shape[-1] == k and k % Q_BLOCK == 0 and n % 128 == 0 and k >= 128


def q80_matmul(x: jax.Array, w: Q8Tensor, layer=None, *, interpret: bool = False) -> jax.Array:
    """``x[..., k] @ dequant(w[layer])`` -> [..., n] in x.dtype.

    Same decode/prefill split as q40_matmul: m <= 16 rides the
    scale-the-partials blockdot (no dequantized matrix is ever built),
    larger m the classic in-kernel dequant GEMM.
    """
    *lead, k = x.shape
    assert k % Q_BLOCK == 0 and k >= 128 and w.shape[-1] % 128 == 0, (
        f"untileable Q80 matmul: k={k}, n={w.shape[-1]} (see supported())"
    )
    m = 1
    for d in lead:
        m *= d
    codes, scales = w.codes, w.scales
    if codes.ndim == 2:
        codes, scales = codes[None], scales[None]
        layer = 0
    else:
        assert layer is not None, "stacked Q8Tensor needs a layer index"
    n = codes.shape[-1]
    if scales.dtype == jnp.float16:
        scales = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    x2 = x.reshape(m, k)
    pad = (-m) % 8
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    if m + pad <= 16:
        out = _blockdot_call(lay, x2, codes, scales, interpret=interpret)
    else:
        out = _deq_call(lay, x2, codes, scales, interpret=interpret)
    if pad:
        out = out[:m]
    return out.reshape(*lead, n).astype(x.dtype)
