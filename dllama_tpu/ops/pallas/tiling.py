"""Shared tiling helpers for the Pallas kernels."""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

#: jax-version compat: the TPU compiler-params dataclass is
#: ``pltpu.CompilerParams`` on newer jax and ``pltpu.TPUCompilerParams`` on
#: older releases (e.g. 0.4.x). Every kernel constructs it through this
#: alias so one jax pin change cannot strand the whole Pallas tier.
COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def pick_tile(dim: int, candidates: tuple[int, ...]) -> int:
    """Largest candidate that divides `dim`, else `dim` itself (one tile)."""
    for c in candidates:
        if dim % c == 0:
            return c
    return dim
