"""Shared tiling helpers for the Pallas kernels."""

from __future__ import annotations


def pick_tile(dim: int, candidates: tuple[int, ...]) -> int:
    """Largest candidate that divides `dim`, else `dim` itself (one tile)."""
    for c in candidates:
        if dim % c == 0:
            return c
    return dim
