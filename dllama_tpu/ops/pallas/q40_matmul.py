"""Fused Q40 dequant-matmul Pallas kernel — the decode hot loop.

The reference's equivalent is matmul_Q80_Q40 (nn-cpu-ops.cpp:225-446) plus
llamafile sgemm for prefill; on TPU the win is HBM bandwidth: the kernel
streams the *packed* 4-bit weights (0.56 bytes/weight incl. scales) from HBM
into VMEM and dequantizes on-chip right before the MXU dot — ~3.5x less HBM
traffic than bf16 weights, which is the whole game for batch=1 decode.

Layout (see ops/quant.QTensor): ``packed: u8[k/2, n]`` where packed row
``16*b + j`` holds codes for input dims ``32*b + j`` (low nibble) and
``32*b + j + 16`` (high nibble); ``scales: f16[k/32, n]``.

Grid is (m_tiles, n_tiles, k_tiles) with k innermost: the f32 accumulator
block stays VMEM-resident across the k sweep and is written back once per
(m, n) tile. Inputs are double-buffered by the Pallas pipeline automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dllama_tpu.ops.pallas.tiling import pick_tile as _pick_tile
from dllama_tpu.ops.quant import Q_BLOCK, QTensor


def _kernel(x_ref, packed_ref, scales_ref, out_ref, acc_ref, *, tk: int, tn: int):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # unpack nibbles -> codes in [-8, 7] laid out [tk//32, 32, tn]
    p = packed_ref[:].astype(jnp.int32).reshape(tk // Q_BLOCK, Q_BLOCK // 2, tn)
    lo = (p & 0x0F) - 8
    hi = (p >> 4) - 8
    codes = jnp.concatenate([lo, hi], axis=1)  # [tk//32, 32, tn]
    s = scales_ref[:].astype(jnp.float32)[:, None, :]
    w = (codes.astype(jnp.float32) * s).reshape(tk, tn).astype(x_ref.dtype)
    acc_ref[:] += jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def q40_matmul_2d(x: jax.Array, packed: jax.Array, scales: jax.Array, *, interpret: bool = False) -> jax.Array:
    """x[m, k] @ dequant(packed, scales)[k, n] -> f32[m, n]."""
    m, k = x.shape
    n = packed.shape[1]
    tm = _pick_tile(m, (256, 128, 64, 32, 16, 8))
    tn = _pick_tile(n, (512, 256, 128))
    tk = _pick_tile(k, (512, 256, 128, 64, 32))
    assert k % Q_BLOCK == 0 and tk % Q_BLOCK == 0, (k, tk)

    grid = (m // tm, n // tn, k // tk)
    return pl.pallas_call(
        functools.partial(_kernel, tk=tk, tn=tn),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, tk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((tk // 2, tn), lambda i, j, kb: (kb, j)),
            pl.BlockSpec((tk // Q_BLOCK, tn), lambda i, j, kb: (kb, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, kb: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * x.dtype.itemsize + k * n // 2 + (k // Q_BLOCK) * n * 2 + m * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, packed, scales)


def supported(x_shape: tuple[int, ...], w: QTensor) -> bool:
    """Tileability check used by the ops.matmul dispatcher."""
    k, n = w.shape
    return k % Q_BLOCK == 0 and n % 128 == 0 and k >= 128


def q40_matmul(x: jax.Array, w: QTensor, *, interpret: bool = False) -> jax.Array:
    """``x @ w`` for any leading batch dims; returns x.dtype like the XLA path."""
    *lead, k = x.shape
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, k)
    # pad rows up to the f32 sublane (8) so tiny decode batches still tile
    pad = (-m) % 8
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = q40_matmul_2d(x2, w.packed, w.scales, interpret=interpret)
    if pad:
        out = out[:m]
    return out.reshape(*lead, w.shape[1]).astype(x.dtype)
