"""Fused Q40 dequant-matmul Pallas kernels — the decode/prefill hot loop.

The reference's equivalent is matmul_Q80_Q40 (nn-cpu-ops.cpp:225-446) for
decode plus llamafile sgemm (sgemm.cpp:819-1010) for prefill; on TPU the win
is HBM bandwidth: the kernel streams the *packed* 4-bit weights (0.5625
bytes/weight incl. f16 scales) from HBM into VMEM and dequantizes on-chip
right before the MXU dot — ~3x less HBM traffic than bf16 weights, which is
the whole game for small-batch decode.

Two TPU-specific design points beyond the reference's scheme:

1. **Layer-stacked weights with scalar-prefetch indexing.** The model keeps
   every layer's weights stacked as one ``[L, k/2, n]`` array (the scanned
   forward needs that layout). Feeding ``lax.dynamic_slice`` output to a
   custom call would make XLA materialize a full HBM copy of every weight,
   every layer, every token — tripling decode traffic. Instead the kernels
   take the whole stacked array plus the layer index as a scalar-prefetch
   argument; the Pallas DMA pipeline indexes the layer directly in HBM
   (``PrefetchScalarGridSpec``), so no copy ever exists.

2. **Two dequant schemes, split by batch size** (the reference's decode
   GEMV / prefill sgemm split, nn-cpu-ops.cpp:1003-1019):

   * ``deq`` (m > 16): classic in-kernel dequant — unpack nibbles, one
     fused multiply per weight, bf16 dot. Dequant cost amortizes over the m
     rows, so prefill is MXU-bound.
   * ``blockdot`` (m <= 16): decode is HBM/VPU-bound and per-element dequant
     arithmetic is the bottleneck, so this kernel never builds the dequantized
     matrix. Nibbles become *exact* signed codes ``q - 8`` via an
     exponent-trick bitcast (OR into the mantissa of 2^23 where the float ulp
     is 1, subtract 2^23 + 8 — exact by Sterbenz), the codes are lossless in
     bf16 (|q-8| <= 8), the MXU computes per-block partial dots
     y[kb] = x_kb @ codes_kb, and the f32 block scales touch only the tiny
     [k/32, m, n-tile] partials:  out = sum_kb s[kb] * y[kb].
     Per-weight VPU work drops to the ~2-op unpack; the scale math is
     O(m/32) per weight element and the per-element dequant multiply is gone.

Layout (see ops/quant.QTensor): ``packed: u8[(L,) k/2, n]`` where packed row
``16*b + j`` holds codes for input dims ``32*b + j`` (low nibble) and
``32*b + j + 16`` (high nibble); ``scales: f16[(L,) k/32, n]`` (streamed as
raw u16 bits, widened in-register by ``_scales_f32``).

Grid is (m_tiles, n_tiles, k_tiles) with k innermost: the f32 accumulator
block stays VMEM-resident across the k sweep and is written back once per
(m, n) tile. Inputs are double-buffered by the Pallas pipeline automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dllama_tpu.ops.pallas.tiling import COMPILER_PARAMS, pick_tile as _pick_tile
from dllama_tpu.ops.quant import Q_BLOCK, QTensor

# f32 bit pattern of 2^23 = 8388608.0; mantissa ulp there is exactly 1, so
# OR-ing a nibble q into the low bits gives the exact float 2^23 + q, and
# subtracting (2^23 + 8) yields the exact signed code q - 8 (the subtraction
# of nearby floats is exact by Sterbenz' lemma) — int->float conversion and
# the -8 offset in two cheap VPU ops, no convert instruction.
_EXP_BITS = 0x4B000000
_V_OFFSET = 8388608.0 + 8.0

# kernel-style override for benchmarks:
# 'auto' | 'deq' | 'blockdot' | 'maskdot' | 'loopdot'
# ('maskdot' = blockdot's math with the per-block partial dots expressed as
# ONE plain dot on a block-masked activation matrix — a fallback in case
# Mosaic rejects the batched dot_general; MXU does nb x redundant zero MACs,
# irrelevant while decode is HBM/VPU-bound. 'loopdot' = the same math as a
# STATICALLY UNROLLED sequence of plain [m,32]x[32,tn] dots — no batched
# dot_general, no masking, no redundant MACs; the most lowering-conservative
# fallback, at the cost of nb tiny MXU launches per grid step.)
STYLE = "auto"

# decode-kernel tile overrides for on-hardware autotuning (experiments/
# kbench.py sweeps these): None = the pick_tile defaults. tk/tn must divide
# the op's k/n; out-of-range overrides fall back to the default pick.
BLOCKDOT_TK: int | None = None
BLOCKDOT_TN: int | None = None


def _unpack_codes(packed_block, tk: int, tn: int):
    """u8[tk/2, tn] nibbles -> f32[tk/32, 32, tn] of exact codes q - 8."""
    p = packed_block.astype(jnp.int32)
    lo = (p & 0x0F) | _EXP_BITS
    hi = (p >> 4) | _EXP_BITS
    nb = tk // Q_BLOCK
    half = Q_BLOCK // 2
    codes = jnp.concatenate(
        [lo.reshape(nb, half, tn), hi.reshape(nb, half, tn)], axis=1
    )
    return jax.lax.bitcast_convert_type(codes, jnp.float32) - _V_OFFSET


# 2^112: shifts an f16 exponent (bias 15) into the f32 field (bias 127) after
# the mantissa/exponent bits are placed at f32 positions.
_F16_WIDEN = 2.0 ** 112


def _scales_f32(s):
    """Widen a scales tile to f32 in-register.

    QTensor scales live as f16 in HBM (half the scale bytes — ~10% of Q40
    decode traffic) and reach the kernel bitcast to u16 (the dispatcher does
    the bitcast; Mosaic support for f16 vectors is not assumed). The widening
    places sign/exponent/mantissa at their f32 offsets and rescales by 2^112 —
    exact for all normal AND subnormal f16 values (the classic half->float
    exponent-scaling identity; the only mismatch would be f16 inf/nan, which
    the Q40 quantizer never produces). Note: if the VPU flushes f32
    subnormals, a subnormal f16 scale (<6.1e-5) decodes to 0 — affected
    weights are < 5e-4 in magnitude, far below quantization noise.

    f32 tiles pass through untouched (hand-built QTensors)."""
    if s.dtype == jnp.uint16:
        u = s.astype(jnp.uint32)
        bits = ((u & 0x8000) << 16) | ((u & 0x7FFF) << 13)
        return jax.lax.bitcast_convert_type(bits, jnp.float32) * _F16_WIDEN
    return s.astype(jnp.float32)


def _deq_kernel(layer_ref, x_ref, packed_ref, scales_ref, out_ref, acc_ref, *, tk, tn):
    del layer_ref  # consumed by the index maps
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    c = _unpack_codes(packed_ref[:], tk, tn)  # [nb, 32, tn] exact q - 8
    s = _scales_f32(scales_ref[:])[:, None, :]
    w = (c * s).reshape(tk, tn).astype(x_ref.dtype)
    acc_ref[:] += jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _blockdot_kernel(
    layer_ref, xb_ref, packed_ref, scales_ref, out_ref, acc_ref, *, tk, tn
):
    del layer_ref
    kb = pl.program_id(1)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # codes q-8 are EXACT in the activation dtype (|q-8| <= 8, integral —
    # lossless even in bf16), so the MXU block-dot on raw codes is exact; the
    # f32 scales touch only the [nb, m, tn] partials — per-weight VPU work is
    # just the unpack, no per-element dequant multiply.
    c = _unpack_codes(packed_ref[:], tk, tn).astype(xb_ref.dtype)  # [nb, 32, tn]
    y = jax.lax.dot_general(
        xb_ref[:], c, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )  # [nb, m, tn]
    s = _scales_f32(scales_ref[:])[:, None, :]  # [nb, 1, tn]
    acc_ref[:] += jnp.sum(y * s, axis=0)

    @pl.when(kb == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _deq_call(layer, x, packed, scales, *, interpret: bool = False):
    """x[m, k] @ dequant(packed[layer], scales[layer]) -> f32[m, n]."""
    m, k = x.shape
    n = packed.shape[-1]
    tm = _pick_tile(m, (512, 256, 128, 64, 32, 16, 8))
    tn = _pick_tile(n, (512, 256, 128))
    tk = _pick_tile(k, (512, 256, 128, 64, 32))
    grid = (m // tm, n // tn, k // tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, tk), lambda i, j, kb, L: (i, kb)),
            pl.BlockSpec((None, tk // 2, tn), lambda i, j, kb, L: (L[0], kb, j)),
            pl.BlockSpec((None, tk // Q_BLOCK, tn), lambda i, j, kb, L: (L[0], kb, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, kb, L: (i, j)),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_deq_kernel, tk=tk, tn=tn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * x.dtype.itemsize
            + k * n // 2
            + (k // Q_BLOCK) * n * scales.dtype.itemsize
            + m * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(layer, x, packed, scales)


def _maskdot_kernel(
    layer_ref, x_ref, packed_ref, scales_ref, out_ref, acc_ref, *, tk, tn
):
    del layer_ref
    kb = pl.program_id(1)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    m = x_ref.shape[0]
    nb = tk // Q_BLOCK
    w = _unpack_codes(packed_ref[:], tk, tn).astype(x_ref.dtype).reshape(tk, tn)
    # x replicated per block row, masked to that block's 32 lanes: one big dot
    # then computes every per-block partial y[b] = x_b @ codes_b at once
    lane = jax.lax.broadcasted_iota(jnp.int32, (nb, m, tk), 2)
    blk = jax.lax.broadcasted_iota(jnp.int32, (nb, m, tk), 0)
    xaug = jnp.where(lane // Q_BLOCK == blk, x_ref[:][None], 0).reshape(nb * m, tk)
    y = jnp.dot(xaug, w, preferred_element_type=jnp.float32).reshape(nb, m, tn)
    acc_ref[:] += jnp.sum(y * _scales_f32(scales_ref[:])[:, None, :], axis=0)

    @pl.when(kb == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _loopdot_kernel(
    layer_ref, xb_ref, packed_ref, scales_ref, out_ref, acc_ref, *, tk, tn
):
    del layer_ref
    kb = pl.program_id(1)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # blockdot's exact math (codes q-8 lossless in the activation dtype, f32
    # scales applied to the per-block partials) with the nb-batched dot
    # unrolled into nb PLAIN dots at static indices — nothing here that a
    # Mosaic build supporting jnp.dot can reject
    c = _unpack_codes(packed_ref[:], tk, tn).astype(xb_ref.dtype)  # [nb, 32, tn]
    s = _scales_f32(scales_ref[:])  # [nb, tn]
    acc = acc_ref[:]
    for b in range(tk // Q_BLOCK):  # static unroll
        y = jnp.dot(xb_ref[b], c[b], preferred_element_type=jnp.float32)
        acc = acc + y * s[b][None, :]
    acc_ref[:] = acc

    @pl.when(kb == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _loopdot_call(layer, x, packed, scales, *, interpret: bool = False):
    """blockdot fallback #2: same math, statically-unrolled plain dots. Small
    tk keeps the unroll count (tk/32 dots per grid step) bounded."""
    m, k = x.shape
    n = packed.shape[-1]
    nb = k // Q_BLOCK
    tn = _pick_tile(n, (512, 256, 128))
    tk = _pick_tile(k, (256, 128, 64, 32))
    grid = (n // tn, k // tk)
    xb = x.reshape(m, nb, Q_BLOCK).transpose(1, 0, 2)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tk // Q_BLOCK, m, Q_BLOCK), lambda j, kb, L: (kb, 0, 0)),
            pl.BlockSpec((None, tk // 2, tn), lambda j, kb, L: (L[0], kb, j)),
            pl.BlockSpec((None, tk // Q_BLOCK, tn), lambda j, kb, L: (L[0], kb, j)),
        ],
        out_specs=pl.BlockSpec((m, tn), lambda j, kb, L: (0, j)),
        scratch_shapes=[pltpu.VMEM((m, tn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_loopdot_kernel, tk=tk, tn=tn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * 4 + k * n // 2 + (k // Q_BLOCK) * n * scales.dtype.itemsize + m * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(layer, xb, packed, scales)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _maskdot_call(layer, x, packed, scales, *, interpret: bool = False):
    """blockdot fallback: same math, plain-dot-only lowering (m <= 16)."""
    m, k = x.shape
    n = packed.shape[-1]
    tn = _pick_tile(n, (512, 256, 128))
    tk = _pick_tile(k, (512, 256, 128, 64, 32))
    grid = (n // tn, k // tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, tk), lambda j, kb, L: (0, kb)),
            pl.BlockSpec((None, tk // 2, tn), lambda j, kb, L: (L[0], kb, j)),
            pl.BlockSpec((None, tk // Q_BLOCK, tn), lambda j, kb, L: (L[0], kb, j)),
        ],
        out_specs=pl.BlockSpec((m, tn), lambda j, kb, L: (0, j)),
        scratch_shapes=[pltpu.VMEM((m, tn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_maskdot_kernel, tk=tk, tn=tn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k * (tk // Q_BLOCK),  # nb-masked redundant MACs
            bytes_accessed=m * k * x.dtype.itemsize + k * n // 2 + (k // Q_BLOCK) * n * scales.dtype.itemsize + m * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(layer, x, packed, scales)


@functools.partial(jax.jit, static_argnames=("interpret", "tk", "tn"))
def _blockdot_call(layer, x, packed, scales, *, interpret: bool = False,
                   tk: int | None = None, tn: int | None = None):
    """Decode-shaped path: x[m<=16, k] against stacked Q40 weights.
    tk/tn are static tile overrides (from the module knobs, validated by the
    dispatcher) — part of the jit key so an autotune sweep actually recompiles."""
    m, k = x.shape
    n = packed.shape[-1]
    nb = k // Q_BLOCK
    tn = tn or _pick_tile(n, (512, 256, 128))
    tk = tk or _pick_tile(k, (2048, 1024, 512, 256, 128, 64, 32))
    grid = (n // tn, k // tk)
    # pre-shaped outside the kernel: Mosaic can't split the lane dim in-kernel
    xb = x.reshape(m, nb, Q_BLOCK).transpose(1, 0, 2)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tk // Q_BLOCK, m, Q_BLOCK), lambda j, kb, L: (kb, 0, 0)),
            pl.BlockSpec((None, tk // 2, tn), lambda j, kb, L: (L[0], kb, j)),
            pl.BlockSpec((None, tk // Q_BLOCK, tn), lambda j, kb, L: (L[0], kb, j)),
        ],
        out_specs=pl.BlockSpec((m, tn), lambda j, kb, L: (0, j)),
        scratch_shapes=[pltpu.VMEM((m, tn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_blockdot_kernel, tk=tk, tn=tn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * 4 + k * n // 2 + (k // Q_BLOCK) * n * scales.dtype.itemsize + m * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(layer, xb, packed, scales)


def supported(x_shape: tuple[int, ...], w: QTensor) -> bool:
    """Tileability check used by the ops.matmul dispatcher."""
    k, n = w.shape[-2], w.shape[-1]
    return k % Q_BLOCK == 0 and n % 128 == 0 and k >= 128


def q40_matmul(
    x: jax.Array, w: QTensor, layer=None, *, interpret: bool = False
) -> jax.Array:
    """``x @ w[layer]`` for any leading batch dims; returns x.dtype.

    ``w`` may be a 2-D weight (``layer=None``) or a layer-stacked
    ``[L, k, n]`` weight addressed by the traced scalar ``layer`` — the
    stacked form is indexed by the DMA engine, never sliced by XLA.
    """
    *lead, k = x.shape
    assert k % Q_BLOCK == 0 and k >= 128 and w.shape[-1] % 128 == 0, (
        f"untileable Q40 matmul: k={k}, n={w.shape[-1]} (see supported())"
    )
    m = 1
    for d in lead:
        m *= d
    if w.packed.ndim == 2:
        packed, scales = w.packed[None], w.scales[None]
        layer = 0
    else:
        packed, scales = w.packed, w.scales
        assert layer is not None, "stacked QTensor needs a layer index"
    n = packed.shape[-1]
    if scales.dtype == jnp.float16:
        # kernels take raw u16 bits (see _scales_f32); the bitcast is free
        scales = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    x2 = x.reshape(m, k)
    # pad rows up to the f32 sublane (8) so tiny decode batches still tile
    pad = (-m) % 8
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    mp = m + pad
    style = STYLE
    if style == "auto":
        style = "blockdot" if mp <= 16 else "deq"
    elif style in ("blockdot", "maskdot", "loopdot") and mp > 16:
        # forced decode-shaped styles apply only to decode-shaped calls; a
        # forced style is a DECODE-kernel selector, prefill always uses deq
        # (callers labeling results must report per-m paths, see bench.py)
        style = "deq"
    if style == "blockdot":
        tk_o = BLOCKDOT_TK if (
            BLOCKDOT_TK and k % BLOCKDOT_TK == 0 and BLOCKDOT_TK % Q_BLOCK == 0
        ) else None
        tn_o = BLOCKDOT_TN if (BLOCKDOT_TN and n % BLOCKDOT_TN == 0) else None
        out = _blockdot_call(layer_arr, x2, packed, scales, interpret=interpret,
                             tk=tk_o, tn=tn_o)
    elif style == "maskdot":
        out = _maskdot_call(layer_arr, x2, packed, scales, interpret=interpret)
    elif style == "loopdot":
        out = _loopdot_call(layer_arr, x2, packed, scales, interpret=interpret)
    else:
        out = _deq_call(layer_arr, x2, packed, scales, interpret=interpret)
    if pad:
        out = out[:m]
    return out.reshape(*lead, n).astype(x.dtype)


def q40_matmul_2d(
    x: jax.Array, packed: jax.Array, scales: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """Back-compat wrapper: x[m, k] @ dequant(packed, scales) -> f32[m, n]."""
    if scales.dtype == jnp.float16:
        scales = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    layer = jnp.zeros((1,), jnp.int32)
    return _deq_call(layer, x, packed[None], scales[None], interpret=interpret)
