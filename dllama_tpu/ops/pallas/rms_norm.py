"""Fused RMSNorm Pallas kernel.

The reference splits this into two ops — OP_INV_RMS then OP_RMS_NORM
(nn-cpu-ops.cpp:108-183) — because its executor has no fusion. XLA usually
fuses the jnp version (ops/layers.rms_norm) into neighbors on its own; this
kernel exists for the cases where it doesn't (norm feeding a Pallas matmul,
which XLA treats as an opaque call and won't fuse across) and as the
single-pass reference for kernel-equivalence tests: one VMEM-resident tile,
f32 accumulation, rsqrt, weight multiply, one HBM read + one write per row.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dllama_tpu.ops.pallas.tiling import pick_tile as _pick_tile


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def _rms_norm_2d(x: jax.Array, w: jax.Array, *, eps: float, interpret: bool) -> jax.Array:
    rows, d = x.shape
    tr = _pick_tile(rows, (256, 128, 64, 32, 16, 8))
    return pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=(rows // tr,),
        in_specs=[
            pl.BlockSpec((tr, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tr, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x, w.reshape(1, d))


def rms_norm(x: jax.Array, weight: jax.Array, eps: float, *, interpret: bool = False) -> jax.Array:
    """Drop-in for ops.layers.rms_norm: y = x * w / rms(x), any leading dims."""
    *lead, d = x.shape
    m = 1
    for s in lead:
        m *= s
    x2 = x.reshape(m, d)
    pad = (-m) % 8
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = _rms_norm_2d(x2, weight, eps=eps, interpret=interpret)
    if pad:
        out = out[:m]
    return out.reshape(*lead, d)
