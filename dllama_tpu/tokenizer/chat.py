"""Chat template engine + streaming stop-sequence (EOS) detection.

Behavioral port of the reference's ChatTemplate (tokenizer.cpp:481-552) and
EosDetector (tokenizer.cpp:554-639): templates are auto-detected from the
tokenizer's embedded jinja string; the EOS detector buffers partially-matched
stop strings so they are never emitted to the user.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum, IntEnum

from dllama_tpu.tokenizer.tokenizer import Tokenizer


class ChatTemplateType(IntEnum):
    UNKNOWN = 0
    LLAMA2 = 1
    LLAMA3 = 2
    DEEP_SEEK3 = 3


@dataclass
class ChatItem:
    role: str
    message: str


@dataclass
class GeneratedChat:
    content: str
    public_prompt: str | None  # template-injected text the user should see (e.g. "<think>\n")


class ChatTemplate:
    def __init__(self, type_: ChatTemplateType, chat_template: str | None, eos: str):
        if type_ == ChatTemplateType.UNKNOWN:
            if chat_template is None:
                raise ValueError("the tokenizer does not include a chat template")
            if "[INST]" in chat_template:
                type_ = ChatTemplateType.LLAMA2
            elif "<|start_header_id|>" in chat_template:
                type_ = ChatTemplateType.LLAMA3
            elif "<｜Assistant｜>" in chat_template:
                type_ = ChatTemplateType.DEEP_SEEK3
            else:
                raise ValueError("not supported chat template")
        self.type = type_
        self.eos = eos

    def generate(self, items: list[ChatItem], append_generation_prompt: bool = True) -> GeneratedChat:
        buf = []
        public_prompt = None
        if self.type == ChatTemplateType.LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                buf.append(
                    f"[INST] <<SYS>>\n{items[0].message}\n<</SYS>>\n\n{items[1].message} [/INST]{self.eos}"
                )
                i = 2
            for item in items[i:]:
                if item.role == "assistant":
                    buf.append(item.message + self.eos)
                elif item.role == "user":
                    buf.append(f"[INST] {item.message} [/INST]{self.eos}")
        elif self.type == ChatTemplateType.LLAMA3:
            for item in items:
                buf.append(
                    f"<|start_header_id|>{item.role}<|end_header_id|>\n\n{item.message}{self.eos}"
                )
            if append_generation_prompt:
                buf.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == ChatTemplateType.DEEP_SEEK3:
            i = 0
            if items and items[0].role == "system":
                buf.append(items[0].message)
                i = 1
            for item in items[i:]:
                if item.role == "user":
                    buf.append(f"<｜User｜>{item.message}")
                elif item.role == "assistant":
                    buf.append(f"<｜Assistant｜>{item.message}")
            if append_generation_prompt:
                buf.append("<｜Assistant｜><think>\n")
                public_prompt = "<think>\n"
        return GeneratedChat("".join(buf), public_prompt)


def chat_stops(tokenizer: Tokenizer) -> list[str]:
    """Stop strings = pieces of the tokenizer's EOS token ids (tokenizer.cpp:455-468)."""
    return [tokenizer.piece(t) for t in tokenizer.eos_ids]


class EosResult(Enum):
    MAYBE_EOS = 0
    EOS = 1
    NOT_EOS = 2


class EosDetector:
    """Streaming multi-stop-sequence matcher with MAYBE buffering.

    `padding_left/right` tolerate up to that many junk characters before/after
    a stop string (the chat CLI uses left=2/right=2 for stray spaces and
    newlines around e.g. "<|eot_id|>", dllama.cpp:140).
    """

    def __init__(self, stop_token_ids: list[int], stop_pieces: list[str], padding_left: int = 0, padding_right: int = 0):
        self.stop_token_ids = list(stop_token_ids)
        self.stop_pieces = list(stop_pieces)
        self.padding_left = padding_left
        self.padding_right = padding_right
        self.buffer = ""
        self._eos_pos: int | None = None

    def is_eos_token(self, token: int) -> bool:
        return token in self.stop_token_ids

    def append(self, token: int, piece: str | None) -> EosResult:
        if piece:
            self.buffer += piece
        if self.is_eos_token(token):
            self._eos_pos = len(self.buffer)
            return EosResult.EOS
        self._eos_pos = None
        for stop in self.stop_pieces:
            if len(self.buffer) > len(stop) + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = len(self.buffer) - lo
                if n == 0 or n > len(stop) + self.padding_right:
                    continue
                n = min(n, len(stop))
                if self.buffer[lo : lo + n] == stop[:n]:
                    if n == len(stop):
                        self._eos_pos = lo
                        self.buffer = self.buffer[:lo]
                        return EosResult.EOS
                    return EosResult.MAYBE_EOS
        return EosResult.NOT_EOS

    def get_delta(self) -> str | None:
        """Text safe to emit now (everything before any detected stop)."""
        if not self.buffer:
            return None
        if self._eos_pos == 0:
            return None
        return self.buffer

    def reset(self) -> None:
        self.buffer = ""
        self._eos_pos = None
