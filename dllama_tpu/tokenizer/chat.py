"""Chat template engine + streaming stop-sequence (EOS) detection.

Behavioral port of the reference's ChatTemplate (tokenizer.cpp:481-552) and
EosDetector (tokenizer.cpp:554-639): templates are auto-detected from the
tokenizer's embedded jinja string; the EOS detector buffers partially-matched
stop strings so they are never emitted to the user.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum, IntEnum

from dllama_tpu.tokenizer.tokenizer import Tokenizer


class ChatTemplateType(IntEnum):
    UNKNOWN = 0
    LLAMA2 = 1
    LLAMA3 = 2
    DEEP_SEEK3 = 3


@dataclass
class ChatItem:
    role: str
    message: str


@dataclass
class GeneratedChat:
    content: str
    public_prompt: str | None  # template-injected text the user should see (e.g. "<think>\n")


class ChatTemplate:
    def __init__(self, type_: ChatTemplateType, chat_template: str | None, eos: str):
        if type_ == ChatTemplateType.UNKNOWN:
            if chat_template is None:
                raise ValueError("the tokenizer does not include a chat template")
            if "[INST]" in chat_template:
                type_ = ChatTemplateType.LLAMA2
            elif "<|start_header_id|>" in chat_template:
                type_ = ChatTemplateType.LLAMA3
            elif "<｜Assistant｜>" in chat_template:
                type_ = ChatTemplateType.DEEP_SEEK3
            else:
                raise ValueError("not supported chat template")
        self.type = type_
        self.eos = eos

    def generate(self, items: list[ChatItem], append_generation_prompt: bool = True) -> GeneratedChat:
        buf = []
        public_prompt = None
        if self.type == ChatTemplateType.LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                buf.append(
                    f"[INST] <<SYS>>\n{items[0].message}\n<</SYS>>\n\n{items[1].message} [/INST]{self.eos}"
                )
                i = 2
            for item in items[i:]:
                if item.role == "assistant":
                    buf.append(item.message + self.eos)
                elif item.role == "user":
                    buf.append(f"[INST] {item.message} [/INST]{self.eos}")
        elif self.type == ChatTemplateType.LLAMA3:
            for item in items:
                buf.append(
                    f"<|start_header_id|>{item.role}<|end_header_id|>\n\n{item.message}{self.eos}"
                )
            if append_generation_prompt:
                buf.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == ChatTemplateType.DEEP_SEEK3:
            i = 0
            if items and items[0].role == "system":
                buf.append(items[0].message)
                i = 1
            for item in items[i:]:
                if item.role == "user":
                    buf.append(f"<｜User｜>{item.message}")
                elif item.role == "assistant":
                    buf.append(f"<｜Assistant｜>{item.message}")
            if append_generation_prompt:
                buf.append("<｜Assistant｜><think>\n")
                public_prompt = "<think>\n"
        return GeneratedChat("".join(buf), public_prompt)


def chat_stops(tokenizer: Tokenizer) -> list[str]:
    """Stop strings = pieces of the tokenizer's EOS token ids (tokenizer.cpp:455-468)."""
    return [tokenizer.piece(t) for t in tokenizer.eos_ids]


class EosResult(Enum):
    MAYBE_EOS = 0
    EOS = 1
    NOT_EOS = 2


class EosDetector:
    """Streaming multi-stop-sequence matcher with hold-back buffering.

    Guarantee (reference semantics, tokenizer.cpp:583-628, strengthened): no
    character of a stop string — or of a buffer suffix that could still grow
    into one — is ever returned by `get_delta()`. Held text is flushed as soon
    as the partial match dies; on a full match the stop string and everything
    after it are swallowed. Unlike the reference, the match is not anchored to
    the last token boundary: a stop appearing anywhere in the stream fires, so
    the `padding_left/right` junk-tolerance knobs are accepted for API
    compatibility but no longer needed.
    """

    def __init__(self, stop_token_ids: list[int], stop_pieces: list[str], padding_left: int = 0, padding_right: int = 0):
        self.stop_token_ids = list(stop_token_ids)
        self.stop_pieces = [s for s in stop_pieces if s]
        self.buffer = ""  # held-back text: longest suffix that may be a stop prefix
        self._delta: str | None = None

    def is_eos_token(self, token: int) -> bool:
        return token in self.stop_token_ids

    def append(self, token: int, piece: str | None) -> EosResult:
        self._delta = None
        if self.is_eos_token(token):
            # the stop token's own text is never user content; held text is —
            # its partial-stop suspicion died without a string match
            self._delta = self.buffer or None
            self.buffer = ""
            return EosResult.EOS
        if piece:
            self.buffer += piece
        if not self.buffer:
            return EosResult.NOT_EOS

        first = None  # earliest full stop match anywhere in held text
        for stop in self.stop_pieces:
            i = self.buffer.find(stop)
            if i >= 0 and (first is None or i < first):
                first = i
        if first is not None:
            self._delta = self.buffer[:first] or None
            self.buffer = ""
            return EosResult.EOS

        # hold the longest buffer suffix that is a proper prefix of any stop
        hold = 0
        for stop in self.stop_pieces:
            for k in range(min(len(self.buffer), len(stop) - 1), hold, -1):
                if self.buffer.endswith(stop[:k]):
                    hold = k
                    break
        if hold:
            self._delta = self.buffer[:-hold] or None
            self.buffer = self.buffer[-hold:]
            return EosResult.MAYBE_EOS
        self._delta = self.buffer
        self.buffer = ""
        return EosResult.NOT_EOS

    def get_delta(self) -> str | None:
        """Text cleared for emission by the last `append` (never a stop prefix)."""
        return self._delta

    def flush(self) -> str | None:
        """End of stream: release held text (the partial match will never complete)."""
        text, self.buffer, self._delta = self.buffer, "", None
        return text or None

    def reset(self) -> None:
        self.buffer = ""
        self._delta = None
