"""`.t` tokenizer format + byte-level BPE encode + streaming UTF-8 decode.

File schema (tokenizer.cpp:77-198): i32 magic 0x567124, i32 headerSize,
(key,value) i32 pairs per TokenizerHeaderKey (tokenizer.hpp:21-31), an
optional chat-template string, then `vocab_size` records of
{f32 score, i32 length, bytes}. Vocabulary ids below bos_id are "regular"
(byte-level BPE merge candidates); ids >= bos_id are special tokens matched
greedily as literal prefixes during encode (tokenizer.cpp:166-181).
"""

from __future__ import annotations

import codecs
import struct
from enum import IntEnum

TOKENIZER_MAGIC = 0x567124
TOKENIZER_MAGIC_OLD = 0x567123


class TokHeaderKey(IntEnum):
    VERSION = 0
    VOCAB_SIZE = 1
    MAX_TOKEN_LENGTH = 2
    BOS_ID = 3
    EOS_ID = 4
    PAD_ID = 5
    CHAT_EOS_ID = 6
    CHAT_TEMPLATE = 7
    CHAT_STOP = 8
    # dllama-tpu extension (>=100, like FloatType.BF16): byte length of an
    # i32[] payload listing the special-token ids. Only written when the set
    # differs from the layout heuristic, so typical files stay readable by the
    # reference (its reader throws on unknown keys, tokenizer.cpp:122).
    SPECIAL_IDS = 100


class Tokenizer:
    def __init__(
        self,
        vocab: list[bytes],
        scores: list[float],
        bos_id: int,
        eos_ids: list[int],
        chat_template: str | None = None,
        max_token_length: int | None = None,
        special_ids: list[int] | None = None,
    ):
        self.vocab = vocab
        self.scores = scores
        self.bos_id = bos_id
        self.eos_ids = list(eos_ids)
        self.chat_template = chat_template
        self.max_token_length = max_token_length or max((len(v) for v in vocab), default=0)
        # regular/special split (tokenizer.cpp:166-181 role).
        if special_ids is None:
            special_ids = self._heuristic_special_ids(len(vocab), bos_id, self.eos_ids)
        self._special_ids = sorted(set(special_ids))
        special = set(self._special_ids)
        self.regular_vocab_size = len(vocab) - len(special)
        self._regular_index = {v: i for i, v in enumerate(vocab) if i not in special}
        self._utf8 = codecs.getincrementaldecoder("utf-8")("replace")
        self._native = None  # lazily-built native BPE handle (utils/native.py)
        self._native_tried = False

    @staticmethod
    def _heuristic_special_ids(vocab_len: int, bos_id: int, eos_ids: list[int]) -> list[int]:
        """Layout guess for files without an explicit special set: HF/llama3
        layouts put all specials in a tail starting at bos; sentencepiece-style
        vocabs put bos/eos at the *head* with the whole merge vocabulary after
        them, so there only bos/eos are special."""
        if bos_id >= 0 and 2 * bos_id >= vocab_len:
            return list(range(bos_id, vocab_len))
        return [i for i in {bos_id, *eos_ids} if 0 <= i < vocab_len]

    # ------------------------------------------------------------------ file io

    @classmethod
    def load(cls, path: str) -> "Tokenizer":
        with open(path, "rb") as f:
            magic = struct.unpack("<i", f.read(4))[0]
            chat_template = None
            special_ids = None
            if magic == TOKENIZER_MAGIC_OLD:
                vocab_size, max_token_length, bos_id, eos_id, _pad = struct.unpack(
                    "<IIiii", f.read(20)
                )
                eos_ids = [eos_id]
            elif magic == TOKENIZER_MAGIC:
                header_size = struct.unpack("<i", f.read(4))[0]
                n_kv = (header_size - 8) // 4 // 2
                version = -1
                vocab_size = max_token_length = 0
                bos_id = -1
                eos_ids = []
                # read the whole kv block first (like tokenizer.cpp:104-107);
                # string payloads (CHAT_STOP, CHAT_TEMPLATE) follow the block
                # and are skipped/read in key order afterwards.
                kv = [struct.unpack("<ii", f.read(8)) for _ in range(n_kv)]
                payloads = []  # (key, byte_len) in kv order — read after the block
                for key, value in kv:
                    if key == TokHeaderKey.VERSION:
                        version = value
                    elif key == TokHeaderKey.VOCAB_SIZE:
                        vocab_size = value
                    elif key == TokHeaderKey.MAX_TOKEN_LENGTH:
                        max_token_length = value
                    elif key == TokHeaderKey.BOS_ID:
                        bos_id = value
                    elif key in (TokHeaderKey.EOS_ID, TokHeaderKey.CHAT_EOS_ID):
                        eos_ids.append(value)
                    elif key in (TokHeaderKey.CHAT_TEMPLATE, TokHeaderKey.CHAT_STOP,
                                 TokHeaderKey.SPECIAL_IDS):
                        payloads.append((key, value))
                    elif key == TokHeaderKey.PAD_ID:
                        pass
                    else:
                        raise ValueError(f"invalid tokenizer header key: {key}")
                if version != 1:
                    raise ValueError("old tokenizer version, please regenerate your tokenizer")
                for key, nbytes in payloads:
                    if key == TokHeaderKey.CHAT_TEMPLATE and nbytes > 0:
                        chat_template = f.read(nbytes).decode("utf-8")
                    elif key == TokHeaderKey.SPECIAL_IDS:
                        special_ids = list(struct.unpack(f"<{nbytes // 4}i", f.read(nbytes)))
                    else:  # CHAT_STOP: legacy; ignored (tokenizer.cpp:121)
                        f.seek(nbytes, 1)
            else:
                raise ValueError("invalid tokenizer file")

            vocab, scores = [], []
            for _ in range(vocab_size):
                score = struct.unpack("<f", f.read(4))[0]
                length = struct.unpack("<i", f.read(4))[0]
                vocab.append(f.read(length))
                scores.append(score)
        return cls(vocab, scores, bos_id, eos_ids, chat_template, max_token_length,
                   special_ids=special_ids)

    def save(self, path: str) -> None:
        """Write the v1 `.t` format (tokenizer-writer.py equivalent)."""
        kv = [
            (TokHeaderKey.VERSION, 1),
            (TokHeaderKey.VOCAB_SIZE, len(self.vocab)),
            (TokHeaderKey.MAX_TOKEN_LENGTH, self.max_token_length),
            (TokHeaderKey.BOS_ID, self.bos_id),
        ]
        if self.eos_ids:
            kv.append((TokHeaderKey.EOS_ID, self.eos_ids[0]))
        for extra in self.eos_ids[1:]:
            kv.append((TokHeaderKey.CHAT_EOS_ID, extra))
        template = self.chat_template.encode("utf-8") if self.chat_template else b""
        if template:
            kv.append((TokHeaderKey.CHAT_TEMPLATE, len(template)))
        specials = b""
        if self._special_ids != sorted(
            set(self._heuristic_special_ids(len(self.vocab), self.bos_id, self.eos_ids))
        ):
            # the load() heuristic would mis-derive the set — persist it
            specials = struct.pack(f"<{len(self._special_ids)}i", *self._special_ids)
            kv.append((TokHeaderKey.SPECIAL_IDS, len(specials)))
        with open(path, "wb") as f:
            f.write(struct.pack("<ii", TOKENIZER_MAGIC, 8 + len(kv) * 8))
            for k, v in kv:
                f.write(struct.pack("<ii", int(k), int(v)))
            f.write(template)
            f.write(specials)
            for score, piece in zip(self.scores, self.vocab):
                f.write(struct.pack("<fi", score, len(piece)))
                f.write(piece)

    # ------------------------------------------------------------------ encode

    def is_eos(self, token: int) -> bool:
        return token in self.eos_ids

    def _find_special_prefix(self, data: bytes, start: int) -> int:
        for tid in self._special_ids:
            piece = self.vocab[tid]
            if piece and data.startswith(piece, start):
                return tid
        return -1

    def _native_bpe(self):
        if not self._native_tried:
            self._native_tried = True
            from dllama_tpu.utils import native

            if native.available():
                self._native = native.NativeBpe(self.vocab, self.scores, self._special_ids)
        return self._native

    def encode(self, text: str | bytes, add_bos: bool = True, add_special_tokens: bool = True) -> list[int]:
        """Byte-level BPE (tokenizer.cpp:265-330): greedy special-token scan,
        byte-accumulation to seed tokens, then iterative best-scoring pair
        merges until no mergeable pair remains. The hot loop runs in C++ when
        the native library is available (identical semantics, tests pin it)."""
        data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
        bos = [self.bos_id] if add_bos and self.bos_id >= 0 else []
        nat = self._native_bpe()
        if nat is not None:
            ids = nat.encode(data, add_special_tokens)
            if ids is None:
                raise ValueError("cannot tokenize byte sequence (not in vocab)")
            return bos + ids
        tokens: list[int] = []
        i = 0
        buf = b""
        while i < len(data):
            if add_special_tokens and not buf:
                tid = self._find_special_prefix(data, i)
                if tid >= 0:
                    tokens.append(tid)
                    i += len(self.vocab[tid])
                    continue
            buf += data[i : i + 1]
            i += 1
            tid = self._regular_index.get(buf)
            if tid is not None:
                tokens.append(tid)
                buf = b""
        if buf:
            raise ValueError(f"cannot tokenize byte sequence {buf!r} (not in vocab)")

        while True:
            best_score, best_id, best_idx = -1e10, -1, -1
            for j in range(len(tokens) - 1):
                merged = self.vocab[tokens[j]] + self.vocab[tokens[j + 1]]
                tid = self._regular_index.get(merged)
                if tid is not None and self.scores[tid] > best_score:
                    best_score, best_id, best_idx = self.scores[tid], tid, j
            if best_idx == -1:
                break
            tokens[best_idx : best_idx + 2] = [best_id]
        return bos + tokens

    # ------------------------------------------------------------------ decode

    def make_stream_decoder(self) -> "StreamDecoder":
        """A decoder with its own UTF-8 state — one per concurrent request
        (the Tokenizer's built-in decode() state is single-stream)."""
        return StreamDecoder(self)

    def reset_decoder(self) -> None:
        self._utf8.reset()

    def decode(self, token: int) -> str | None:
        """Streaming decode (tokenizer.cpp:240-263 role): emits text as soon as
        it forms complete UTF-8, buffering partial sequences across tokens.
        (The reference's heuristic only buffers pieces *ending* in continuation
        bytes; an incremental decoder handles every split point.)"""
        return _decode_streaming(self, self._utf8, token)

    def decode_all(self, tokens: list[int]) -> str:
        self.reset_decoder()
        parts = [self.decode(t) for t in tokens]
        rest = self._utf8.decode(b"", final=True)
        self.reset_decoder()
        return "".join(p for p in parts if p) + rest

    def piece(self, token: int) -> str:
        return self.vocab[token].decode("utf-8", errors="replace")


def _decode_streaming(tok: Tokenizer, utf8, token: int) -> str | None:
    if token == tok.bos_id:
        return None
    if tok.is_eos(token):
        rest = utf8.decode(b"", final=True)
        utf8.reset()
        return rest or None
    out = utf8.decode(tok.vocab[token])
    return out or None


class StreamDecoder:
    """Per-stream incremental UTF-8 decode state over a shared Tokenizer."""

    def __init__(self, tok: Tokenizer):
        self._tok = tok
        self._utf8 = codecs.getincrementaldecoder("utf-8")("replace")

    def decode(self, token: int) -> str | None:
        return _decode_streaming(self._tok, self._utf8, token)

    def reset(self) -> None:
        self._utf8.reset()
