"""Per-engine kernel selection — the ONE place the (kernels flag, attn_impl,
shardings, platform) tuple turns into concrete matmul/attention callables.

InferenceEngine and BatchEngine both construct their compiled steps from this
resolution, so the gating rules (sharded => shard_map'd Pallas or XLA, flash
only where pallas_call can lower, interpret off-TPU) can never diverge
between the latency and serving tiers.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Callable

import jax

from dllama_tpu.models.config import LlamaConfig


#: Paged-layout attention routes (documented in the README "Paged KV cache"
#: routing table — scripts/checks.sh asserts the two stay in sync):
#: ``paged_kernel`` = the any-page-size Pallas flash-decode kernel with the
#: fused KV scatter (ops/pallas/paged_attention), ``paged_gather`` = the jnp
#: block-table gather fallback (ops/layers.paged_gqa_attention).
PAGED_ROUTES = ("paged_kernel", "paged_gather")


def pow2_buckets(cap: int) -> tuple[int, ...]:
    """The bounded pow2 shape universe ``engine.pow2_chunk`` can emit under
    ``cap`` — (1, 2, 4, ..., <=cap). This is THE bucket enumeration behind
    the compile ledger's shape contract (obs/compile): prefill chunks,
    hybrid budget slices, and the warmup precompile worklist all quantize
    to exactly this set, which is what makes the compiled-shape universe
    declarable (and its violations detectable) in the first place."""
    vals, c = [], 1
    while c <= max(1, int(cap)):
        vals.append(c)
        c *= 2
    return tuple(vals)


@dataclass
class KernelSelection:
    mm: Callable  # matmul for output-dim-sharded / replicated weights
    mm_in: Callable | None  # matmul for input-dim-sharded weights (wo/w2)
    attn_fn: Callable | None  # attention impl; None = jnp gqa_attention
    backend: str  # 'pallas' | 'xla' (what the quantized matmuls run on)
    attn_route: str = "jnp"  # which attention path attn_fn resolves to:
    # 'jnp' | 'flash' | 'sharded_flash' | 'ring' | 'paged_kernel' |
    # 'paged_gather' — the single string obs/bench/README quote for "what
    # actually runs", and what chunk_cost_model prices (kernel vs gather
    # paged bytes differ by the whole re-materialized view)
    def bucket_tag(self) -> str:
        """'backend/attn_route' — the variant tag the compile ledger's
        shape-bucket contract stamps on each declared bucket, so a
        coverage dump says WHICH compiled universe (dense vs paged, jnp vs
        flash) the buckets belong to."""
        return f"{self.backend}/{self.attn_route}"

    fused_scatter_max_t: int | None = None  # paged_kernel route only: the
    # widest chunk (query rows per slot) whose new-KV scatter stays fused
    # inside the kernel launch. A speculative verify forward is spec_k+1
    # rows wide, so engines log when their K rides the per-layer
    # pre-scatter path instead (still correct — one XLA scatter per layer
    # per cycle — just not the zero-extra-dispatch fused write)


def resolve_moe_impl(moe_impl: str, shardings=None) -> str:
    """MoE compute-scheme resolution shared by both engines. On an
    expert-parallel mesh (ep > 1) the 'sort' scheme is OFF the table:
    jax.lax.ragged_dot has no correct GSPMD partitioning over a sharded
    group (expert) axis on this backend — the partitioned lowering drifts
    far beyond accumulation noise (~3e-2 on a 64-dim toy). The ep layout
    was designed for the dense all-experts einsum (parallel/sharding.py:
    "the all-experts einsum psums over ep under GSPMD"), so 'auto'
    resolves to 'dense' there and an explicit 'sort' is rejected loudly
    instead of serving wrong numerics."""
    ep = shardings.mesh.shape.get("ep", 1) if shardings is not None else 1
    if ep > 1:
        if moe_impl == "sort":
            raise ValueError(
                "moe_impl='sort' is unsupported on ep>1 meshes: ragged_dot "
                "partitions incorrectly over a sharded expert axis; use "
                "'dense' (exact) or 'dispatch'")
        if moe_impl == "auto":
            return "dense"
    return moe_impl


def resolve_kernels(
    cfg: LlamaConfig,
    seq_len: int,
    batch: int,
    kernels: str = "auto",  # 'auto' | 'pallas' | 'xla'
    attn_impl: str = "auto",  # 'auto' | 'jnp' | 'flash'
    shardings=None,
    paged: bool = False,  # paged KV layout: route the paged attention path
    page_size: int = 0,
    cache_dtype=None,  # KV pool element type (paged capability check);
    # None = bf16, the serving default
) -> KernelSelection:
    """Resolution rules:

    * unsharded on TPU (or kernels='pallas' anywhere): fused Pallas kernels,
      flash attention; off-TPU they run in interpret mode.
    * tp/dp mesh, auto-on-TPU or forced pallas: shard_map'd Pallas
      (parallel/sharding.pallas_mms + pallas_attn) — each chip runs the fused
      kernel on its local shard; wo/w2 partials psum over ICI.
    * any other sharded case: XLA path — pallas_call has no GSPMD
      partitioning rule, so outside shard_map it would gather sharded
      operands per call (VERDICT r2 weak #1 / ADVICE r1).
    * sp meshes keep their ring-attention shard_map (shardings.attn_fn).
    """
    from dllama_tpu.ops.matmul import engine_matmul

    mm = engine_matmul(kernels, shardings)
    backend = mm.keywords["backend"]
    mm_in = None
    on_tpu = jax.devices()[0].platform == "tpu"

    sharded_pallas = (
        shardings is not None
        and shardings.supports_sharded_pallas()
        and (kernels == "pallas" or (kernels == "auto" and on_tpu))
    )
    if sharded_pallas:
        mm, mm_in = shardings.pallas_mms(batch)
        backend = "pallas"

    if paged and shardings is None:
        # paged KV cache (BatchEngine --kv-layout paged; unsharded only — the
        # page pool has no slot axis for a dp mesh to shard, and BatchEngine
        # rejects paged+mesh at construction; a sharded resolve_kernels call
        # falls through to the dense rules below as defense in depth).
        # attn_fn=None means models.llama.forward defaults to the jnp gather
        # fallback (ops.layers.paged_gqa_attention), valid everywhere but
        # re-materializing the whole paged view through XLA each step; the
        # general flash-decode kernel (scalar-prefetched block tables,
        # double-buffered page DMA, fused KV scatter) routes on an explicit
        # CAPABILITY check — dtype/head-dim/page-geometry, ANY page size —
        # not the old whole-64-row-tile gate.
        from dllama_tpu.ops.pallas.paged_attention import (
            FUSED_SCATTER_MAX_T,
            paged_decode_attention,
            paged_decode_supported,
        )

        import jax.numpy as jnp

        attn_fn = None
        route = "paged_gather"
        fused_cap = None
        if attn_impl != "jnp" and paged_decode_supported(
            (cfg.n_heads, cfg.head_size), page_size,
            kv_dtype=cache_dtype if cache_dtype is not None else jnp.bfloat16,
        ) and (attn_impl == "flash" or on_tpu):
            interp = not on_tpu

            def attn_fn(q, k_pool, v_pool, tables, pos, new_k, new_v, active):
                return paged_decode_attention(
                    q, k_pool, v_pool, tables, pos, new_k, new_v, active,
                    interpret=interp)

            # models/llama._layer hands the new KV rows to the kernel
            # instead of paying a separate scatter dispatch per layer; the
            # fused write serves chunks up to FUSED_SCATTER_MAX_T rows —
            # decode (t=1) and spec verify (t=spec_k+1) both ride it as
            # long as spec_k+1 fits (wider verifies pre-scatter via XLA,
            # identical results)
            attn_fn.fused_kv_scatter = True
            route = "paged_kernel"
            fused_cap = FUSED_SCATTER_MAX_T
        return KernelSelection(mm=mm, mm_in=mm_in, attn_fn=attn_fn,
                               backend=backend, attn_route=route,
                               fused_scatter_max_t=fused_cap)

    attn_fn = shardings.attn_fn(batch) if shardings is not None else None
    route = "ring" if attn_fn is not None else "jnp"
    if attn_fn is None and attn_impl != "jnp":
        from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention, supported

        if supported((cfg.n_heads, cfg.head_size), seq_len):
            if sharded_pallas:
                attn_fn = shardings.pallas_attn(batch, interpret=not on_tpu)
                route = "sharded_flash"
            elif attn_impl == "flash" or (on_tpu and shardings is None):
                route = "flash"
                attn_fn = partial(
                    flash_gqa_attention, interpret=not on_tpu,
                    # kv grids bucketed by live-context length — decode steps
                    # and early prefill chunks alike. RECORDED REASON this
                    # stays opt-in (VERDICT r4 next #8): exactness is tested
                    # and the lax.switch is AOT-accepted, but the flip
                    # criterion is a MEASURED shallow-pos win at S=8192 with
                    # no deep-pos regression (PLAYBOOK "Bucketed flash grid";
                    # decide.py prints FLIP/keep from the kbench depth sweep
                    # + the bench 8b_long A/B) — and no TPU window has ever
                    # produced those timings. CPU-smoke numbers showed 3.4x
                    # at pos=8 but CPU interpret timings don't transfer.
                    s_buckets=os.environ.get("DLLAMA_FLASH_BUCKETS") == "1")

    return KernelSelection(mm=mm, mm_in=mm_in, attn_fn=attn_fn,
                           backend=backend, attn_route=route)
