"""Inference engine: compiled prefill/decode steps + host-side driver.

Replaces the reference's executor/step-list machinery and RootLlmInference
driver (nn-executor.cpp, app.cpp:131-195): XLA *is* the executor here — one
jitted step function with a donated KV cache, driven by a host loop. The
reference's per-forward control packet broadcast (app.cpp:161-173) has no
analog: a pjit'd step over a mesh launches on all chips from one host call.

Prefill is chunked in power-of-two widths so a prompt of any length compiles
at most log2(max_chunk)+1 step variants (the reference instead fixes
nBatches=32 and pads the final chunk; we never compute padded positions).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.engine.sampling import Sampler
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, forward
from dllama_tpu.obs import compile as compile_obs
from dllama_tpu.obs import instruments as ins
from dllama_tpu.ops.layers import build_rope_cache


def pow2_chunk(remaining: int, max_chunk: int) -> int:
    """Largest power-of-two width <= min(max_chunk, remaining): prompts of
    any length compile at most log2(max_chunk)+1 prefill step variants
    (shared by InferenceEngine.prefill and BatchEngine.add_step)."""
    c = min(max_chunk, 1 << (remaining - 1).bit_length())
    while c > remaining:
        c //= 2
    return c


@dataclass
class GenerationStats:
    """Per-token timing in the reference's report shape (dllama.cpp:93-104)."""

    prefill_tokens: int = 0
    prefill_s: float = 0.0
    decode_tokens: int = 0
    decode_s: float = 0.0

    @property
    def prefill_tok_s(self) -> float:
        return self.prefill_tokens / self.prefill_s if self.prefill_s else 0.0

    @property
    def decode_tok_s(self) -> float:
        return self.decode_tokens / self.decode_s if self.decode_s else 0.0

    def summary(self) -> str:
        return (
            f"Prefill: {self.prefill_tokens} tokens in {self.prefill_s*1000:.0f} ms "
            f"({self.prefill_tok_s:.1f} tok/s)\n"
            f"Decode:  {self.decode_tokens} tokens in {self.decode_s*1000:.0f} ms "
            f"({self.decode_tok_s:.1f} tok/s, {1000*self.decode_s/max(1,self.decode_tokens):.2f} ms/token)"
        )


class InferenceEngine:
    """Owns params + KV cache + compiled steps for one model replica.

    `shardings` (optional, from parallel/sharding.py) carries the mesh and the
    in/out shardings for the step function; without it everything runs on the
    default device (single chip — the reference's `--workers`-less mode).
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        params,
        batch: int = 1,
        cache_dtype=jnp.bfloat16,
        max_seq_len: int | None = None,
        max_prefill_chunk: int = 256,
        shardings=None,
        donate_cache: bool = True,
        attn_impl: str = "auto",  # 'auto' | 'jnp' | 'flash' (Pallas online-softmax)
        layer_unroll: int | bool = 1,  # lax.scan unroll over layers
        sync: str = "bf16",  # 'bf16' (exact, default) | 'q80' (quantized
        # exchange) | 'auto' (the data-earned policy: q80 iff tp=2 —
        # parallel/collectives.resolve_sync has the numbers)
        kernels: str = "auto",  # 'auto' | 'pallas' | 'xla' matmul backend
        moe_impl: str = "auto",  # 'auto' | 'dispatch' | 'sort' | 'dense' (ops.layers.moe_ffn)
        pp_micro: int = 1,  # GPipe microbatches on pp meshes (batch % pp_micro == 0)
        fuse_weights: bool = False,  # wqkv/w13 fused launches (unsharded only;
        # concatenates copies on device — caller keeps the originals alive)
    ):
        self.cfg = cfg
        self.params = params
        if fuse_weights:
            if shardings is not None:
                raise ValueError("fuse_weights requires an unsharded engine "
                                 "(tp shards q and kv blocks at different granularity)")
            from dllama_tpu.models.llama import fuse_layer_weights

            # session fingerprint must hash the CALLER's layout — a session
            # saved unfused must resume on a fused engine and vice versa
            self._params_digest()
            self.params = dict(params, layers=fuse_layer_weights(params["layers"]))
        self.batch = batch
        self.seq_len = min(max_seq_len or cfg.seq_len, cfg.seq_len)
        self.max_prefill_chunk = max_prefill_chunk
        self.shardings = shardings
        self.rope_cache = build_rope_cache(cfg, self.seq_len)
        self.cache = KVCache.create(cfg, batch, cache_dtype, self.seq_len)
        self.pos = 0

        if shardings is not None:
            self.params = shardings.put_params(self.params)
            self.cache = shardings.put_cache(self.cache)
            self.rope_cache = shardings.put_replicated(self.rope_cache)

        # matmul + attention kernels resolved ONCE at construction (per-engine,
        # not a process-global read at trace time); gating rules shared with
        # BatchEngine via engine/kernel_select.py.
        from dllama_tpu.engine.kernel_select import (
            resolve_kernels,
            resolve_moe_impl,
        )

        moe_impl = resolve_moe_impl(moe_impl, shardings)
        sel = resolve_kernels(cfg, self.seq_len, batch, kernels, attn_impl, shardings)
        mm, mm_in, attn_fn = sel.mm, sel.mm_in, sel.attn_fn
        self.backend = sel.backend
        from dllama_tpu.parallel.collectives import resolve_sync

        self.sync = sync = resolve_sync(sync, shardings)
        col_fn = None
        if sync == "q80":
            # the reference's Q80 ZQ-pipe exchange as an ICI option: wo/w2
            # partial sums ride quantized (parallel/collectives.py). Only
            # meaningful with a tp axis; silently native otherwise.
            if shardings is not None and shardings.mesh.shape["tp"] > 1:
                from dllama_tpu.parallel.collectives import make_q80_col_matmul

                col_fn = make_q80_col_matmul(shardings.mesh)

        if shardings is not None and shardings.mesh.shape["pp"] > 1:
            # stage-split forward: GPipe shard_map over 'pp' (manual axis),
            # tp/dp composed by GSPMD inside each stage (parallel/pipeline.py).
            # pp_micro > 1 splits the batch into GPipe microbatches so prefill
            # and batched decode fill the pipeline bubble (B=1 decode keeps
            # pp_micro=1: pure sequential layer split). layer_unroll does not
            # apply (the stage schedule replaces the layer scan).
            if col_fn is not None:
                raise ValueError("--sync q80 is not supported on pp meshes yet")
            if pp_micro < 1 or batch % pp_micro != 0:
                raise ValueError(
                    f"pp_micro must be >= 1 and divide batch; got pp_micro={pp_micro} "
                    f"batch={batch}"
                )
            from dllama_tpu.parallel.pipeline import make_pp_forward

            pp_fwd = make_pp_forward(cfg, shardings.mesh, n_micro=pp_micro,
                                     attn_fn=attn_fn, mm=mm)

            def fwd(params, cache, tokens, pos, rope_cache, last_only=False):
                # pp computes all positions (stage schedule); callers slice
                logits, cache = pp_fwd(params, tokens, pos, cache, rope_cache)
                return (logits[:, -1:] if last_only else logits), cache
        else:
            def fwd(params, cache, tokens, pos, rope_cache, last_only=False):
                return forward(cfg, params, tokens, pos, cache, rope_cache, attn_fn,
                               unroll=layer_unroll, col_fn=col_fn, mm=mm, mm_in=mm_in,
                               moe_impl=moe_impl, last_only=last_only)

        donate = (1,) if donate_cache else ()
        self._donate_cache = donate_cache
        self._fwd = fwd  # speculative decoder builds on the same closure
        self._spec_decoders: dict = {}
        self._spec_h = None  # (device h, pos, cur): chunked-call history reuse
        self._step = jax.jit(partial(self._step_impl, fwd), donate_argnums=donate)
        self._decode_n = jax.jit(
            partial(self._decode_n_impl, fwd),
            static_argnums=(5,),
            donate_argnums=donate,
        )
        self._decode_sample_n = jax.jit(
            partial(self._decode_sample_n_impl, fwd),
            static_argnums=(6,),
            donate_argnums=donate,
        )
        self._decode_penalized_n = jax.jit(
            partial(self._decode_penalized_n_impl, fwd),
            static_argnums=(6,),
            donate_argnums=donate,
        )

    @staticmethod
    def _step_impl(fwd, params, cache, tokens, pos, rope_cache):
        logits, cache = fwd(params, cache, tokens, pos, rope_cache, last_only=True)
        return logits[:, -1], cache

    @staticmethod
    def _decode_n_impl(fwd, params, cache, token, pos, rope_cache, n):
        """n greedy decode steps fused into one device program (lax.scan) —
        no host roundtrip per token. The whole reference decode loop
        (dllama.cpp:69-88: control packet + forward + sample per token)
        collapses into a single XLA while-loop on chip."""

        def body(carry, _):
            token, cache, p = carry
            logits, cache = fwd(params, cache, token, p, rope_cache, last_only=True)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
            return (nxt, cache, p + 1), nxt[:, 0]

        (_, cache, _), toks = jax.lax.scan(body, (token, cache, pos), None, length=n)
        return toks, cache

    @staticmethod
    def _decode_sample_n_impl(fwd, params, cache, token, pos, rope_cache,
                              key, n, temperature, topp):
        """n *sampled* decode steps fused on device — the sampler runs inside
        the scan (branchless in temperature/topp, sampling.sample_logits), so
        non-greedy generation also avoids the per-token host roundtrip the
        reference's decode loop pays (dllama.cpp:69-88)."""
        from dllama_tpu.engine.sampling import sample_logits

        def body(carry, _):
            token, cache, p, key = carry
            logits, cache = fwd(params, cache, token, p, rope_cache, last_only=True)
            key, sub = jax.random.split(key)
            nxt = sample_logits(logits[:, -1], sub, temperature, topp)[:, None]
            return (nxt, cache, p + 1, key), nxt[:, 0]

        (_, cache, _, _), toks = jax.lax.scan(body, (token, cache, pos, key), None, length=n)
        return toks, cache

    @staticmethod
    def _decode_penalized_n_impl(fwd, params, cache, token, pos, rope_cache,
                                 key, n, temperature, topp, counts,
                                 presence, frequency):
        """The sampled scan with OpenAI-style repetition penalties: token
        occurrence counts ride the scan carry (each fed token is counted
        before its successor is sampled), so penalized generation keeps the
        one-host-roundtrip-per-chunk property. Separate jit from the
        penalty-free scan — requests without penalties pay zero extra."""
        from dllama_tpu.engine.sampling import apply_penalties, sample_logits

        def body(carry, _):
            token, cache, p, key, counts = carry
            counts = counts.at[jnp.arange(counts.shape[0]), token[:, 0]].add(1)
            logits, cache = fwd(params, cache, token, p, rope_cache, last_only=True)
            key, sub = jax.random.split(key)
            pen = apply_penalties(logits[:, -1], counts, presence, frequency)
            nxt = sample_logits(pen, sub, temperature, topp)[:, None]
            return (nxt, cache, p + 1, key, counts), nxt[:, 0]

        (_, cache, _, _, _), toks = jax.lax.scan(
            body, (token, cache, pos, key, counts), None, length=n)
        return toks, cache

    # ------------------------------------------------------------------ core

    def step(self, tokens: np.ndarray) -> jax.Array:
        """Run T tokens at the current position; returns last-pos logits [B, V]."""
        t = tokens.shape[1]
        if self.pos + t > self.seq_len:
            raise ValueError(f"position {self.pos}+{t} exceeds seq_len {self.seq_len}")
        # compile attribution (ISSUE 14): the single-engine tier's jit
        # dispatches are ledger-scoped like the batched tier's, so its
        # compiles land under labeled fns instead of "untracked"
        toks_dev = jnp.asarray(tokens, jnp.int32)
        with compile_obs.LEDGER.scope(
                "single_step", f"m{t}",
                sig=lambda: compile_obs.sig_of(toks_dev)):
            logits, self.cache = self._step(
                self.params, self.cache, toks_dev, jnp.int32(self.pos),
                self.rope_cache
            )
        self.pos += t
        return logits

    def reset(self, pos: int = 0) -> None:
        """Rewind to `pos` (prefix-cache reuse keeps cache contents ≤ pos valid)."""
        self.pos = pos

    def measured_collective_report(self) -> dict:
        """Collective bytes MEASURED from the compiled decode step's HLO (the
        ops XLA actually emitted after SPMD partitioning), vs the analytic
        model in utils.profiling.collective_bytes_per_token. Collectives
        inside the layer scan are counted once per loop trip — construct the
        engine with layer_unroll=True for exact per-token totals.

        Costs one extra AOT compile of the T=1 step on first call (lower().
        compile() does not reuse the jit executable cache); memoized after."""
        if not hasattr(self, "_collective_report"):
            from dllama_tpu.utils.profiling import measured_collective_bytes

            tokens = jnp.zeros((self.batch, 1), jnp.int32)
            lowered = self._step.lower(
                self.params, self.cache, tokens, jnp.int32(0), self.rope_cache
            )
            self._collective_report = measured_collective_bytes(
                lowered.compile().as_text()
            )
        return self._collective_report

    # ------------------------------------------------------------- checkpoint

    def _session_fingerprint(self) -> str:
        c = self.cfg
        return (
            f"{c.dim}:{c.n_layers}:{c.n_kv_heads}:{c.head_size}:"
            f"{self.seq_len}:{self.batch}:{self.cache.k.dtype}:{self._params_digest()}"
        )

    def _params_digest(self) -> str:
        """Cheap weight-identity hash so a session saved against one checkpoint
        refuses to resume on a different model with the same geometry (ADVICE
        r1): leaf shapes/dtypes plus a few sampled values from each of up to 8
        leaves — O(bytes of a handful of scalars), not a full-weights hash."""
        if not hasattr(self, "_digest"):
            import hashlib

            h = hashlib.sha1()
            leaves = jax.tree.leaves(self.params)
            for leaf in leaves:
                h.update(f"{getattr(leaf, 'shape', ())}{getattr(leaf, 'dtype', '')}".encode())
            step = max(1, len(leaves) // 8)
            for leaf in leaves[::step]:
                sample = np.asarray(jax.device_get(jnp.ravel(leaf)[:4]))
                h.update(sample.tobytes())
            self._digest = h.hexdigest()[:16]
        return self._digest

    def save_session(self, path: str) -> None:
        """Persist the KV cache + position — resume a long conversation across
        process restarts. The reference has no checkpointing at all (SURVEY.md
        §5.4: its NaiveCache prefix reuse is in-memory only); this is the
        durable version of that capability."""
        import numpy as np

        k = np.asarray(self.cache.k)
        v = np.asarray(self.cache.v)
        # npz cannot represent ml_dtypes elements (an f8 cache loads back as
        # raw void): persist the BYTES plus the dtype name and re-view on load
        np.savez_compressed(
            path,
            fingerprint=self._session_fingerprint(),
            cache_dtype=str(k.dtype),
            pos=self.pos,
            k=k.view(np.uint8),
            v=v.view(np.uint8),
        )

    def load_session(self, path: str) -> None:
        """Restore a saved session (re-places the cache with the current mesh
        shardings, so a session saved single-chip resumes on a mesh and vice
        versa — device placement is orthogonal to the session state)."""
        import numpy as np

        with np.load(path) as data:
            fp = str(data["fingerprint"])
            if fp != self._session_fingerprint():
                raise ValueError(
                    f"session file does not match this engine: {fp!r} != "
                    f"{self._session_fingerprint()!r}"
                )
            if "cache_dtype" in data:  # bytes + dtype-name format
                dt = jnp.dtype(str(data["cache_dtype"]))
                k = data["k"].view(dt)
                v = data["v"].view(dt)
            else:
                # legacy format stored typed arrays directly; npz turns
                # ml_dtypes elements (bf16) into raw void — re-view them as
                # the engine dtype (the fingerprint already pinned it)
                k, v = data["k"], data["v"]
                if k.dtype.kind == "V":
                    dt = self.cache.k.dtype
                    k = k.view(np.uint8).view(dt).reshape(self.cache.k.shape)
                    v = v.view(np.uint8).view(dt).reshape(self.cache.v.shape)
            cache = KVCache(jnp.asarray(k), jnp.asarray(v))
            if self.shardings is not None:
                cache = self.shardings.put_cache(cache)
            self.cache = cache
            self.pos = int(data["pos"])

    def prefill(self, tokens: np.ndarray) -> jax.Array:
        """Chunked prefill; returns logits after the last token."""
        tokens = np.atleast_2d(np.asarray(tokens, dtype=np.int32))
        n = tokens.shape[1]
        if n == 0:
            raise ValueError("prompt must be non-empty")
        logits = None
        off = 0
        while off < n:
            chunk = pow2_chunk(n - off, self.max_prefill_chunk)
            logits = self.step(tokens[:, off : off + chunk])
            off += chunk
        return logits

    def decode_step(self, tokens: np.ndarray) -> jax.Array:
        return self.step(np.asarray(tokens, dtype=np.int32).reshape(self.batch, 1))

    def decode_greedy_n(self, token: np.ndarray, n: int) -> np.ndarray:
        """Fused n-step greedy decode on device; returns tokens [n, B]."""
        if self.pos + n > self.seq_len:
            raise ValueError(f"position {self.pos}+{n} exceeds seq_len {self.seq_len}")
        tok_dev = jnp.asarray(token, jnp.int32).reshape(self.batch, 1)
        with compile_obs.LEDGER.scope(
                "single_decode", f"n{n}",
                sig=lambda: compile_obs.sig_of(tok_dev)):
            toks, self.cache = self._decode_n(
                self.params,
                self.cache,
                tok_dev,
                jnp.int32(self.pos),
                self.rope_cache,
                n,
            )
        self.pos += n
        return np.asarray(toks)

    def decode_spec_greedy_n(self, history, token: int, n: int, k: int = 8,
                             ngram: int = 2) -> np.ndarray:
        """n exact-greedy tokens via prompt-lookup speculative decoding
        (engine/speculative.py): up to k tokens drafted from the sequence's
        own n-gram statistics are verified per forward, so repetitive text
        decodes several tokens per weight sweep. Output is bit-identical to
        decode_greedy_n; only the forward count changes.

        ``history``: the tokens already FED, MOST RECENT last — the full
        prompt+continuation, or any suffix of it (a chat turn's delta: tokens
        at earlier positions are marked unknown and simply can't be drafted
        from). ``token``: the last sampled, not-yet-fed token. B=1 engines
        only. self._spec_stats records {emitted, cycles} of the last call
        (emitted/cycles = realized speedup). Consecutive calls that continue
        exactly where the last one stopped reuse the on-device history — no
        per-chunk host rebuild (generate's chunked loop hits this path)."""
        if self.batch != 1:
            # a clean, actionable error instead of the old bare assert: the
            # batched serving tier has its own speculation (per-slot
            # accept/reject vectors, per-request spec_k) — point there
            raise ValueError(
                f"decode_spec_greedy_n drives a single sequence (batch==1, "
                f"got batch={self.batch}); for batched speculation use "
                "BatchEngine(spec=K) — its spec cycles serve every slot "
                "with per-request spec_k (serve --spec-k / body spec_k)")
        if self.pos + n > self.seq_len:
            raise ValueError(f"position {self.pos}+{n} exceeds seq_len {self.seq_len}")
        key = (k, ngram)
        if key not in self._spec_decoders:
            from dllama_tpu.engine.speculative import make_spec_decode

            self._spec_decoders[key] = make_spec_decode(
                self._fwd, self.seq_len, k, ngram, donate=self._donate_cache
            )
        cached = self._spec_h
        if cached is not None and cached[1] == self.pos and cached[2] == token:
            h = cached[0]  # continue the device-resident history
        else:
            hist = np.asarray(history, np.int32).reshape(-1)
            if hist.shape[0] > self.pos:
                raise ValueError(f"history length {hist.shape[0]} > pos {self.pos}")
            # unknown earlier positions hold -1: no real token id equals -1,
            # so the n-gram matcher can never draft across the unknown region
            h = np.full(self.seq_len + 1, -1, np.int32)
            h[self.pos - hist.shape[0] : self.pos] = hist
            h[self.pos] = token
            h = jnp.asarray(h)
        with compile_obs.LEDGER.scope(
                "single_spec", f"n{n}",
                sig=lambda: compile_obs.sig_of(h)):
            out, cnt, cyc, self.cache, h_out, pos = self._spec_decoders[key](
                self.params, self.cache, h, jnp.int32(token),
                jnp.int32(self.pos), self.rope_cache, n,
            )
        cnt = int(cnt)
        m = min(n, cnt)
        toks = np.asarray(out)[:m]
        # overshoot rewind: emitted tokens beyond n were fed rows we do not
        # keep (same stale-row invariant as generate's mid-chunk rewind).
        # h_out stays valid for the rewound position: index pos+m holds
        # out[m-1], the new unfed token.
        self.pos = int(pos) - (cnt - m)
        self._spec_stats = {"emitted": cnt, "cycles": int(cyc)}
        self._spec_h = (h_out, self.pos, int(toks[-1])) if m else None
        return toks

    def decode_sample_n(self, token: np.ndarray, n: int, sampler: Sampler,
                        counts: np.ndarray | None = None) -> np.ndarray:
        """Fused n-step sampled decode on device; returns tokens [n, B].
        Advances the sampler's PRNG key once per call. ``counts`` ([B, V]
        occurrence counts of the text so far, EXCLUDING the unfed ``token`` —
        it is counted in-scan) routes through the penalized scan when the
        sampler carries presence/frequency penalties."""
        if self.pos + n > self.seq_len:
            raise ValueError(f"position {self.pos}+{n} exceeds seq_len {self.seq_len}")
        sampler.key, sub = jax.random.split(sampler.key)
        args = (
            self.params,
            self.cache,
            jnp.asarray(token, jnp.int32).reshape(self.batch, 1),
            jnp.int32(self.pos),
            self.rope_cache,
            sub,
            n,
            jnp.float32(sampler.temperature),
            jnp.float32(sampler.topp),
        )
        with compile_obs.LEDGER.scope(
                "single_decode", f"n{n}",
                sig=lambda: compile_obs.sig_of(args[2])):
            if counts is not None and sampler.has_penalties:
                toks, self.cache = self._decode_penalized_n(
                    *args,
                    jnp.asarray(counts, jnp.int32).reshape(self.batch, -1),
                    jnp.float32(sampler.presence),
                    jnp.float32(sampler.frequency))
            else:
                toks, self.cache = self._decode_sample_n(*args)
        self.pos += n
        return np.asarray(toks)

    # ------------------------------------------------------------- generation

    def generate(
        self,
        prompt_tokens: list[int],
        max_tokens: int,
        sampler: Sampler,
        stop_fn: Callable[[int], bool] | None = None,
        stats: GenerationStats | None = None,
        chunk: int = 8,
        spec: int = 0,
    ) -> Iterator[int]:
        """Host generation loop: prefill the prompt, then decode in fused
        device chunks of up to `chunk` tokens (sampling included on device —
        one host roundtrip per chunk instead of per token; chunk=1 recovers
        token-at-a-time). Yields each token id; stops at max_tokens, seq_len,
        or when `stop_fn(token)` returns True. On an early stop mid-chunk the
        engine position is rewound so the KV cache stays prefix-consistent
        (cache rows past pos are masked, so over-decoded rows are harmless).

        ``spec`` > 0 enables prompt-lookup speculative decoding with that
        draft length for GREEDY runs (temperature 0) — bit-identical output,
        fewer forwards on repetitive text (decode_spec_greedy_n); sampled
        runs ignore it.
        """
        assert self.batch == 1, "generate() drives a single sequence; use step() for batches"
        # penalized greedy is argmax of MODIFIED logits: speculative drafting
        # verifies against raw argmax, so penalties force the plain scan
        use_spec = spec > 0 and sampler.temperature == 0.0 and not sampler.has_penalties
        penalized = sampler.has_penalties
        t0 = time.perf_counter()
        logits = self.prefill(np.asarray([prompt_tokens], dtype=np.int32))
        if penalized:
            # OpenAI semantics: counts cover tokens SAMPLED in this
            # completion only — the prompt (and any KV-cached earlier turns)
            # carries no penalty, so output is independent of prefix-cache
            # state. No sampled tokens exist yet: the first token is
            # penalty-free by the same formula (all counts zero).
            v = logits.shape[-1]
            text: list[int] = []  # tokens sampled so far
        token = int(sampler(logits)[0])
        jax.block_until_ready(logits)
        t1 = time.perf_counter()
        if stats is not None:
            stats.prefill_tokens += len(prompt_tokens)
            stats.prefill_s += t1 - t0
        # registry mirror of the stats marks (one sample for the whole
        # chunked prefill — the block_until_ready above makes it device-real)
        ins.PREFILL_CHUNK_SECONDS.observe(t1 - t0)
        ins.PREFILL_TOKENS.inc(len(prompt_tokens))
        ins.TOKENS_GENERATED.inc()  # the prefill-sampled first token

        fed = list(prompt_tokens) if use_spec else None
        produced = 0
        yield token
        produced += 1
        if stop_fn is not None and stop_fn(token):
            return
        while produced < max_tokens and self.pos < self.seq_len:
            c = min(chunk, max_tokens - produced, self.seq_len - self.pos)
            start_pos = self.pos
            t2 = time.perf_counter()
            if use_spec:
                if self.pos + c + spec + 1 > self.seq_len:
                    use_spec = False  # no head-room for a draft window
                    toks = self.decode_sample_n(np.array([[token]]), c, sampler)
                else:
                    flat = self.decode_spec_greedy_n(fed, token, c, k=spec)
                    c = len(flat)
                    if c == 0:
                        break
                    fed.extend([token] + [int(t) for t in flat[:-1]])
                    toks = flat[:, None]
            elif penalized:
                # counts of the text so far EXCLUDING the unfed token (the
                # scan counts it before its successor is sampled); rebuilt
                # from host history per chunk — one [1, V] ship per chunk
                counts = np.bincount(text, minlength=v)[None, :v]
                toks = self.decode_sample_n(np.array([[token]]), c, sampler,
                                            counts=counts)
                text.append(token)
                text.extend(int(t) for t in toks[:-1, 0])
            else:
                toks = self.decode_sample_n(np.array([[token]]), c, sampler)
            if stats is not None:
                stats.decode_tokens += c
                stats.decode_s += time.perf_counter() - t2
            ins.DECODE_CHUNK_SECONDS.observe(time.perf_counter() - t2)
            for i in range(c):
                token = int(toks[i, 0])
                # counted at hand-off (the next() that returns this token):
                # after the yield it would never run for the final token of a
                # stop-terminated iteration, whose consumer breaks and leaves
                # the generator suspended
                ins.TOKENS_GENERATED.inc()
                yield token
                produced += 1
                stopped = stop_fn is not None and stop_fn(token)
                if stopped or produced >= max_tokens:
                    if i + 1 < c:
                        # rewind over-decoded rows (valid prefix ends after
                        # the row written when sampling this token)
                        self.reset(start_pos + i + 1)
                    if stopped:
                        return
                    break
