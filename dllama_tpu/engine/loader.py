"""High-level model loading: file -> sharded params -> ready InferenceEngine.

The analog of the reference's runInferenceApp bootstrap sequence
(app.cpp:197-260): header -> tokenizer -> graph -> device -> weights. The
worker-side half of that sequence (config/weight shipping over TCP,
nn-network.cpp:606-869) has no equivalent here — every weight goes straight
from the memory-mapped file to its device shard via jax.device_put.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp

from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.formats import ModelFileError, load_params, read_header
from dllama_tpu.parallel.mesh import MeshConfig, auto_mesh_config, make_mesh
from dllama_tpu.parallel.sharding import LlamaShardings
from dllama_tpu.tokenizer.tokenizer import Tokenizer

log = logging.getLogger("dllama_tpu")


@dataclasses.dataclass
class LoadedModel:
    config: LlamaConfig
    engine: InferenceEngine
    tokenizer: Tokenizer | None
    shardings: LlamaShardings | None
    sync: str = "bf16"  # tp exchange mode, forwarded to the serving tier


def build_shardings(cfg: LlamaConfig, mesh_spec: str | None) -> LlamaShardings | None:
    """mesh_spec: 'tp=4,dp=2'-style string, 'auto', or None (single device)."""
    n_dev = len(jax.devices())
    if mesh_spec is None or (mesh_spec == "auto" and n_dev == 1):
        return None
    if mesh_spec == "auto":
        mesh_cfg = auto_mesh_config(n_dev, cfg.n_kv_heads)
    else:
        mesh_cfg = MeshConfig.parse(mesh_spec)
    mesh = make_mesh(mesh_cfg)
    log.info("mesh: %s over %d devices", dict(mesh.shape), mesh_cfg.n_devices)
    return LlamaShardings(mesh, cfg)


def load_model(
    model_path: str,
    tokenizer_path: str | None = None,
    *,
    max_seq_len: int | None = None,
    mesh: str | None = "auto",
    batch: int = 1,
    cache_dtype=jnp.bfloat16,
    dequantize: bool = False,
    max_prefill_chunk: int = 256,
    sync: str = "bf16",
    kernels: str = "auto",
    moe_impl: str = "auto",
    pp_micro: int = 1,  # GPipe microbatches (library callers with batch > 1;
    # the CLI always drives batch=1, so it exposes no flag for this)
    fuse_weights: bool = False,  # wqkv/w13 fused launches (unsharded engines)
) -> LoadedModel:
    # header + size validation happens in formats (ModelFileError: path,
    # expected-vs-actual bytes, first incomplete tensor). Anything ELSE that
    # escapes the byte-level reader is re-raised with the path attached, so a
    # corrupt file never surfaces as a bare struct/mmap traceback.
    try:
        cfg, header_size = read_header(model_path, max_seq_len)
    except (ModelFileError, FileNotFoundError, IsADirectoryError):
        raise
    except (OSError, ValueError) as e:
        raise ModelFileError(f"{model_path}: unreadable .m model file: {e}") from e
    log.info("model: %s", cfg.describe())
    shardings = build_shardings(cfg, mesh)
    # shard-direct: each tensor goes memmap -> its device shards; a 70B/405B
    # model never materializes on one device (VERDICT r1 weak #2).
    put = shardings.param_put if shardings is not None else None
    params = load_params(
        model_path, cfg, header_size, dtype=jnp.bfloat16, dequantize=dequantize, put=put,
        # Q80 weights stay packed (int8 + f16 scales, fused Pallas matmuls)
        # on unsharded engines; the mesh slicers keep the dense-bf16 path
        q80_packed=shardings is None,
    )
    tokenizer = Tokenizer.load(tokenizer_path) if tokenizer_path else None
    if tokenizer is not None and tokenizer.regular_vocab_size > cfg.vocab_size:
        raise ValueError(
            f"tokenizer vocab ({len(tokenizer.vocab)}) exceeds model vocab ({cfg.vocab_size})"
        )
    engine = InferenceEngine(
        cfg,
        params,
        batch=batch,
        cache_dtype=cache_dtype,
        max_seq_len=max_seq_len,
        max_prefill_chunk=max_prefill_chunk,
        shardings=shardings,
        sync=sync,
        kernels=kernels,
        moe_impl=moe_impl,
        pp_micro=pp_micro,
        fuse_weights=fuse_weights and shardings is None,
    )
    return LoadedModel(cfg, engine, tokenizer, shardings, sync=sync)
