"""Continuous-batching engine: independent sequences sharing one compiled step.

The reference's API server is single-request, blocking (dllama-api.cpp:522-533
— SURVEY.md §7.4.6 calls this out as the tier to replace). This engine keeps
B cache *slots*, each with its own position, so requests can join (prefill one
slot while others hold), decode together in fused chunks, and leave at EOS —
the scheduling core of continuous batching. Mechanics:

* positions are an i32[B] vector: rope rows gathered per row, KV writes are
  per-row scatters, the causal mask is per-row (models/llama.forward).
* an `active` bool[B] masks cache writes: a prefill touches only the joining
  slot; finished slots stay frozen while others decode.
* sampling params are per-slot vectors (sampling.sample_logits broadcasts),
  and each slot carries its OWN PRNG key — a request's sampled continuation is
  reproducible from its seed regardless of what shares the batch.
* decode state is DEVICE-RESIDENT: the per-slot vectors above live as JAX
  arrays threaded chunk-to-chunk (numpy mirrors refresh at admission/commit/
  release boundaries), so steady-state decode pays zero host->device
  transfers, and decode_dispatch/decode_consume split a chunk into an async
  dispatch and a blocking fetch — the serving scheduler overlaps its Python
  work (emit loops, EOS checks, admission scans) with the in-flight chunk's
  device compute instead of idling the device between chunks.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.engine.engine import pow2_chunk
from dllama_tpu.engine.sampling import sample_logits
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, PagedKVCache, forward
from dllama_tpu.obs import compile as compile_obs
from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import trace
from dllama_tpu.utils import faults
from dllama_tpu.utils import locks

log = logging.getLogger("dllama_tpu.engine")


class AdmissionAborted(RuntimeError):
    """A cooperative abort fired between prefill chunks of add() — the slot
    is released-equivalent (pos unspecified); callers must not reuse its
    cached rows."""


class PageExhausted(RuntimeError):
    """The paged KV pool cannot cover a requested allocation. The serving
    scheduler never lets this surface (it checks admission_deficit() and
    defers/evicts first); direct library callers of add() see it when their
    pool is undersized for the prompt."""


class PoolAuditError(RuntimeError):
    """A PagePool invariant violation: a double release, a refcount that
    disagrees with the block tables, or a free-list/live-page overlap.
    Any raise means the allocator's shared mutable state was corrupt —
    dllama_kv_audit_failures_total counts every detection."""


class PagePool:
    """Host-side refcounted page allocator for the paged KV cache layout.

    Owns the per-slot block tables (numpy mirrors of PagedKVCache.tables),
    the per-page refcounts, and the free list. Pages are the allocation
    quantum: a slot's logical rows [0, n_blocks*page_size) are backed, one
    page per block, and a page referenced by several tables (prefix sharing)
    is freed only when its last reference drops. All methods are host-only
    and called from the engine under the scheduler worker thread; device
    copies needed by copy-on-write are performed by the engine-supplied
    ``copy_fn(src_page, dst_page)`` callback.

    Publishes the dllama_kv_pages_{total,used,shared} gauges after every
    mutation — the pool is the single owner of those series."""

    def __init__(self, n_pages: int, page_size: int, n_slots: int,
                 max_blocks: int):
        if n_pages < 2:
            raise ValueError(
                f"kv_pages={n_pages}: the pool needs at least a prompt page "
                "and a decode page")
        self.n_pages = n_pages
        self.page_size = page_size
        self.max_blocks = max_blocks
        self.refcount = np.zeros(n_pages, np.int32)
        self.tables = np.zeros((n_slots, max_blocks), np.int32)
        self.n_blocks = np.zeros(n_slots, np.int32)
        self._free: list[int] = list(range(n_pages - 1, -1, -1))
        # reentrant: the scheduler worker is the only mutator, but audit()
        # is also served from HTTP handler threads (GET /debug/kv) — the
        # lock keeps a cross-thread audit from reading a half-applied
        # mutation as corruption. Named rank "engine.pool" (utils/locks):
        # the radix prefix tree shares this object, and DLLAMA_LOCK_AUDIT=1
        # turns any out-of-rank nesting under it into a raise
        self._mu = locks.make_rlock("engine.pool")
        # DLLAMA_POOL_AUDIT=1: run the full invariant check after EVERY
        # release (tests/conftest.py arms it for the whole suite — any page
        # leak fails at the release that caused it, not at drain)
        self.audit_on_release = (
            os.environ.get("DLLAMA_POOL_AUDIT", "") not in ("", "0"))
        # radix prefix cache hook (engine/radix.RadixCache.audit_refs): a
        # provider of per-page TREE reference counts, so audit() reconciles
        # refcount == table refs + tree refs instead of flagging every
        # cached prefix page as corruption
        self.radix_refs = None
        # write-horizon hook (BatchEngine._write_horizons): a provider of
        # (slot, first_writable_row) pairs for ACTIVE slots, so audit()
        # can enforce the draft-write safety invariant — every allocated
        # block covering rows a decode or spec-verify step may write must
        # be EXCLUSIVELY owned (refcount 1, no tree refs). Spec verify
        # writes K+1 draft rows past the live position; a shared page in
        # that range would leak draft garbage into a radix- or
        # sibling-shared prefix.
        self.write_horizons = None
        # host-RAM spill tier (--kv-host-pages, ISSUE 16): audit() and
        # stats() reconcile it alongside the device pages when attached
        self.host: "HostKVPool | None" = None
        self._publish()

    # ----------------------------------------------------------- accounting

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def shared_count(self) -> int:
        return int(np.count_nonzero(self.refcount > 1))

    def blocks_for(self, rows: int) -> int:
        return -(-int(rows) // self.page_size)

    def covered_rows(self, slot: int) -> int:
        """Rows of `slot` with backing pages (its decode row limit)."""
        return int(self.n_blocks[slot]) * self.page_size

    def stats(self) -> dict:
        with self._mu:
            return {"total": self.n_pages, "free": self.free_count,
                    "used": self.n_pages - self.free_count,
                    "shared": self.shared_count, "page_size": self.page_size}

    def audit(self, raise_on_fail: bool = True) -> dict:
        """Invariant checker over the allocator's shared mutable state — the
        refcounts, block tables, and free list that every admission, COW,
        prefix share, and release mutate. Run at drain, after warm-restart
        recovery, on demand via GET /debug/kv, and (under
        DLLAMA_POOL_AUDIT=1) after every release. Checks:

        * per-page refcount == number of live block-table references;
        * the free list holds exactly the refcount-0 pages, once each;
        * no negative refcounts (double releases — also guarded inline);
        * the published gauges match the recount.

        Returns ``{"ok": bool, "problems": [...], ...stats}``; violations
        increment dllama_kv_audit_failures_total and (default) raise
        :class:`PoolAuditError` — corrupt allocator state must never be
        silently served."""
        with self._mu:
            problems: list[str] = []
            refs = np.zeros(self.n_pages, np.int64)
            for s in range(self.tables.shape[0]):
                for b in range(int(self.n_blocks[s])):
                    p = int(self.tables[s, b])
                    if 0 <= p < self.n_pages:
                        refs[p] += 1
                    else:
                        problems.append(
                            f"slot {s} block {b} references page {p} "
                            f"outside the pool [0, {self.n_pages})")
            radix_pages = 0
            if self.radix_refs is not None:
                # radix prefix-cache reconciliation: tree refs + block-table
                # refs must EXACTLY account for every refcount — a node ref
                # the tree forgot (leak) or double-counted shows up as the
                # same mismatch a corrupt table would
                tree_refs, tree_problems = self.radix_refs()
                problems.extend(tree_problems)
                for p, c in tree_refs.items():
                    if 0 <= p < self.n_pages:
                        refs[p] += c
                        radix_pages += c
            bad = np.flatnonzero(refs != self.refcount)
            for p in bad[:8]:
                problems.append(
                    f"page {int(p)}: refcount {int(self.refcount[p])} but "
                    f"{int(refs[p])} block-table references")
            if len(bad) > 8:
                problems.append(f"... and {len(bad) - 8} more refcount "
                                "mismatches")
            if self.write_horizons is not None:
                # draft-write safety: blocks at/above an active slot's next
                # write row (decode feeds one row; spec verify feeds K+1,
                # incl. rejected drafts) must be exclusively owned —
                # cow_writable() splits them before a dispatch, so a shared
                # page here means a write path skipped the COW
                for s, row in self.write_horizons():
                    first = int(row) // self.page_size
                    for b in range(first, int(self.n_blocks[s])):
                        p = int(self.tables[s, b])
                        if 0 <= p < self.n_pages and self.refcount[p] > 1:
                            problems.append(
                                f"active slot {s} block {b} (page {p}, "
                                f"refcount {int(self.refcount[p])}) is "
                                f"shared inside the writable range (row "
                                f">= {int(row)}): decode/spec draft "
                                "writes would leak into a shared page")
            neg = np.flatnonzero(self.refcount < 0)
            if neg.size:
                problems.append(
                    f"negative refcounts at pages {neg[:8].tolist()} "
                    "(double release)")
            free = set(self._free)
            if len(free) != len(self._free):
                problems.append(
                    f"free list holds duplicates ({len(self._free)} entries, "
                    f"{len(free)} distinct)")
            live = {p for p in range(self.n_pages) if self.refcount[p] > 0}
            overlap = free & live
            if overlap:
                problems.append(
                    f"free list overlaps live pages: {sorted(overlap)[:8]}")
            orphan = set(range(self.n_pages)) - free - live
            if orphan:
                problems.append(
                    f"leaked pages (refcount 0 but not on the free list): "
                    f"{sorted(orphan)[:8]}")
            if self.host is not None:
                # host-tier reconciliation: the spill tier's entries are
                # audited with the same rigor as device pages — capacity
                # respected, one entry per token path, page-aligned keys,
                # payload geometry intact, gauges matching the recount
                problems.extend(self.host.audit_problems())
            shared = int(np.count_nonzero(self.refcount > 1))
            # gauge consistency vs what THIS pool last published (the global
            # series itself may belong to another pool instance in
            # multi-engine tests — each _publish overwrites it)
            if self._published_used != self.n_pages - len(self._free):
                problems.append(
                    f"dllama_kv_pages_used published as "
                    f"{self._published_used} != recount "
                    f"{self.n_pages - len(self._free)} (a mutation skipped "
                    "_publish)")
            if self._published_shared != shared:
                problems.append(
                    f"dllama_kv_pages_shared published as "
                    f"{self._published_shared} != recount {shared}")
            report = {"ok": not problems, "problems": problems,
                      "total": self.n_pages, "free": len(self._free),
                      "used": self.n_pages - len(self._free),
                      "shared": shared, "page_size": self.page_size,
                      "radix_pages": radix_pages}
            if self.host is not None:
                report["host"] = self.host.stats()
        if problems:
            ins.KV_AUDIT_FAILURES.inc()
            if raise_on_fail:
                raise PoolAuditError(
                    "kv page-pool audit failed: " + "; ".join(problems))
        return report

    def _publish(self) -> None:
        self._published_used = self.n_pages - self.free_count
        self._published_shared = self.shared_count
        ins.KV_PAGES_TOTAL.set(self.n_pages)
        ins.KV_PAGES_USED.set(self._published_used)
        ins.KV_PAGES_SHARED.set(self._published_shared)

    # ------------------------------------------------------------ primitives

    def _alloc_page(self) -> int:
        faults.fire("pool.alloc")
        if not self._free:
            raise PageExhausted(
                f"page pool exhausted ({self.n_pages} pages of "
                f"{self.page_size} rows, all referenced)")
        p = self._free.pop()
        self.refcount[p] = 1
        return p

    def _decref(self, p: int) -> None:
        if self.refcount[p] <= 0:
            # double-release guard: decrementing past zero would silently
            # drive refcounts negative and hand the page to two owners at
            # once — the worst class of paged-KV corruption. Fail loudly at
            # the release that caused it.
            ins.KV_AUDIT_FAILURES.inc()
            raise PoolAuditError(
                f"double release of page {p} (refcount already "
                f"{int(self.refcount[p])})")
        self.refcount[p] -= 1
        if self.refcount[p] == 0:
            self._free.append(p)

    def grow(self, slot: int, rows: int, best_effort: bool = False) -> bool:
        """Extend `slot`'s table until its pages cover `rows` logical rows.
        All-or-nothing unless best_effort (then: allocate what the free list
        holds and stop). Returns True when the table changed."""
        with self._mu:
            need = self.blocks_for(rows) - int(self.n_blocks[slot])
            if need <= 0:
                return False
            if not best_effort and need > self.free_count:
                self._publish()
                raise PageExhausted(
                    f"slot {slot} needs {need} pages to reach row {rows}; "
                    f"{self.free_count} free of {self.n_pages}")
            changed = False
            for _ in range(need):
                if not self._free:
                    break
                self.tables[slot, self.n_blocks[slot]] = self._alloc_page()
                self.n_blocks[slot] += 1
                changed = True
            if changed:
                self._publish()
            return changed

    def free_tail(self, slot: int, keep_rows: int) -> int:
        """Drop `slot`'s blocks past the one containing row keep_rows-1
        (all of them for keep_rows == 0). Returns pages actually returned
        to the free list (shared pages just lose one reference). keep_rows
        past the covered range keeps everything — n_blocks must never GROW
        here (that would fabricate coverage backed by unallocated pages)."""
        with self._mu:
            keep = min(self.blocks_for(keep_rows), int(self.n_blocks[slot]))
            freed = 0
            for b in range(keep, int(self.n_blocks[slot])):
                p = int(self.tables[slot, b])
                before = self.free_count
                self._decref(p)
                freed += self.free_count - before
                self.tables[slot, b] = 0
            if self.n_blocks[slot] != keep:
                self.n_blocks[slot] = keep
                self._publish()
            return freed

    def ensure_writable(self, slot: int, row: int, copy_fn) -> None:
        """Copy-on-write: make the page holding `row` exclusively owned by
        `slot` before it is (partially) rewritten — a shared page's other
        referents keep the original bytes. copy_fn(src_page, dst_page)
        performs the device copy."""
        with self._mu:
            b = int(row) // self.page_size
            if b >= int(self.n_blocks[slot]):
                return
            old = int(self.tables[slot, b])
            if self.refcount[old] <= 1:
                return
            new = self._alloc_page()
            copy_fn(old, new)
            self.refcount[old] -= 1  # > 1 before, so never frees
            self.tables[slot, b] = new
            self._publish()

    def cow_writable(self, slot: int, start_row: int, end_row: int,
                     copy_fn) -> bool:
        """Copy-on-write every SHARED allocated block of `slot` covering
        rows [start_row, end_row) — the pre-dispatch guarantee behind the
        audit's write-horizon invariant: a decode chunk writes one row per
        step and a spec verify writes K+1 draft rows past the live
        position, and none of those writes may land in a page another slot
        or the radix tree still references. By construction (admission
        COW + fresh grow pages + full-page-only prefix shares) the range
        is normally exclusive already; this is the enforcement point that
        keeps it so under every composition. Returns True when any page
        was split (block tables changed — callers must refresh the device
        copy)."""
        with self._mu:
            first = int(start_row) // self.page_size
            last = min(self.blocks_for(end_row), int(self.n_blocks[slot]))
            changed = False
            for b in range(first, last):
                if self.refcount[int(self.tables[slot, b])] > 1:
                    self.ensure_writable(slot, b * self.page_size, copy_fn)
                    changed = True
            return changed

    def share_prefix(self, src: int, dst: int, rows: int, copy_fn) -> None:
        """Make dst's first `rows` rows alias src's pages: full pages are
        refcounted (zero copy), a partial boundary page is cloned into a
        fresh page (its tail will diverge immediately). Drops whatever dst
        held before."""
        with self._mu:
            self.free_tail(dst, 0)
            full, part = divmod(int(rows), self.page_size)
            for b in range(full):
                p = int(self.tables[src, b])
                self.refcount[p] += 1
                self.tables[dst, b] = p
            self.n_blocks[dst] = full
            if part:
                new = self._alloc_page()
                copy_fn(int(self.tables[src, full]), new)
                self.tables[dst, full] = new
                self.n_blocks[dst] = full + 1
            self._publish()

    def adopt_prefix(self, slot: int, pages: list[int]) -> None:
        """Point `slot`'s first blocks at `pages` BY REFERENCE — the radix
        prefix-cache mapping primitive: refcounts bump, zero device copies
        (a shared partial boundary page among `pages` is copy-on-written
        later by prepare_admission/ensure_writable when the divergent rows
        are about to be rewritten). Drops whatever the slot held before."""
        with self._mu:
            self.free_tail(slot, 0)
            for i, p in enumerate(pages):
                self.refcount[p] += 1
                self.tables[slot, i] = p
            self.n_blocks[slot] = len(pages)
            self._publish()

    def prepare_admission(self, slot: int, start: int, end: int, copy_fn) -> None:
        """Position `slot` for a prefill of rows [start, end): drop the dead
        tail past start, copy-on-write the boundary page when it is both
        kept and shared (rows [block_start, start) must survive the
        overwrite of [start, ...)), then allocate pages through `end`."""
        with self._mu:
            self.free_tail(slot, start)
            if start % self.page_size:
                self.ensure_writable(slot, start, copy_fn)
            self.grow(slot, end)

    def admission_deficit(self, slot: int, reuse: int, total_rows: int,
                          cross: bool) -> int:
        """How many pages the pool is SHORT for admitting a `total_rows`
        prompt into `slot` with `reuse` prefix rows already resolved
        (`cross`: the prefix arrives by share_prefix from another slot) —
        including one reserve page so the first decode rows after the
        prompt cannot immediately starve. 0 means the admission fits."""
        with self._mu:
            req = self.blocks_for(total_rows) + 1  # +1 decode-page reserve
            if cross:
                kept = int(reuse) // self.page_size  # full shared blocks free
                avail = self.free_count + self._tail_refund(slot, 0)
            else:
                kept = min(int(self.n_blocks[slot]), self.blocks_for(reuse))
                avail = self.free_count + self._tail_refund(slot, reuse)
                b = int(reuse) // self.page_size
                if (reuse % self.page_size and b < int(self.n_blocks[slot])
                        and self.refcount[int(self.tables[slot, b])] > 1):
                    req += 1  # boundary copy-on-write page
            return max(0, req - kept - avail)

    def _tail_refund(self, slot: int, keep_rows: int) -> int:
        """Pages free_tail(slot, keep_rows) would return to the free list."""
        keep = self.blocks_for(keep_rows)
        return sum(
            1 for b in range(keep, int(self.n_blocks[slot]))
            if self.refcount[int(self.tables[slot, b])] == 1
        )


class HostKVPool:
    """Host-RAM KV spill tier behind :class:`PagePool` (``--kv-host-pages``,
    ISSUE 16). A bounded LRU of page payloads keyed by the FULL token-id
    prefix the page's rows encode: when radix LRU eviction (or preempt-to-
    pages pressure routed through it) drops the last reference to a cold
    page, the engine copies its KV rows d2h into this pool instead of
    discarding them; a later admission whose prompt walks past the tree's
    resident prefix pops matching pages back h2d (restore-on-hit), so a
    multi-turn chat returning after eviction re-prefills only its partial
    boundary page. Entries are numpy (host) copies — the reference's
    root→worker framing where state that left the device is never the only
    copy (nn-network.hpp's named-tensor ship), applied to the KV tier.

    Keying by the token path (not the page id) is what makes the tier safe
    across warm restarts of the DEVICE pool: page ids die with the pool, a
    token prefix is meaningful forever — but a restart drops BOTH tiers
    (warm_restart) because a half-poisoned chunk may have corrupted the
    very rows a spill would preserve.

    Shares the pool's reentrant lock: spills happen under radix eviction
    (already inside the lock), restores under admission lookup, and
    ``audit_problems()`` is re-entered by ``PagePool.audit()`` from HTTP
    handler threads. Owns the dllama_kv_host_pages_{total,used} gauges."""

    def __init__(self, n_pages: int, page_size: int, mu):
        if n_pages < 1:
            raise ValueError(f"kv_host_pages={n_pages}: the host tier "
                             "needs at least one page slot")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._mu = mu
        # token-path key (tuple[int], len % page_size == 0, last page_size
        # entries are the page's rows) -> (k_page, v_page) numpy payloads
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        # cumulative accounting (stats/debug; chaos reconciles spill counts)
        self.spilled = 0
        self.restored = 0
        self.dropped = 0  # LRU pressure evictions of the HOST tier itself
        self._publish()

    def _publish(self) -> None:
        self._published_used = len(self._entries)
        ins.KV_HOST_PAGES_TOTAL.set(self.n_pages)
        ins.KV_HOST_PAGES_USED.set(self._published_used)

    @property
    def used(self) -> int:
        with self._mu:
            return len(self._entries)

    def put(self, key: tuple, payload: tuple) -> None:
        """Admit one spilled page; the coldest entry makes room when full
        (the host tier is itself an LRU — losing ITS coldest page merely
        restores the pre-tier discard behavior for that prefix)."""
        with self._mu:
            key = tuple(int(t) for t in key)
            self._entries.pop(key, None)
            while len(self._entries) >= self.n_pages:
                self._entries.popitem(last=False)
                self.dropped += 1
            self._entries[key] = payload
            self.spilled += 1
            self._publish()

    def peek(self, key: tuple) -> tuple | None:
        """Payload for `key` without removing it (restore uploads first,
        then commits the take — a failed device alloc must not lose the
        host copy)."""
        with self._mu:
            return self._entries.get(tuple(int(t) for t in key))

    def take(self, key: tuple) -> None:
        """Commit a restore: the page is device-resident (tree-owned)
        again, so the host copy retires — keeping both would double-count
        the prefix and stale the host bytes once the page is COW'd."""
        with self._mu:
            if self._entries.pop(tuple(int(t) for t in key), None) is not None:
                self.restored += 1
                self._publish()

    def clear(self) -> int:
        with self._mu:
            n = len(self._entries)
            self._entries.clear()
            self._publish()
            return n

    def stats(self) -> dict:
        with self._mu:
            return {"total": self.n_pages, "used": len(self._entries),
                    "page_size": self.page_size, "spilled": self.spilled,
                    "restored": self.restored, "dropped": self.dropped}

    def audit_problems(self) -> list[str]:
        """Invariant recount for ``PagePool.audit()``: capacity respected,
        keys page-aligned, payload geometry intact (a corrupt payload would
        restore garbage KV rows), published gauge matching the recount."""
        with self._mu:
            problems: list[str] = []
            if len(self._entries) > self.n_pages:
                problems.append(
                    f"host tier holds {len(self._entries)} pages over its "
                    f"{self.n_pages}-page capacity")
            for key, payload in self._entries.items():
                if not key or len(key) % self.page_size:
                    problems.append(
                        f"host tier key of {len(key)} tokens is not "
                        f"page-aligned (page_size {self.page_size})")
                    break
            for key, payload in self._entries.items():
                if (not isinstance(payload, tuple) or len(payload) != 2
                        or any(getattr(b, "shape", None) is None
                               or b.shape[-2] != self.page_size
                               for b in payload)):
                    problems.append(
                        "host tier payload geometry corrupt (expected "
                        f"(k, v) arrays of {self.page_size} rows)")
                    break
            if self._published_used != len(self._entries):
                problems.append(
                    f"dllama_kv_host_pages_used published as "
                    f"{self._published_used} != recount "
                    f"{len(self._entries)} (a mutation skipped _publish)")
            return problems


def _sample_rows(logits, keys, temps, topps):
    """Per-row sampling with per-row keys: [B, V] x [B, 2] -> [B]."""
    return jax.vmap(lambda lg, k, t, p: sample_logits(lg[None], k, t, p)[0])(
        logits, keys, temps, topps
    )


@dataclass
class Admission:
    """In-flight incremental prefill of one slot (add_begin/add_step/add_commit)."""

    slot: int
    toks: np.ndarray  # i32 prompt tokens still owed rows from toks[off:]
    off: int = 0
    logits: jax.Array | None = None  # [1, V] slot row from the LAST chunk
    req_id: str = ""  # serving-tier request id, for engine-level log/trace lines


@dataclass
class DecodeChunk:
    """A dispatched-but-unconsumed fused decode chunk (decode_dispatch).

    `toks` is the device-side [n, B] token array — JAX dispatch is async, so
    it materializes while the caller does host work; decode_consume blocks on
    it. The numpy fields are HOST snapshots taken at dispatch time: the
    scheduler attributes each slot's tokens against the positions/activity
    the chunk was actually dispatched with, not whatever boundary mutations
    happened since."""

    toks: jax.Array  # [n, B] i32, materializes asynchronously
    n: int  # scan length actually dispatched
    start_pos: np.ndarray  # i32[B] per-slot position at dispatch
    active: np.ndarray  # bool[B] active mask at dispatch
    advance: np.ndarray  # i32[B] rows each slot really advances (per-row
    # freeze at seq_len: min(n, room) for active slots, 0 otherwise)
    t0: float  # dispatch wall-clock (DECODE_CHUNK_SECONDS stops at consume)
    seq: int = 0  # monotone chunk number (trace correlation key: the
    # scheduler's dispatch/consume spans and the flight-recorder chunk
    # lists all cite this id)
    t_disp: float = 0.0  # dispatch mark on the TRACE clock (time.monotonic;
    # t0 above is perf_counter) — decode_consume's device-window span runs
    # from here to token materialization
    bad: jax.Array | None = None  # bool[B] rows whose logits went
    # non-finite inside the scan (the decode NaN guard's device-side half)
    bad_inject: np.ndarray | None = None  # decode.nan fault overlay
    device_s: float = 0.0  # exclusive device window, stamped at consumption
    # (same clock as DECODE_CHUNK_SECONDS: starts at the later of this
    # chunk's dispatch and the previous chunk's consumption) — what the
    # roofline-attainment gauge divides priced HBM bytes by
    spec: bool = False  # this chunk is a fused spec chunk of `n` verify
    # cycles: `toks` is the stacked per-cycle emit tensor [n, B, K+1]
    # (decode_consume flattens each slot's accepted runs into the plain
    # [rows, B] layout), `advance` holds a HOST LOWER BOUND at dispatch
    # (emit counts are data-dependent) and is overwritten with the real
    # per-slot totals when decode_consume materializes `adv_dev`
    adv_dev: jax.Array | None = None  # i32[m, B] real per-cycle emitted
    # counts (spec); decode_consume sums them into `advance`
    adv_cycles: np.ndarray | None = None  # host copy of adv_dev after
    # consumption — the scheduler's per-request participation record
    start_dev: jax.Array | None = None  # i32[B] the cycle's TRUE start
    # positions (the device pos carry captured at dispatch — under the
    # overlapped pipeline the host mirror may lag the in-flight
    # predecessor); decode_consume overwrites start_pos with it
    drafted_dev: jax.Array | None = None  # i32[B] draft tokens verified per
    # row this cycle (0 for sampled/non-spec/frozen rows) — the acceptance
    # telemetry's denominator, materialized alongside adv_dev at consume
    hybrid_slot: int = -1  # >= 0: this chunk also carried a fused prefill
    # slice for that (inactive) admitting slot (hybrid_dispatch)
    hybrid_tokens: int = 0  # prompt tokens the fused slice covered

    def nonfinite(self) -> np.ndarray | None:
        """bool[B] rows whose logits went non-finite during this chunk
        (real detection from the scan carry, OR'd with any armed
        ``decode.nan`` injection); None when every row is clean. The
        scheduler fails flagged rows' REQUESTS (finish_reason='error',
        rows released unreusable) — a poisoned slot must not crash the
        engine nor serve garbage tokens."""
        out = None
        if self.bad is not None:
            out = np.asarray(self.bad)
            compile_obs.note_transfer("d2h", "nan_guard", int(out.nbytes))
        if self.bad_inject is not None:
            out = self.bad_inject if out is None else (out | self.bad_inject)
        if out is None or not out.any():
            return None
        return out


class BatchEngine:
    def __init__(
        self,
        cfg: LlamaConfig,
        params,
        n_slots: int = 4,
        cache_dtype=jnp.bfloat16,
        max_seq_len: int | None = None,
        max_prefill_chunk: int = 256,
        seed: int = 0,
        shardings=None,  # parallel/sharding.LlamaShardings: multi-chip serving
        attn_impl: str = "auto",  # 'auto' | 'jnp' | 'flash' (same as InferenceEngine)
        sync: str = "bf16",  # 'bf16' | 'q80' | 'auto' tp exchange
        # (resolved like InferenceEngine via parallel/collectives.resolve_sync)
        kernels: str = "auto",  # 'auto' | 'pallas' | 'xla' matmul backend
        moe_impl: str = "auto",  # 'auto' | 'dispatch' | 'sort' | 'dense' (ops.layers.moe_ffn)
        fuse_weights: bool = False,  # wqkv/w13 fused launches (unsharded only,
        # same contract as InferenceEngine)
        spec: int = 0,  # K-token prompt-lookup speculative decoding for the
        # batch (spec_step); 0 = off. Greedy slots emit 1..K+1 exact-argmax
        # tokens per verify forward; sampled slots advance exactly 1.
        spec_ngram: int = 2,
        kv_layout: str = "dense",  # 'dense' | 'paged' (--kv-layout): paged
        # replaces the per-slot [seq_len] reservation with a global page pool
        # + block tables — bit-exact vs dense, capacity decoupled from slots
        page_size: int = 128,  # paged: rows per page (must divide seq_len)
        kv_pages: int = 0,  # paged: pool size in pages; 0 = full coverage
        # (n_slots * seq_len/page_size — semantically identical to dense).
        # Smaller pools overcommit: admission becomes capacity-aware in the
        # serving scheduler, and slots freeze per-row at their allocated
        # limit when the pool runs dry mid-decode.
        radix_cache: str = "auto",  # 'auto' | 'on' | 'off' (--radix-cache):
        # cross-request radix prefix tree over the page pool (engine/radix).
        # auto = on whenever the layout is paged; the tree only acts through
        # the radix_* methods the serving scheduler drives, so direct add/
        # decode/release library use is unchanged either way.
        kv_host_pages: int = 0,  # host-RAM KV spill tier (--kv-host-pages,
        # ISSUE 16): page slots in the pinned host pool radix eviction
        # spills cold pages into (d2h) instead of discarding them, restored
        # h2d on an admission prefix hit. 0 = off; > 0 requires the paged
        # layout with the radix cache on (the tree's token-path keys ARE
        # the host tier's addressing).
        transfer_guard: str = "off",  # 'off' | 'log' | 'strict'
        # (--transfer-guard, ISSUE 13): steady-state decode/spec jit calls
        # run under jax.transfer_guard_host_to_device — their operands are
        # device-resident carries by construction, so 'strict' turns any
        # implicit per-chunk upload into an error instead of a silently
        # serialized pipeline. Boundary uploads (vector refresh, prefill
        # chunks) happen outside the guarded window and stay legal.
    ):
        from dllama_tpu.ops.layers import build_rope_cache

        self.cfg = cfg
        self.params = params
        if fuse_weights:
            if shardings is not None:
                raise ValueError("fuse_weights requires an unsharded engine "
                                 "(tp shards q and kv blocks at different granularity)")
            from dllama_tpu.models.llama import fuse_layer_weights

            self.params = dict(params, layers=fuse_layer_weights(params["layers"]))
        self.n_slots = n_slots
        self.seq_len = min(max_seq_len or cfg.seq_len, cfg.seq_len)
        self.max_prefill_chunk = max_prefill_chunk
        self.rope_cache = build_rope_cache(cfg, self.seq_len)
        if kv_layout not in ("dense", "paged"):
            raise ValueError(f"kv_layout must be dense|paged, got {kv_layout!r}")
        self.kv_layout = kv_layout
        self.page_size = int(page_size)
        # retained for warm_restart(): a crash-recovery rebuild must recreate
        # the cache/pool with the exact construction-time parameters
        self.cache_dtype = cache_dtype
        self._shardings = shardings
        self.pool: PagePool | None = None
        if kv_layout == "paged":
            if shardings is not None:
                raise ValueError(
                    "paged KV cache requires an unsharded engine (the page "
                    "pool has no slot axis for a mesh to shard); use "
                    "kv_layout='dense' on meshes")
            if self.page_size <= 0 or self.seq_len % self.page_size:
                raise ValueError(
                    f"page_size {self.page_size} must divide the context "
                    f"length {self.seq_len} (paged attention keeps the "
                    "logical view the same shape as the dense cache, which "
                    "is what makes it bit-exact)")
            max_blocks = self.seq_len // self.page_size
            n_pages = int(kv_pages) or max_blocks * n_slots
            self.pool = PagePool(n_pages, self.page_size, n_slots, max_blocks)
            self.pool.write_horizons = self._write_horizons
            self.cache = PagedKVCache.create(
                cfg, n_slots, n_pages, self.page_size, cache_dtype, max_blocks)
        else:
            self.cache = KVCache.create(cfg, n_slots, cache_dtype, self.seq_len)
        if radix_cache not in ("auto", "on", "off"):
            raise ValueError(
                f"radix_cache must be auto|on|off, got {radix_cache!r}")
        if radix_cache == "on" and self.pool is None:
            raise ValueError("--radix-cache on requires the paged KV layout "
                             "(the tree's nodes own page-pool references)")
        self.radix = None
        if self.pool is not None and radix_cache != "off":
            from dllama_tpu.engine.radix import RadixCache

            self.radix = RadixCache(self.pool)
        self.kv_host_pages = int(kv_host_pages)
        if self.kv_host_pages > 0:
            if self.radix is None:
                raise ValueError(
                    "kv_host_pages > 0 requires the paged KV layout with "
                    "the radix cache on (host-tier pages are keyed by the "
                    "tree's token paths)")
            self.pool.host = HostKVPool(self.kv_host_pages, self.page_size,
                                        self.pool._mu)
            self.radix.spill = self._host_spill
        if shardings is not None:
            if shardings.mesh.shape["sp"] > 1 or shardings.mesh.shape["pp"] > 1:
                # per-slot vector positions don't fit the sp shard_map masks or
                # the GPipe schedule; continuous batching serves tp/dp meshes
                raise ValueError("BatchEngine supports tp/dp meshes (not sp/pp)")
            self.params = shardings.put_params(self.params)
            self.cache = shardings.put_cache(self.cache)
            self.rope_cache = shardings.put_replicated(self.rope_cache)
        self.pos = np.zeros(n_slots, np.int32)  # next cache row per slot
        self.active = np.zeros(n_slots, bool)  # slot is decoding
        self.last_token = np.zeros(n_slots, np.int32)
        self.temperature = np.zeros(n_slots, np.float32)
        self.topp = np.full(n_slots, 0.9, np.float32)
        # per-request speculation (ISSUE 11): each slot carries its OWN
        # draft length, set at add_commit from the request's spec_k (clamped
        # to the engine's compile-time K). 0 = the slot rides spec cycles as
        # a plain one-token-per-forward row (sampled rows always do), so
        # mixed spec/non-spec traffic batches together without freezing.
        self.spec_k_slot = np.zeros(n_slots, np.int32)
        # OpenAI repetition penalties, per slot; counts ([B, V] sampled-token
        # occurrences) allocate lazily on the first penalized request
        self.presence = np.zeros(n_slots, np.float32)
        self.frequency = np.zeros(n_slots, np.float32)
        self._counts: jax.Array | None = None
        # per-slot PRNG keys (threefry uint32[2]); requests without a seed get
        # a unique key derived from the engine seed + admission counter.
        # NOTE: `keys` is a commit-time record only — the LIVE keys advance
        # on-device inside the decode scan (self._keys_dev below) and are
        # never copied back; each row here is the key its slot's request
        # STARTED from, overwritten at the next add_commit.
        self.keys = np.tile(np.array(jax.random.PRNGKey(seed)), (n_slots, 1))
        self._base_key = jax.random.PRNGKey(seed)
        self._admissions = 0
        self.chunk_seq = 0  # decode/spec chunk counter (DecodeChunk.seq)

        # ---- device-resident decode state. The JAX arrays below are the
        # authoritative operands of the fused decode step, threaded
        # chunk-to-chunk so steady-state decode uploads NOTHING (the numpy
        # arrays above are host mirrors for the scheduler's bookkeeping).
        # Two regimes:
        #   * host-authoritative (pos/active/temperature/topp/presence/
        #     frequency): only admission/commit/release mutate them, and the
        #     host can track pos exactly (decode advances it
        #     deterministically) — re-uploaded on `_vec_dirty`, i.e. at
        #     boundaries only.
        #   * device-authoritative (last_token, keys): mutated by the scan
        #     itself with data-dependent values the host cannot reproduce
        #     (sampled tokens, threefry splits) — never uploaded; commit
        #     surgically row-writes them, and the host last_token mirror
        #     refreshes when a chunk's tokens are consumed.
        self._vec_dirty = True
        self._last_dev = jnp.zeros(n_slots, jnp.int32)
        self._keys_dev = jnp.asarray(self.keys.copy())
        # pos is DEVICE-authoritative like last_token/keys (since ISSUE 11):
        # a speculative cycle advances it by a data-dependent count the host
        # cannot mirror until consumption, so under the overlapped pipeline
        # a bulk host re-upload could clobber an in-flight cycle's carry.
        # Host mutation sites (admission/commit/release/copy/map) write
        # their slot's row surgically instead; the host `self.pos` stays
        # the scheduler-facing mirror (exact at boundaries, arithmetically
        # advanced for plain chunks, fixed up at spec consumption).
        self._pos_dev = jnp.zeros(n_slots, jnp.int32)
        self._active_dev = None
        self._temps_dev = None
        self._topp_dev = None
        self._pres_dev = None
        self._freq_dev = None
        self._speck_dev = None  # i32[B] per-slot draft length (spec_k_slot)
        self._limit_dev = None  # i32[B] per-slot decode row limit: seq_len
        # on dense, min(seq_len, allocated pages * page_size) on paged —
        # the scans freeze rows at it exactly like the old seq_len edge
        # when the previous chunk's tokens materialized (perf_counter): the
        # DECODE_CHUNK_SECONDS clock for an overlapped chunk starts at the
        # LATER of its dispatch and this — a chunk dispatched while its
        # predecessor still runs must not be billed the predecessor's tail
        self._t_last_consume: float | None = None

        from dllama_tpu.parallel.collectives import resolve_sync

        self.sync = sync = resolve_sync(sync, shardings)
        self._col_fn = None
        if sync == "q80" and shardings is not None and shardings.mesh.shape["tp"] > 1:
            from dllama_tpu.parallel.collectives import make_q80_col_matmul

            self._col_fn = make_q80_col_matmul(shardings.mesh)

        # kernel selection shared with InferenceEngine (engine/kernel_select.py)
        from dllama_tpu.engine.kernel_select import (
            resolve_kernels,
            resolve_moe_impl,
        )

        moe_impl = resolve_moe_impl(moe_impl, shardings)
        sel = resolve_kernels(cfg, self.seq_len, n_slots, kernels, attn_impl,
                              shardings, paged=self.pool is not None,
                              page_size=self.page_size,
                              cache_dtype=cache_dtype)
        mm, mm_in, attn_fn = sel.mm, sel.mm_in, sel.attn_fn
        self.backend = sel.backend
        # which attention path actually runs ('paged_kernel' = the fused
        # flash-decode kernel, 'paged_gather' = jnp view gather, ...) — the
        # cost model prices the two paged routes very differently
        self.attn_route = sel.attn_route

        self._prefill_step = jax.jit(
            partial(self._prefill_impl, cfg, attn_fn, self._col_fn, mm, mm_in, moe_impl),
            donate_argnums=(1,),
        )
        slot_prefill = (self._prefill_slot_paged_impl if self.pool is not None
                        else self._prefill_slot_impl)
        self._prefill_slot = jax.jit(
            partial(slot_prefill, cfg, attn_fn, self._col_fn, mm, mm_in, moe_impl),
            donate_argnums=(1,),
        )
        # admission prefill sliced to one slot runs the forward at B=1 —
        # admission cost independent of n_slots. Needs the batch axis
        # unsharded (a dp mesh shards slots across chips; slicing one slot
        # would cross shards), so dp>1 keeps the masked full-width path.
        # Paged engines are unsharded by construction and ALWAYS use it (the
        # pool has no slot axis to slice; writes land in the slot's own
        # pages by table construction).
        self._use_slot_prefill = (self.pool is not None or shardings is None
                                  or shardings.mesh.shape["dp"] == 1)
        self._decode = jax.jit(
            partial(self._decode_impl, cfg, attn_fn, self._col_fn, mm, mm_in, moe_impl),
            static_argnums=(8,), donate_argnums=(1,),
        )
        self._decode_pen = jax.jit(
            partial(self._decode_penalized_impl, cfg, attn_fn, self._col_fn, mm,
                    mm_in, moe_impl),
            static_argnums=(8,), donate_argnums=(1, 11),
        )
        # fused hybrid step (ISSUE 12): a prefill slice + a decode chunk in
        # ONE launch. Same single-slot prefill contract as _prefill_slot, so
        # it needs an unsharded batch axis (dp meshes keep the phase-split
        # path — the scheduler checks supports_hybrid).
        self._hybrid = jax.jit(
            partial(self._hybrid_impl, cfg, attn_fn, self._col_fn, mm, mm_in,
                    moe_impl),
            static_argnums=(11,), donate_argnums=(1,),
        )
        self._hybrid_pen = jax.jit(
            partial(self._hybrid_pen_impl, cfg, attn_fn, self._col_fn, mm,
                    mm_in, moe_impl),
            static_argnums=(11,), donate_argnums=(1, 14),
        )
        self._copy_rows = jax.jit(self._copy_rows_impl, donate_argnums=(0,))
        self._copy_page = jax.jit(self._copy_page_impl, donate_argnums=(0,))
        # host-tier restore upload: write one page's (k, v) host payload
        # into a freshly allocated pool page (the h2d counterpart of the
        # spill's d2h slice; boundary-attributed like the COW clone)
        self._write_page = jax.jit(self._write_page_impl, donate_argnums=(0,))
        self._read_page = jax.jit(self._read_page_impl)

        # batched speculative decoding (see spec_step): per-slot on-device
        # token history feeds the n-gram proposer; one verify forward per
        # cycle serves every slot. `spec` is the COMPILE-TIME draft width K
        # (the verify forward is K+1 wide); each slot's effective draft
        # length is its own spec_k_slot row, clamped to K — so one compile
        # serves per-request speculation.
        self.spec_k = int(spec)
        # cumulative acceptance accounting (spec_stats): fed by
        # decode_consume for spec chunks, mirrors the dllama_spec_* series
        self._spec_totals = {"cycles": 0, "drafted": 0, "accepted": 0,
                             "emitted": 0}
        # dispatched-but-unconsumed spec chunks (0 or 1 under the
        # depth-one pipeline): while nonzero the host pos mirror lags the
        # device carry, so the next dispatch's page top-up covers the
        # in-flight rows too
        self._spec_inflight = 0
        if self.spec_k:
            if shardings is not None and shardings.mesh.shape["dp"] > 1:
                # history rows are slot-indexed on the host admission path;
                # a dp mesh shards the slot axis
                raise ValueError("spec batching supports unsharded/tp engines")
            cap = sel.fused_scatter_max_t
            if cap is not None and self.spec_k + 1 > cap:
                # routing note, not an error: verify forwards wider than
                # the paged kernel's fused-scatter cap pre-scatter their
                # new KV rows via one XLA scatter per layer per cycle —
                # identical results, one extra dispatch per layer
                log.info(
                    "spec_k=%d verify chunks (t=%d) exceed the paged "
                    "kernel's fused-scatter cap (%d rows); new-KV rows "
                    "pre-scatter via XLA per layer", self.spec_k,
                    self.spec_k + 1, cap)
            self.history = jnp.full((n_slots, self.seq_len + 1), -1, jnp.int32)
            self._spec_step = jax.jit(
                partial(self._spec_step_impl, cfg, attn_fn, self._col_fn, mm,
                        mm_in, moe_impl, self.spec_k, spec_ngram),
                static_argnums=(12,), donate_argnums=(1, 2),
            )
            # penalized traffic rides its own jit (counts in the cycle
            # carry) so penalty-free serving pays nothing — same split as
            # _decode vs _decode_pen
            self._spec_step_pen = jax.jit(
                partial(self._spec_step_pen_impl, cfg, attn_fn, self._col_fn,
                        mm, mm_in, moe_impl, self.spec_k, spec_ngram),
                static_argnums=(15,), donate_argnums=(1, 2, 12),
            )
            self._hist_write = jax.jit(self._hist_write_impl, donate_argnums=(0,))

        # ---- compile observability (ISSUE 13, obs/compile): the ledger's
        # jax.monitoring listener attributes every trace/compile to the
        # scoped dispatch sites below, and THIS engine's shape contract
        # declares the expected compiled universe. Engine construction
        # declares the scheduler-independent buckets (pow2 prefill chunks,
        # the B=1 commit sample); the serving scheduler adds the decode/
        # spec/hybrid buckets it will dispatch (declare_serving_buckets).
        if transfer_guard not in compile_obs.TRANSFER_GUARD_MODES:
            raise ValueError(
                f"transfer_guard must be one of "
                f"{compile_obs.TRANSFER_GUARD_MODES}, got {transfer_guard!r}")
        self.transfer_guard = transfer_guard
        self.contract = compile_obs.ShapeContract()
        self._bucket_tag = sel.bucket_tag()
        from dllama_tpu.engine.kernel_select import pow2_buckets

        # pow2_chunk never emits a chunk wider than the prompt cap, and a
        # prompt is < seq_len — the declared prefill universe honors both
        for c in pow2_buckets(self._prefill_bucket_cap()):
            self.contract.declare("prefill_chunk", f"m{c}",
                                  note=self._bucket_tag)
        self.contract.declare("commit", "b1", note=self._bucket_tag)
        compile_obs.LEDGER.install_contract(self.contract)
        compile_obs.LEDGER.ensure_listener()

    # ------------------------------------------------------------- jitted fns

    @staticmethod
    def _prefill_impl(cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params, cache, tokens,
                      pos_vec, active, rope):
        logits, cache = forward(cfg, params, tokens, pos_vec, cache, rope, attn_fn,
                                active=active, col_fn=col_fn, mm=mm, mm_in=mm_in,
                                moe_impl=moe_impl, last_only=True)
        return logits[:, -1], cache

    @staticmethod
    def _prefill_slot_impl(cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params, cache,
                           tokens, slot, pos, rope):
        """Admission prefill for ONE slot: slice the slot's cache rows
        (batch axis), run the forward at B=1, write the rows back. A 32-slot
        engine admits a prompt at 1/32 the FLOPs of the masked full-width
        step — the other slots' caches are untouched by construction, not by
        masking. `slot` and `pos` are traced scalars (no per-slot recompiles).

        The reference has no analog: its server prefills one request at a
        time on the whole machine (dllama-api.cpp:380-431, single-request
        blocking per SURVEY.md §7.4.6); this keeps admission O(prompt) while
        the other slots' decode state waits untouched.
        """
        sub = KVCache(
            jax.lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1),
            jax.lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1),
        )
        logits, sub = forward(cfg, params, tokens, pos, sub, rope, attn_fn,
                              col_fn=col_fn, mm=mm, mm_in=mm_in,
                              moe_impl=moe_impl, last_only=True)
        return logits[:, -1], KVCache(
            jax.lax.dynamic_update_slice_in_dim(cache.k, sub.k, slot, axis=1),
            jax.lax.dynamic_update_slice_in_dim(cache.v, sub.v, slot, axis=1),
        )

    @staticmethod
    def _prefill_slot_paged_impl(cfg, attn_fn, col_fn, mm, mm_in, moe_impl,
                                 params, cache, tokens, slot, pos, rope):
        """Paged admission prefill: B=1 over the GLOBAL page pool with the
        one slot's block-table row. No batch-axis slice/unslice — the writes
        land in the slot's own pages by table construction, so other slots'
        pages are untouched exactly like the dense slot slice."""
        row = jax.lax.dynamic_slice_in_dim(cache.tables, slot, 1, axis=0)
        sub = PagedKVCache(cache.k, cache.v, row)
        logits, sub = forward(cfg, params, tokens, pos, sub, rope, attn_fn,
                              col_fn=col_fn, mm=mm, mm_in=mm_in,
                              moe_impl=moe_impl, last_only=True)
        return logits[:, -1], PagedKVCache(sub.k, sub.v, cache.tables)

    @staticmethod
    def _copy_page_impl(cache, src, dst):
        """Clone pool page src into dst across all layers (k and v) — the
        copy-on-write primitive behind partial-page prefix shares and
        divergence into a shared page. Traced indices: one compile serves
        every page pair."""

        def one(buf):  # [L, P, H, page, hd]
            pg = jax.lax.dynamic_index_in_dim(buf, src, axis=1, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(buf, pg, dst, axis=1)

        return PagedKVCache(one(cache.k), one(cache.v), cache.tables)

    @staticmethod
    def _write_page_impl(cache, kpg, vpg, dst):
        """Install a host-restored page payload into pool page `dst` across
        all layers — the h2d counterpart of _copy_page_impl. Traced index:
        one compile serves every destination page."""

        def one(buf, pg):  # [L, P, H, page, hd] <- [L, H, page, hd]
            return jax.lax.dynamic_update_index_in_dim(buf, pg, dst, axis=1)

        return PagedKVCache(one(cache.k, kpg), one(cache.v, vpg),
                            cache.tables)

    @staticmethod
    def _read_page_impl(cache, src):
        """Slice one pool page's (k, v) rows across all layers for the d2h
        spill copy. Traced index — a plain `cache.k[:, p]` would bake the
        page id into the executable and compile once per distinct page."""

        def one(buf):  # [L, P, H, page, hd] -> [L, H, page, hd]
            return jax.lax.dynamic_index_in_dim(buf, src, axis=1,
                                                keepdims=False)

        return one(cache.k), one(cache.v)

    @staticmethod
    def _decode_impl(cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params, cache, tokens,
                     pos_vec, active, keys, temps, topps, n, rope, limit):
        def body(carry, _):
            tok, cache, p, keys, bad = carry
            # per-ROW freeze at the cache edge: a slot that fills its last
            # row mid-chunk stops sampling/advancing while batch-mates keep
            # their full chunk (the old whole-batch clamp shrank everyone's
            # chunk to the fullest slot's room). Frozen rows behave exactly
            # like inactive ones: writes masked, token repeats, key held —
            # p is clamped only for their rope/cache row indexing. `limit`
            # is seq_len on the dense layout; on paged it is each slot's
            # allocated-page horizon, so a pool running dry freezes rows
            # the same way the cache edge always has.
            act = jnp.asarray(active) & (p < limit)
            p_clamped = jnp.minimum(p, jnp.maximum(limit - 1, 0))
            logits, cache = forward(cfg, params, tok, p_clamped,
                                    cache, rope, attn_fn,
                                    active=act, col_fn=col_fn, mm=mm,
                                    mm_in=mm_in, moe_impl=moe_impl, last_only=True)
            # NaN guard, device-side half: a row whose logits went
            # non-finite is flagged (sticky across the chunk) so the
            # scheduler can fail THAT request instead of serving garbage —
            # inactive/frozen rows legitimately compute junk and are masked
            bad = bad | (act & ~jnp.isfinite(logits[:, -1]).all(axis=-1))
            splits = jax.vmap(jax.random.split)(keys)  # [B, 2, 2]
            nkeys, subs = splits[:, 0], splits[:, 1]
            keys = jnp.where(act[:, None], nkeys, keys)
            nxt = _sample_rows(logits[:, -1], subs, temps, topps)[:, None]
            nxt = jnp.where(act[:, None], nxt, tok)  # frozen slots keep token
            return (nxt, cache, p + act.astype(jnp.int32), keys, bad), nxt[:, 0]

        bad0 = jnp.zeros(tokens.shape[0], bool)
        (last, cache, pos2, keys, bad), toks = jax.lax.scan(
            body, (tokens, cache, pos_vec, keys, bad0), None, length=n
        )
        return toks, cache, keys, pos2, last[:, 0], bad

    @staticmethod
    def _decode_penalized_impl(cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params,
                               cache, tokens, pos_vec, active, keys, temps, topps,
                               n, rope, limit, counts, presence, frequency):
        """The fused multi-slot scan with OpenAI repetition penalties:
        per-slot counts of sampled-this-request tokens ride the carry (the
        fed token is counted before its successor is sampled — active slots
        only, so a frozen slot's repeated last token never inflates its
        counts). A separate jit from _decode_impl: penalty-free serving pays
        nothing."""
        from dllama_tpu.engine.sampling import apply_penalties

        b = tokens.shape[0]

        def body(carry, _):
            tok, cache, p, keys, counts, bad = carry
            # same per-row freeze as _decode_impl: a slot frozen at the cache
            # edge must not inflate its counts with its repeated last token
            act = jnp.asarray(active) & (p < limit)
            counts = counts.at[jnp.arange(b), tok[:, 0]].add(
                act.astype(jnp.int32))
            p_clamped = jnp.minimum(p, jnp.maximum(limit - 1, 0))
            logits, cache = forward(cfg, params, tok, p_clamped,
                                    cache, rope, attn_fn,
                                    active=act, col_fn=col_fn, mm=mm,
                                    mm_in=mm_in, moe_impl=moe_impl, last_only=True)
            # same sticky non-finite flag as _decode_impl (raw logits,
            # before penalties — penalties can only subtract finite values)
            bad = bad | (act & ~jnp.isfinite(logits[:, -1]).all(axis=-1))
            splits = jax.vmap(jax.random.split)(keys)
            nkeys, subs = splits[:, 0], splits[:, 1]
            keys = jnp.where(act[:, None], nkeys, keys)
            pen = apply_penalties(logits[:, -1], counts, presence, frequency)
            nxt = _sample_rows(pen, subs, temps, topps)[:, None]
            nxt = jnp.where(act[:, None], nxt, tok)
            return (nxt, cache, p + act.astype(jnp.int32), keys, counts,
                    bad), nxt[:, 0]

        bad0 = jnp.zeros(b, bool)
        (last, cache, pos2, keys, counts, bad), toks = jax.lax.scan(
            body, (tokens, cache, pos_vec, keys, counts, bad0), None, length=n
        )
        return toks, cache, keys, pos2, last[:, 0], counts, bad

    @classmethod
    def _hybrid_prefill_part(cls, cfg, attn_fn, col_fn, mm, mm_in, moe_impl,
                             params, cache, ptoks, slot, ppos, rope):
        """The admission half of one fused hybrid step: prefill `ptoks`
        ([1, P]) into `slot` at position `ppos` — the exact single-slot
        B=1 forward add_step uses (dense: batch-axis slice/unslice; paged:
        the slot's own block-table row over the global pool), just traced
        INSIDE the same jit as the decode scan, so the admission slice and
        the decode chunk are ONE device launch. The admitting slot is
        inactive in the decode half's mask, and every attention read is
        per-row (own slot / own table), so the decode rows' values are
        bitwise independent of this write — which is what makes hybrid-on
        token streams bit-exact vs the phase-split path. Returns
        (last-token logits [1, V], updated cache)."""
        if isinstance(cache, PagedKVCache):
            row = jax.lax.dynamic_slice_in_dim(cache.tables, slot, 1, axis=0)
            sub = PagedKVCache(cache.k, cache.v, row)
            plog, sub = forward(cfg, params, ptoks, ppos, sub, rope, attn_fn,
                                col_fn=col_fn, mm=mm, mm_in=mm_in,
                                moe_impl=moe_impl, last_only=True)
            return plog[:, -1], PagedKVCache(sub.k, sub.v, cache.tables)
        sub = KVCache(
            jax.lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1),
            jax.lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1),
        )
        plog, sub = forward(cfg, params, ptoks, ppos, sub, rope, attn_fn,
                            col_fn=col_fn, mm=mm, mm_in=mm_in,
                            moe_impl=moe_impl, last_only=True)
        return plog[:, -1], KVCache(
            jax.lax.dynamic_update_slice_in_dim(cache.k, sub.k, slot, axis=1),
            jax.lax.dynamic_update_slice_in_dim(cache.v, sub.v, slot, axis=1),
        )

    @classmethod
    def _hybrid_impl(cls, cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params,
                     cache, ptoks, slot, ppos, tokens, pos_vec, active, keys,
                     temps, topps, n, rope, limit):
        """One fused hybrid step (ISSUE 12): a P-token prefill slice of an
        admitting slot AND an n-step fused decode chunk in a single jitted
        launch — a long prompt's admission rides the decode cadence as a
        bounded per-chunk token budget instead of stalling every decoding
        slot for a whole separate prefill dispatch. The prefill runs first
        (its slot is frozen in the decode mask; ordering is value-neutral
        by per-row isolation, but the threaded cache keeps the device
        stream sequential either way)."""
        plog, cache = cls._hybrid_prefill_part(
            cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params, cache, ptoks,
            slot, ppos, rope)
        toks, cache, keys, pos2, last, bad = cls._decode_impl(
            cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params, cache, tokens,
            pos_vec, active, keys, temps, topps, n, rope, limit)
        return plog, toks, cache, keys, pos2, last, bad

    @classmethod
    def _hybrid_pen_impl(cls, cfg, attn_fn, col_fn, mm, mm_in, moe_impl,
                         params, cache, ptoks, slot, ppos, tokens, pos_vec,
                         active, keys, temps, topps, n, rope, limit, counts,
                         presence, frequency):
        """Hybrid step over the penalized decode scan (mirrors the
        _decode/_decode_pen split: penalty-free hybrid serving pays no
        counts carry)."""
        plog, cache = cls._hybrid_prefill_part(
            cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params, cache, ptoks,
            slot, ppos, rope)
        toks, cache, keys, pos2, last, counts, bad = cls._decode_penalized_impl(
            cfg, attn_fn, col_fn, mm, mm_in, moe_impl, params, cache, tokens,
            pos_vec, active, keys, temps, topps, n, rope, limit, counts,
            presence, frequency)
        return plog, toks, cache, keys, pos2, last, counts, bad

    @staticmethod
    def _spec_cycle_core(cfg, attn_fn, col_fn, mm, mm_in, moe_impl, k, ngram,
                         params, cache, history, cur, pos_vec, active, speck,
                         keys, temps, topps, rope, limit, accept_mask,
                         sample_fn):
        """Shared body of one batched propose/verify cycle with PER-SLOT
        draft lengths (ISSUE 11). Eligibility is resolved ON DEVICE from the
        carried position (`eff`), so a cycle dispatched off an in-flight
        predecessor's carry (the overlapped pipeline) freezes exactly the
        rows whose REAL position lacks the K+1-row verify window — the
        host's possibly-stale view only gates heuristics, never writes.

        Per-slot semantics: greedy rows accept up to min(spec_k_slot, K)
        drafts (spec_k_slot == 0 makes a greedy row a plain
        one-token-per-forward participant, bit-identical to fused decode);
        sampled rows advance exactly 1 token from their offset-0 logits via
        `sample_fn` (which the penalized variant points at the
        counts-carrying sampler). Rejected drafts leave stale KV rows past
        each slot's live position; the per-row causal mask never reads
        them, and the pre-dispatch `cow_writable` guarantees those writes
        never land in a shared page."""
        from dllama_tpu.engine.speculative import propose_ngram

        active = jnp.asarray(active)
        # device-side eligibility: the verify forward writes K+1 rows for
        # every participating slot, so participation needs K+1 backed rows
        # below the slot's limit (context edge / allocated-page horizon)
        eff = active & (pos_vec + k + 1 <= limit)
        # rows that ride the argmax-sequence (draft-accepting) path; the
        # penalized variant excludes penalized rows from it (their token
        # must come from the PENALIZED sampler even at temperature 0)
        accept = accept_mask & eff
        k_eff = jnp.clip(jnp.minimum(speck, limit - pos_vec - 1), 0, k)
        k_eff = jnp.where(accept, k_eff, 0)
        draft = jax.vmap(
            lambda h, ln: propose_ngram(h, ln, k, ngram)[0]
        )(history, pos_vec + 1)  # [B, k]
        toks = jnp.concatenate([cur[:, None], draft], axis=1)  # [B, k+1]
        # frozen rows still flow through the forward (masked writes); clamp
        # their rope/cache indexing so the whole K+1 window stays in range
        p_clamped = jnp.minimum(pos_vec, jnp.maximum(limit - (k + 1), 0))
        logits, cache = forward(cfg, params, toks, p_clamped, cache, rope,
                                attn_fn, active=eff, col_fn=col_fn,
                                mm=mm, mm_in=mm_in,
                                moe_impl=moe_impl, last_only=False)
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]
        agree = jnp.cumprod((draft == g[:, :k]).astype(jnp.int32), axis=1)
        # accepted draft prefix, clamped to the slot's OWN draft length —
        # a spec_k_slot=0 greedy row emits exactly its bonus token g[0]
        a = jnp.minimum(jnp.sum(agree, axis=1), k_eff)

        # NaN guard (device half, mirrors the decode scans): any
        # non-finite logit of a PARTICIPATING row flags it for the
        # scheduler's per-request failure path
        bad = eff & ~jnp.isfinite(logits).all(axis=(1, 2))

        splits = jax.vmap(jax.random.split)(keys)
        keys_next, subs = splits[:, 0], splits[:, 1]
        samp, extras = sample_fn(logits, subs, cur, eff)  # [B]
        # only slots that actually consumed a sample advance their key:
        # argmax-path rows never touch theirs, and a frozen slot
        # (ineligible this cycle — e.g. near seq_len) must keep its
        # seed-pinned stream intact for the cycle/chunk that finishes it
        keys = jnp.where((accept | ~eff)[:, None], keys, keys_next)
        emit = jnp.where(accept[:, None], g,
                         jnp.concatenate([samp[:, None], g[:, 1:]], axis=1))

        # the emitted tokens are ALSO the history entries at pos+1..pos+k+1
        # (entries past the new live position are garbage that is never read
        # below the slot's length and overwritten when really decoded)
        hist2 = jax.vmap(
            lambda h, e, p: jax.lax.dynamic_update_slice(h, e, (p,))
        )(history, emit, pos_vec + 1)
        history = jnp.where(eff[:, None], hist2, history)

        adv = jnp.where(eff, a + 1, 0)  # tokens each slot emitted
        nxt = jnp.take_along_axis(emit, a[:, None], axis=1)[:, 0]
        nxt = jnp.where(eff, nxt, cur)
        drafted = jnp.where(eff, k_eff, 0)  # telemetry: drafts verified
        # pos_vec + adv keeps the device-resident position carry current
        # without a host round-trip (the cycle threads it chunk-to-chunk
        # like decode does)
        return (emit, adv, nxt, cache, history, keys, pos_vec + adv,
                drafted, bad, extras)

    @classmethod
    def _spec_step_impl(cls, cfg, attn_fn, col_fn, mm, mm_in, moe_impl, k,
                        ngram, params, cache, history, cur, pos_vec, active,
                        speck, keys, temps, topps, rope, limit, m):
        """Penalty-free fused spec chunk: m verify cycles in ONE
        lax.scan'd dispatch (see _spec_cycle_core for one cycle's
        semantics) — the speculation analog of the fused n-step decode
        scan, so a spec chunk amortizes host dispatch overhead exactly
        like a decode chunk does. Greedy rows ride the argmax-sequence
        path cycle after cycle; sampled rows take one exactly-sampled
        token per cycle from their offset-0 logits. Returns stacked
        per-cycle (emit [m, B, k+1], adv [m, B], drafted [m, B]) plus the
        threaded carry; `bad` is sticky across the chunk like the decode
        scans' NaN flag."""
        greedy = temps == 0.0

        def body(carry, _):
            cache, history, cur, pos, keys, bad = carry

            def sample_fn(logits, subs, cur, eff):
                return _sample_rows(logits[:, 0], subs, temps, topps), None

            (emit, adv, nxt, cache, history, keys, pos2, drafted, bad1,
             _extras) = cls._spec_cycle_core(
                cfg, attn_fn, col_fn, mm, mm_in, moe_impl, k, ngram, params,
                cache, history, cur, pos, active, speck, keys, temps, topps,
                rope, limit, greedy, sample_fn)
            return ((cache, history, nxt, pos2, keys, bad | bad1),
                    (emit, adv, drafted))

        bad0 = jnp.zeros(cur.shape[0], bool)
        (cache, history, nxt, pos2, keys, bad), (emits, advs, drafts) = \
            jax.lax.scan(body, (cache, history, cur, pos_vec, keys, bad0),
                         None, length=m)
        return emits, advs, nxt, cache, history, keys, pos2, drafts, bad

    @classmethod
    def _spec_step_pen_impl(cls, cfg, attn_fn, col_fn, mm, mm_in, moe_impl,
                            k, ngram, params, cache, history, cur, pos_vec,
                            active, speck, keys, temps, topps, rope, limit,
                            counts, presence, frequency, m):
        """Fused spec chunk with OpenAI repetition penalties in the scan
        carry: a penalized row (which can never accept drafts — acceptance
        compares raw argmax, penalized sampling needs the counts) advances
        exactly 1 token per cycle from its PENALIZED offset-0 logits, with
        its fed token counted first — bit-identical to the penalized
        decode scan's steps, so penalized traffic rides spec chunks
        instead of freezing behind the old _spec_tick alternation. Rows
        without penalties pay `logits - 0.0` (bitwise identity), the same
        mixed-batch contract the penalized decode scan already has; a
        penalized GREEDY row is excluded from the argmax path so its token
        comes from the penalized sampler (temperature 0 = penalized
        argmax)."""
        from dllama_tpu.engine.sampling import apply_penalties

        b = cur.shape[0]
        pen = (presence != 0.0) | (frequency != 0.0)
        accept_mask = (temps == 0.0) & ~pen

        def body(carry, _):
            cache, history, cur, pos, keys, bad, counts = carry

            def sample_fn(logits, subs, cur, eff):
                # fed token counted for participating rows before its
                # successor is sampled (ordering matches the decode scan)
                cnt = counts.at[jnp.arange(b), cur].add(eff.astype(jnp.int32))
                penalized = apply_penalties(logits[:, 0], cnt, presence,
                                            frequency)
                return _sample_rows(penalized, subs, temps, topps), cnt

            (emit, adv, nxt, cache, history, keys, pos2, drafted, bad1,
             cnt) = cls._spec_cycle_core(
                cfg, attn_fn, col_fn, mm, mm_in, moe_impl, k, ngram, params,
                cache, history, cur, pos, active, speck, keys, temps, topps,
                rope, limit, accept_mask, sample_fn)
            return ((cache, history, nxt, pos2, keys, bad | bad1, cnt),
                    (emit, adv, drafted))

        bad0 = jnp.zeros(b, bool)
        (cache, history, nxt, pos2, keys, bad, counts), (emits, advs,
                                                         drafts) = \
            jax.lax.scan(body,
                         (cache, history, cur, pos_vec, keys, bad0, counts),
                         None, length=m)
        return (emits, advs, nxt, cache, history, keys, pos2, drafts, bad,
                counts)

    @staticmethod
    def _hist_write_impl(history, slot, pos, toks):
        """Write toks into history[slot, pos:pos+len] (admission chunks and
        the first sampled token; traced slot/pos, len static per chunk)."""
        row = jax.lax.dynamic_index_in_dim(history, slot, axis=0, keepdims=False)
        row = jax.lax.dynamic_update_slice(row, toks, (pos,))
        return jax.lax.dynamic_update_index_in_dim(history, row, slot, axis=0)

    @staticmethod
    @jax.jit
    def _hist_write_batch(history, toks, pos_vec, active):
        """history[i, pos[i]+1 : pos[i]+1+n] = toks[i] for active slots —
        decode() backfills its emitted tokens so later spec_step drafting
        keeps full n-gram coverage."""
        upd = jax.vmap(
            lambda h, t, p: jax.lax.dynamic_update_slice(h, t, (p,))
        )(history, toks, pos_vec + 1)
        return jnp.where(active[:, None], upd, history)

    @staticmethod
    @jax.jit
    def _hist_copy_prefix(history, src, dst, rows):
        """history[dst, :rows] = history[src, :rows] without per-length
        recompiles (masked full-row copy, mirrors _copy_rows_impl)."""
        s = history.shape[1]
        src_row = jax.lax.dynamic_index_in_dim(history, src, axis=0, keepdims=False)
        dst_row = jax.lax.dynamic_index_in_dim(history, dst, axis=0, keepdims=False)
        merged = jnp.where(jnp.arange(s) < rows, src_row, dst_row)
        return jax.lax.dynamic_update_index_in_dim(history, merged, dst, axis=0)

    @staticmethod
    def _copy_rows_impl(cache, src, dst, rows):
        """Copy the first `rows` cache rows of slot src into slot dst (both
        k and v, all layers/heads). Static shapes: the whole [S] row axis is
        masked rather than sliced, so one compile serves every prefix
        length; src/dst/rows are traced scalars."""

        def one(buf):  # [L, B, H, S, hd]
            s = buf.shape[3]
            src_rows = jax.lax.dynamic_index_in_dim(buf, src, axis=1, keepdims=False)
            dst_rows = jax.lax.dynamic_index_in_dim(buf, dst, axis=1, keepdims=False)
            mask = (jnp.arange(s) < rows)[None, None, :, None]
            return jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(mask, src_rows, dst_rows), dst, axis=1
            )

        return KVCache(one(cache.k), one(cache.v))

    @property
    def supports_cross_slot_copy(self) -> bool:
        """False on dp meshes: the batch axis is sharded, so a slot-to-slot
        row copy would gather across shards."""
        return self._use_slot_prefill

    # ------------------------------------------------------- paged-layout api

    def _pool_page_copy(self, src_page: int, dst_page: int) -> None:
        """PagePool's device-copy callback (copy-on-write page clones)."""
        with compile_obs.LEDGER.scope("boundary", "page_copy"):
            self.cache = self._copy_page(
                self.cache, jnp.int32(src_page), jnp.int32(dst_page))

    def _row_limit(self) -> np.ndarray:
        """i32[B] per-slot decode row limit: the cache edge (seq_len) on
        dense; min(seq_len, allocated pages) on paged."""
        if self.pool is None:
            return np.full(self.n_slots, self.seq_len, np.int32)
        return np.minimum(
            self.seq_len, self.pool.n_blocks.astype(np.int64) * self.page_size
        ).astype(np.int32)

    def _alloc_decode_rows(self, n: int) -> None:
        """Paged: best-effort top-up before a decode/spec dispatch — extend
        each active slot's table to cover n more rows (clamped at seq_len).
        Slots the pool cannot serve keep their current limit and freeze
        per-row in the scan; pages freed by later releases un-freeze them.

        Also the draft-write COW gate: any SHARED allocated page covering
        the slot's writable rows [pos, pos+n) is copy-on-written first, so
        neither a decode row nor a spec cycle's k+1 draft rows (rejected
        drafts included) can ever land in a page the radix tree or a
        sibling slot still references — the invariant PagePool.audit()'s
        write-horizon check enforces."""
        if self.pool is None:
            return
        changed = False
        for s in np.flatnonzero(self.active):
            want = min(self.seq_len, int(self.pos[s]) + n)
            changed |= self.pool.grow(int(s), want, best_effort=True)
            changed |= self.pool.cow_writable(int(s), int(self.pos[s]), want,
                                              self._pool_page_copy)
        if changed:
            self._vec_dirty = True

    def _write_horizons(self) -> list[tuple[int, int]]:
        """PagePool.audit() provider: (slot, first_writable_row) for every
        active slot — rows at/above it may be written by the next decode
        chunk or spec verify cycle, so their pages must be exclusive."""
        return [(int(s), int(self.pos[s])) for s in np.flatnonzero(self.active)]

    def page_starved(self) -> np.ndarray:
        """bool[B]: active slots whose next decode row has no backing page
        even after a top-up attempt — frozen by pool exhaustion, not by the
        context edge. The scheduler uses this to break the all-starved
        livelock (finish one, its pages feed the rest)."""
        if self.pool is None:
            return np.zeros(self.n_slots, bool)
        self._alloc_decode_rows(1)
        limit = self._row_limit()
        return (self.active & (self.pos >= limit) & (self.pos < self.seq_len)
                & (self.pool.free_count == 0))

    def admission_deficit(self, slot: int, reuse: int, prompt_len: int,
                          cross: bool) -> int:
        """Pages SHORT for admitting `prompt_len` rows into `slot` (0 on the
        dense layout or when the admission fits) — the scheduler's
        capacity-aware admission check."""
        if self.pool is None:
            return 0
        return self.pool.admission_deficit(slot, reuse, prompt_len, cross)

    def min_pages_for(self, prompt_len: int) -> int:
        """Pages an admission of `prompt_len` rows needs from an empty pool
        (incl. the decode reserve) — above the pool total it can NEVER fit."""
        if self.pool is None:
            return 0
        return self.pool.blocks_for(prompt_len) + 1

    def drop_slot_pages(self, slot: int) -> int:
        """Evict an idle slot's cached pages (prefix-cache reclaim under
        pool pressure). Returns pages returned to the free list."""
        assert not self.active[slot], f"slot {slot} is busy"
        if self.pool is None:
            return 0
        freed = self.pool.free_tail(slot, 0)
        self.pos[slot] = 0
        self._pos_dev = self._pos_dev.at[slot].set(0)
        self._vec_dirty = True
        return freed

    def kv_page_stats(self) -> dict | None:
        """Pool occupancy snapshot for /health and latency_summary(); None
        on the dense layout. Gains a "host" sub-dict when the spill tier
        is on (GET /debug/kv surfaces it next to the device pages)."""
        if self.pool is None:
            return None
        st = self.pool.stats()
        if self.pool.host is not None:
            st["host"] = self.pool.host.stats()
        return st

    # ------------------------------------------------------ radix prefix api
    # (engine/radix.RadixCache over the page pool; the serving scheduler is
    # the only driver — these are no-ops / zeros when the cache is off)

    def radix_lookup(self, toks) -> tuple[int, object | None]:
        """(reusable_rows, hit-handle) for `toks` against the global radix
        tree; (0, None) when the cache is off. With the host tier on, a
        walk that ends short of the prompt first tries to graft spilled
        pages back (restore-on-hit, h2d), then re-walks — so an evicted
        multi-turn prefix costs O(partial boundary page), not a full
        re-prefill."""
        if self.radix is None:
            return 0, None
        hit = self.radix.lookup(toks)
        host = None if self.pool is None else self.pool.host
        if host is not None and host.used and hit.rows < len(toks) - 1:
            if self.radix.restore_prefix(toks, host.peek,
                                         self._host_restore_install,
                                         host.take):
                hit = self.radix.lookup(toks, count=False)
        return hit.rows, hit

    def radix_map(self, slot: int, hit) -> None:
        """Map a lookup hit into `slot`: the matched full pages land in its
        block table BY REFERENCE (refcount bump, zero copies), a partial
        boundary page is mapped shared too — the following add_begin's
        prepare_admission copy-on-writes it via the existing
        ensure_writable before any divergent row is rewritten. Positions
        the slot at the reused row count like copy_prefix_rows does."""
        assert not self.active[slot], f"slot {slot} is busy"
        pages = list(hit.pages)
        if hit.part:
            pages.append(hit.boundary)
        self.pool.adopt_prefix(slot, pages)
        self.pos[slot] = hit.rows
        self._pos_dev = self._pos_dev.at[slot].set(int(hit.rows))
        if self.spec_k and hit.rows:
            # the mapped prefix's token ids feed the n-gram proposer, same
            # as the cross-slot copy path did
            with compile_obs.LEDGER.scope("boundary", "hist"):
                self.history = self._hist_write(
                    self.history, jnp.int32(slot), jnp.int32(0),
                    jnp.asarray(np.asarray(hit.tokens, np.int32)))
        self._vec_dirty = True

    def radix_insert(self, slot: int, toks) -> int:
        """Insert the full-page prefix of `toks` (rows already written in
        `slot` — the prompt at commit, the emitted prefix at release) into
        the tree; adopted pages gain a tree reference that outlives the
        slot. Returns pages adopted (0 when off / nothing new)."""
        if self.radix is None or not len(toks):
            return 0
        full = min(len(toks) // self.page_size, int(self.pool.n_blocks[slot]))
        if full <= 0:
            return 0
        return self.radix.insert(list(toks)[: full * self.page_size],
                                 self.pool.tables[slot, :full])

    def radix_evict(self, need: int, protect=None) -> int:
        """Reclaim up to `need` pool pages from the tree (LRU leaves,
        coldest first); `protect` pins an in-progress admission's matched
        path. Returns pages actually freed."""
        return 0 if self.radix is None else self.radix.evict(need, protect)

    def radix_admission_deficit(self, total_rows: int, reuse_rows: int) -> int:
        """Pages SHORT for a radix admission of `total_rows` rows with
        `reuse_rows` already mapped from the tree — the radix analog of
        admission_deficit (slots are always empty at admission here: the
        tree, not idle slots, holds the cache). Includes the one-page
        decode reserve; the boundary COW clone and the suffix pages cost
        the same whether the boundary is shared or freshly grown."""
        pool = self.pool
        with pool._mu:
            full = int(reuse_rows) // self.page_size
            return max(0, pool.blocks_for(total_rows) + 1 - full
                       - pool.free_count)

    def radix_stats(self) -> dict | None:
        """Tree occupancy + cumulative hit accounting; None when off."""
        return None if self.radix is None else self.radix.stats()

    # --------------------------------------------------- host KV spill tier

    def _host_spill(self, key: tuple, page: int) -> bool:
        """RadixCache.spill hook, called under the pool lock right before an
        evicted leaf's last-reference page is dropped: copy the page's KV
        rows d2h into the host tier, keyed by the full token path. Returns
        True when captured. Any failure — an armed ``pool.spill`` fault or
        a real copy error — degrades to the old discard, which is always
        correct: the prefix just re-prefills when it returns."""
        host = self.pool.host
        if host is None:
            return False
        try:
            faults.fire("pool.spill")
            with compile_obs.LEDGER.scope("boundary", "page_spill"):
                kpg_d, vpg_d = self._read_page(self.cache, jnp.int32(page))
            kpg, vpg = np.asarray(kpg_d), np.asarray(vpg_d)
        except faults.InjectedFault:
            return False
        compile_obs.note_transfer("d2h", "kv_spill",
                                  int(kpg.nbytes + vpg.nbytes))
        ins.KV_SPILL.labels(direction="out").inc()
        host.put(key, (kpg, vpg))
        return True

    def _host_restore_install(self, payload) -> int | None:
        """restore_prefix's device-install callback: allocate a pool page
        and upload the host payload's (k, v) rows into it. Returns the page
        index the tree should graft, or None when the pool has no free page
        or an armed ``pool.restore`` fault fires — the caller stops
        grafting and the remaining suffix re-prefills as before. The host
        copy is untouched here (peek→install→take ordering: a failed
        install must not lose the only copy)."""
        pool = self.pool
        try:
            faults.fire("pool.restore")
            with pool._mu:
                if not pool._free:
                    return None
                page = pool._alloc_page()
        except faults.InjectedFault:
            return None
        kpg, vpg = payload
        with compile_obs.LEDGER.scope("boundary", "page_restore"):
            self.cache = self._write_page(self.cache, jnp.asarray(kpg),
                                          jnp.asarray(vpg), jnp.int32(page))
        compile_obs.note_transfer("h2d", "kv_restore",
                                  int(kpg.nbytes + vpg.nbytes))
        ins.KV_SPILL.labels(direction="in").inc()
        return page

    def chunk_cost_model(self):
        """Frozen obs/perf.ChunkCostModel pricing THIS engine's decode
        steps (the scheduler's roofline-attainment feed): the same per-op
        byte formula as experiments/hbm_traffic.py's offline tables, with
        `weight_bytes` = the REAL resident parameter bytes — an unquantized
        test model is priced as what it actually streams per step, not as a
        hypothetical Q40."""
        from dllama_tpu.obs.perf import ChunkCostModel
        from dllama_tpu.utils.profiling import params_nbytes

        try:
            cache_el = np.dtype(self.cache_dtype).itemsize
        except TypeError:  # ml_dtypes classes resolve via a jnp scalar
            cache_el = jnp.zeros((), self.cache_dtype).dtype.itemsize
        cfg = self.cfg
        return ChunkCostModel(
            n_layers=cfg.n_layers, dim=cfg.dim, hidden_dim=cfg.hidden_dim,
            kv_dim=cfg.kv_dim, head_size=cfg.head_size,
            n_kv_heads=cfg.n_kv_heads, vocab_size=cfg.vocab_size,
            seq_len=self.seq_len, weight_bytes=int(params_nbytes(self.params)),
            cache_bytes_per_el=int(cache_el),
            paged=self.kv_layout == "paged", page_size=self.page_size,
            # the routed attention path decides the paged pricing: the
            # gather fallback re-materializes the whole block-table view
            # through XLA every step, the kernel streams live pages only
            paged_impl=("gather" if self.attn_route == "paged_gather"
                        else "kernel"))

    # ------------------------------ compile contract & warmup (ISSUE 13)

    def _prefill_bucket_cap(self) -> int:
        """Widest prefill chunk add_step can emit: the CLI cap, bounded by
        the context (a prompt is < seq_len, so pow2_chunk never exceeds
        it)."""
        return max(1, min(self.max_prefill_chunk, self.seq_len - 1))

    @staticmethod
    def _n_in_range(lo: int, hi: int):
        """Contract allow-predicate for 'n{v}' keys: the decode/spec scan
        length can be row-limit-clamped to ANY value in [lo, hi] near the
        context edge — expected, but not worth a warm target each."""

        def pred(key: str) -> bool:
            try:
                v = int(key[1:]) if key.startswith("n") else -1
            except ValueError:
                return False
            return lo <= v <= hi

        return pred

    @staticmethod
    def _hybrid_in_range(pow2s, chunk_hi: int):
        """Allow-predicate for 'p{P}.n{v}' hybrid keys: any declared pow2
        slice × any row-limit-clamped decode length in [1, chunk]."""
        allowed = {int(p) for p in pow2s}

        def pred(key: str) -> bool:
            try:
                p_part, n_part = key.split(".", 1)
                p = int(p_part[1:]) if p_part.startswith("p") else -1
                v = int(n_part[1:]) if n_part.startswith("n") else -1
            except ValueError:
                return False
            return p in allowed and 1 <= v <= chunk_hi

        return pred

    def declare_serving_buckets(self, chunk: int,
                                hybrid_budget_hi: int = 0) -> None:
        """Declare the serving scheduler's expected compiled-shape
        universe into this engine's contract (idempotent): the fused
        decode scan at n∈{1, chunk} (any clamp in between allowed), the
        spec verify chunk ditto, and the hybrid launch at every pow2
        budget slice × the decode chunk — each × {plain, penalized}.
        Called by Scheduler.__init__ with its chunk and budget ceiling;
        direct library users who never declare keep classification at
        'undeclared' (no contract, no false alarms)."""
        from dllama_tpu.engine.kernel_select import pow2_buckets

        tag = self._bucket_tag
        chunk = max(1, int(chunk))
        fns = ["decode", "decode_pen"]
        if self.spec_k:
            fns += ["spec", "spec_pen"]
        for fn in fns:
            for v in sorted({1, chunk}):
                self.contract.declare(fn, f"n{v}", note=tag)
            self.contract.allow(fn, self._n_in_range(1, chunk),
                                key=f"n1..{chunk}")
        if self.supports_hybrid and hybrid_budget_hi > 0:
            cap = min(int(hybrid_budget_hi), self._prefill_bucket_cap())
            ps = pow2_buckets(cap)
            for fn in ("hybrid", "hybrid_pen"):
                for p in ps:
                    self.contract.declare(fn, f"p{p}.n{chunk}", note=tag)
                self.contract.allow(fn, self._hybrid_in_range(ps, chunk),
                                    key=f"p<={cap}.n1..{chunk}")

    def _ensure_counts(self) -> None:
        if self._counts is None:
            self._counts = jnp.zeros((self.n_slots, self.cfg.vocab_size),
                                     jnp.int32)

    def _warm_worklist(self, chunk: int, hybrid_budget_hi: int) -> list:  # dllama: allow[jit-scope] thunks dispatch under ledger.scope(fn, key) in warmup()
        """(fn, key, thunk) for every warm-target bucket. Each thunk
        dispatches the REAL jitted callable with inert operands — the
        all-inactive masks freeze every decode row (writes masked, keys/
        pos/token carries returned value-identical), and prefill slices
        write zeros into idle slot 0's rows, which nothing reads before
        a real admission overwrites them — so XLA compiles the exact
        serving shapes while the engine state stays semantically
        untouched."""
        from dllama_tpu.engine.kernel_select import pow2_buckets

        work: list = []
        B = self.n_slots
        carry: dict = {}

        def prefill_thunk(c):
            def run():
                self._sync_vectors()
                # warmup is unsharded-only, where _use_slot_prefill is
                # always True — the B=1 slot prefill IS the serving shape
                row, self.cache = self._prefill_slot(
                    self.params, self.cache, jnp.zeros((1, c), jnp.int32),
                    jnp.int32(0), jnp.int32(0), self.rope_cache)
                carry["logits"] = row
                if self.spec_k:
                    self.history = self._hist_write(
                        self.history, jnp.int32(0), jnp.int32(0),
                        jnp.zeros((c,), jnp.int32))
            return run

        for c in pow2_buckets(self._prefill_bucket_cap()):
            work.append(("prefill_chunk", f"m{c}", prefill_thunk(c)))

        def commit_thunk():
            row = carry.get("logits")
            if row is None:  # pragma: no cover - prefill thunks run first
                return
            _key, sub = jax.random.split(self._base_key)
            sample_logits(row, sub, jnp.float32(0.8), jnp.float32(0.9))

        work.append(("commit", "b1", commit_thunk))

        def decode_thunk(n, pen):
            def run():
                self._sync_vectors()
                args = (self.params, self.cache, self._last_dev[:, None],
                        self._pos_dev, self._active_dev, self._keys_dev,
                        self._temps_dev, self._topp_dev, n, self.rope_cache,
                        self._limit_dev)
                if pen:
                    self._ensure_counts()
                    (toks, self.cache, self._keys_dev, self._pos_dev,
                     self._last_dev, self._counts, _bad) = self._decode_pen(
                        *args, self._counts, self._pres_dev, self._freq_dev)
                else:
                    (toks, self.cache, self._keys_dev, self._pos_dev,
                     self._last_dev, _bad) = self._decode(*args)
                if self.spec_k:
                    # the per-chunk history backfill dispatches alongside
                    # every real decode chunk — warm its per-n shape too
                    self.history = self._hist_write_batch(
                        self.history, toks.T, self._pos_dev,
                        jnp.zeros(B, bool))
            return run

        for v in sorted({1, max(1, int(chunk))}):
            work.append(("decode", f"n{v}", decode_thunk(v, False)))
            work.append(("decode_pen", f"n{v}", decode_thunk(v, True)))

        if self.spec_k:
            def spec_thunk(n, pen):
                def run():
                    self._sync_vectors()
                    args = (self.params, self.cache, self.history,
                            self._last_dev, self._pos_dev, self._active_dev,
                            self._speck_dev, self._keys_dev, self._temps_dev,
                            self._topp_dev, self.rope_cache, self._limit_dev)
                    if pen:
                        self._ensure_counts()
                        (emits, advs, nxt, self.cache, self.history,
                         self._keys_dev, self._pos_dev, drafts, _bad,
                         self._counts) = self._spec_step_pen(
                            *args, self._counts, self._pres_dev,
                            self._freq_dev, n)
                    else:
                        (emits, advs, nxt, self.cache, self.history,
                         self._keys_dev, self._pos_dev, drafts, _bad) = \
                            self._spec_step(*args, n)
                    self._last_dev = nxt
                return run

            for v in sorted({1, max(1, int(chunk))}):
                work.append(("spec", f"n{v}", spec_thunk(v, False)))
                work.append(("spec_pen", f"n{v}", spec_thunk(v, True)))

        if self.supports_hybrid and hybrid_budget_hi > 0:
            cap = min(int(hybrid_budget_hi), self._prefill_bucket_cap())

            def hybrid_thunk(p, n, pen):
                def run():
                    self._sync_vectors()
                    args = (self.params, self.cache,
                            jnp.zeros((1, p), jnp.int32), jnp.int32(0),
                            jnp.int32(0), self._last_dev[:, None],
                            self._pos_dev, self._active_dev, self._keys_dev,
                            self._temps_dev, self._topp_dev, n,
                            self.rope_cache, self._limit_dev)
                    if pen:
                        self._ensure_counts()
                        (plog, toks, self.cache, self._keys_dev,
                         self._pos_dev, self._last_dev, self._counts,
                         _bad) = self._hybrid_pen(
                            *args, self._counts, self._pres_dev,
                            self._freq_dev)
                    else:
                        (plog, toks, self.cache, self._keys_dev,
                         self._pos_dev, self._last_dev, _bad) = \
                            self._hybrid(*args)
                return run

            nv = max(1, int(chunk))
            for p in pow2_buckets(cap):
                work.append(("hybrid", f"p{p}.n{nv}",
                             hybrid_thunk(p, nv, False)))
                work.append(("hybrid_pen", f"p{p}.n{nv}",
                             hybrid_thunk(p, nv, True)))
        return work

    def _warm_boundary_ops(self) -> None:
        """Precompile the small eager ops the admission/commit/release
        boundaries dispatch (surgical ``.at[row].set`` carry writes, PRNG
        key derivation): each is a once-per-process compile XLA would
        otherwise pay on the FIRST real request — exactly the TTFT the
        warmup pass exists to protect. Results are discarded; engine
        state is untouched."""
        self._pos_dev.at[0].set(0)
        self._last_dev.at[0].set(0)
        self._keys_dev.at[0].set(self._base_key)
        key = jax.random.PRNGKey(0)
        jax.random.split(jax.random.fold_in(key, 0))
        jnp.full((1,), 0, jnp.int32)
        if self._counts is not None:
            self._counts.at[0].set(0)

    def warmup(self, chunk: int = 4, hybrid_budget_hi: int = 0) -> dict:
        """``--warmup auto`` precompile pass: declare + dispatch every
        warm-target bucket once with inert operands, so the first REAL
        request pays zero compile (TTFT stops carrying XLA's cold-start).
        Must run at boot (no active slots; the serving scheduler calls it
        before its worker thread starts); unsharded engines only. Returns
        the warmup report `/debug/compile` serves — ``full_coverage``
        means every declared warm target really compiled."""
        if self.active.any():
            raise RuntimeError("warmup must run before any slot is active")
        if self._shardings is not None:
            raise ValueError("warmup supports unsharded engines (inert "
                             "operands would implicitly reshard on a mesh)")
        self.declare_serving_buckets(chunk, hybrid_budget_hi)
        ledger = compile_obs.LEDGER
        t_start = time.perf_counter()
        compiled, cached = 0, 0
        per_fn: dict[str, int] = {}
        had_counts = self._counts is not None
        work = self._warm_worklist(max(1, int(chunk)), hybrid_budget_hi)
        with ledger.warmup_phase():
            for fn, key, thunk in work:
                with ledger.scope(fn, key) as sc:
                    thunk()
                if sc.trace_s or sc.lower_s or sc.compile_s:
                    compiled += 1
                    per_fn[fn] = per_fn.get(fn, 0) + 1
                else:
                    cached += 1  # this process already compiled the shape
            self._warm_boundary_ops()
        # the report's seconds must cover compile AND the inert device
        # work, and serving must not start with warmup launches still
        # occupying the device stream
        jax.block_until_ready(self.cache.k)
        if not had_counts:
            # the pen-variant warm thunks allocated the [B, vocab] penalty
            # counts just to compile their shapes; only the cached XLA
            # executables are needed after warmup — restore the lazy
            # allocation so a penalty-free deployment pays no HBM for it
            self._counts = None
        report = {
            "mode": "auto",
            "buckets": len(work),
            "compiled": compiled,
            "cached": cached,
            "per_fn": per_fn,
            "seconds": round(time.perf_counter() - t_start, 3),
            "full_coverage": ledger.snapshot(entries=0)["contract"]["full"],
        }
        ledger.warmup_report = report
        log.info("warmup precompile: %d/%d buckets compiled, %d cached "
                 "(%.2fs; %s)", compiled, len(work), cached,
                 report["seconds"],
                 "full coverage" if report["full_coverage"]
                 else "coverage INCOMPLETE")
        return report

    def warm_restart(self) -> None:
        """Crash recovery WITHOUT a model reload: rebuild everything a
        failed chunk may have poisoned — the KV cache buffers (the jitted
        steps donate them, so an exception mid-step leaves them
        indeterminate), the page pool, and every per-slot decode vector —
        against the still-resident weights. The jitted callables are
        untouched (same shapes ⇒ no recompile), so a warm restart costs one
        cache allocation, not a checkpoint reload. The serving scheduler
        calls this under its --restart-max budget and then re-admits
        surviving requests (Scheduler._try_restart)."""
        if self.pool is not None:
            max_blocks = self.seq_len // self.page_size
            audit_flag = self.pool.audit_on_release
            self.pool = PagePool(self.pool.n_pages, self.page_size,
                                 self.n_slots, max_blocks)
            self.pool.audit_on_release = audit_flag
            self.pool.write_horizons = self._write_horizons
            self.cache = PagedKVCache.create(
                self.cfg, self.n_slots, self.pool.n_pages, self.page_size,
                self.cache_dtype, max_blocks)
            if self.radix is not None:
                # the radix tree's page ids died with the pool: rebuild it
                # EMPTY against the fresh allocator (never stale page refs);
                # cumulative hit accounting carries over
                from dllama_tpu.engine.radix import RadixCache

                self.radix = RadixCache(self.pool, carry_from=self.radix)
            if self.kv_host_pages > 0:
                # both tiers die together: a half-poisoned chunk may have
                # corrupted the very rows a spill preserved, and restoring
                # pre-crash bytes into a rebuilt pool would smuggle the
                # corruption past the restart
                self.pool.host = HostKVPool(self.kv_host_pages,
                                            self.page_size, self.pool._mu)
                self.radix.spill = self._host_spill
        else:
            self.cache = KVCache.create(self.cfg, self.n_slots,
                                        self.cache_dtype, self.seq_len)
        if self._shardings is not None:
            self.cache = self._shardings.put_cache(self.cache)
        self.pos[:] = 0
        self.active[:] = False
        self.last_token[:] = 0
        self.temperature[:] = 0.0
        self.topp[:] = 0.9
        self.presence[:] = 0.0
        self.frequency[:] = 0.0
        self.spec_k_slot[:] = 0
        self._counts = None
        self._last_dev = jnp.zeros(self.n_slots, jnp.int32)
        self._keys_dev = jnp.asarray(self.keys.copy())
        self._pos_dev = jnp.zeros(self.n_slots, jnp.int32)
        self._spec_inflight = 0  # any unconsumed chunk died with the crash
        self._t_last_consume = None
        if self.spec_k:
            self.history = jnp.full((self.n_slots, self.seq_len + 1), -1,
                                    jnp.int32)
        self._vec_dirty = True

    def copy_prefix_rows(self, src_slot: int, dst_slot: int, rows: int) -> None:
        """Cross-slot prefix share (the serving tier's RadixAttention-lite):
        make dst_slot's first `rows` KV rows identical to src_slot's, so an
        admission into dst can start_pos=rows off ANOTHER slot's cached
        prefix — e.g. every user of a serving deployment shares the system
        prompt's KV without recomputing it per slot. Dense: one fused
        on-device row copy. Paged: no row copy at all — full pages are
        SHARED by refcount (the dllama_kv_pages_shared gauge counts them)
        and only a partial boundary page is cloned; divergence later
        copy-on-writes (add_begin/prepare_admission)."""
        if not self.supports_cross_slot_copy:
            raise ValueError("cross-slot copy crosses dp shards; not supported "
                             "on batch-sharded meshes")
        assert not self.active[dst_slot], f"dst slot {dst_slot} is busy"
        if self.pool is not None:
            self.pool.share_prefix(src_slot, dst_slot, rows,
                                   self._pool_page_copy)
        else:
            with compile_obs.LEDGER.scope("boundary", "copy_rows"):
                self.cache = self._copy_rows(
                    self.cache, jnp.int32(src_slot), jnp.int32(dst_slot),
                    jnp.int32(rows)
                )
        if self.spec_k:
            # the shared prefix's token ids come along so the n-gram
            # proposer can draft from it in the new slot too (masked full-row
            # copy: one compile serves every prefix length)
            with compile_obs.LEDGER.scope("boundary", "hist"):
                self.history = self._hist_copy_prefix(
                    self.history, jnp.int32(src_slot), jnp.int32(dst_slot),
                    jnp.int32(rows))
        self.pos[dst_slot] = rows
        self._pos_dev = self._pos_dev.at[dst_slot].set(int(rows))
        self._vec_dirty = True

    # ------------------------------------------------------------------- api

    def free_slot(self) -> int | None:
        idle = np.flatnonzero(~self.active)
        return int(idle[0]) if idle.size else None

    def add_begin(self, slot: int, prompt_tokens: list[int], start_pos: int = 0,
                  req_id: str = "") -> "Admission":
        """Start an incremental admission: validate and position the slot,
        returning an Admission handle to pump with add_step / add_commit.
        Lets the serving scheduler interleave prefill chunks with decode
        chunks so a long prompt never stalls decoding batch-mates for its
        whole prefill (VERDICT r3 weak #5). The slot stays inactive (decode
        leaves it frozen) until add_commit. `req_id` (optional) tags the
        admission with the serving-tier request id for log correlation."""
        assert not self.active[slot], f"slot {slot} is busy"
        n = len(prompt_tokens)
        if n == 0:
            raise ValueError("prompt must be non-empty")
        if start_pos + n >= self.seq_len:
            raise ValueError(f"prompt ({start_pos}+{n}) exceeds seq_len {self.seq_len}")
        if self.pool is not None:
            # paged: drop the dead tail past the reused prefix, copy-on-write
            # a shared boundary page, and back every prompt row with a page.
            # Raises PageExhausted when the pool can't cover it — the serving
            # scheduler pre-checks admission_deficit() so it never gets here.
            self.pool.prepare_admission(slot, start_pos, start_pos + n,
                                        self._pool_page_copy)
        self.pos[slot] = start_pos
        self._pos_dev = self._pos_dev.at[slot].set(int(start_pos))
        self._vec_dirty = True
        return Admission(slot=slot, toks=np.asarray(prompt_tokens, np.int32),
                         req_id=req_id)

    def add_step(self, adm: "Admission") -> bool:
        """Prefill ONE power-of-two chunk of the admission's prompt; returns
        True when every prompt token's KV row is written."""
        faults.fire("engine.prefill")
        t0 = time.perf_counter()
        n, off, slot = len(adm.toks), adm.off, adm.slot
        c = pow2_chunk(n - off, self.max_prefill_chunk)
        if self.spec_k:
            # the n-gram proposer drafts from the prompt too — that's the
            # whole point of prompt lookup
            compile_obs.note_transfer("h2d", "history", c * 4)
            with compile_obs.LEDGER.scope("boundary", "hist"):
                self.history = self._hist_write(
                    self.history, jnp.int32(slot), jnp.int32(self.pos[slot]),
                    jnp.asarray(adm.toks[off : off + c]),
                )
        if self._use_slot_prefill:
            if self.pool is not None:
                # the slot's block table changed at add_begin (page alloc /
                # COW): refresh the device copy before the chunk reads it
                self._sync_vectors()
            ptoks = jnp.asarray(adm.toks[off : off + c][None])
            compile_obs.note_transfer("h2d", "prefill", int(ptoks.nbytes))
            with compile_obs.LEDGER.scope(
                    "prefill_chunk", f"m{c}",
                    sig=lambda: compile_obs.sig_of(ptoks)):
                row, self.cache = self._prefill_slot(
                    self.params, self.cache,
                    ptoks,
                    jnp.int32(slot),
                    jnp.int32(self.pos[slot]),
                    self.rope_cache,
                )
            adm.logits = row  # [1, V] — the slot's own row
        else:
            chunk = np.zeros((self.n_slots, c), np.int32)
            chunk[slot] = adm.toks[off : off + c]
            onehot = np.zeros(self.n_slots, bool)
            onehot[slot] = True
            # rope/cache row indexing needs every row's pos valid; frozen
            # rows pass their current pos (writes masked anyway).
            # .copy() is load-bearing on every host->device handoff here:
            # jnp.asarray can zero-copy ALIAS a numpy buffer on CPU, and
            # this engine mutates pos/active/last_token in place after
            # dispatching async device work — aliasing turns that into a
            # read/write race.
            pos_vec = jnp.asarray(self.pos.copy(), jnp.int32)
            chunk_dev = jnp.asarray(chunk)
            onehot_dev = jnp.asarray(onehot)
            compile_obs.note_transfer(
                "h2d", "prefill",
                int(chunk_dev.nbytes) + int(pos_vec.nbytes)
                + int(onehot_dev.nbytes))
            with compile_obs.LEDGER.scope(
                    "prefill_chunk", f"m{c}",
                    sig=lambda: compile_obs.sig_of(chunk_dev)):
                logits, self.cache = self._prefill_step(
                    self.params, self.cache,
                    chunk_dev,
                    pos_vec,
                    onehot_dev,
                    self.rope_cache,
                )
            adm.logits = logits[slot : slot + 1]
        self.pos[slot] += c
        adm.off += c
        self._vec_dirty = True
        # JAX dispatch is async: without a sync this is host dispatch time
        # only. The scheduler blocks on adm.logits whenever decoders would
        # stall, so serving-path samples ARE device-real; direct callers see
        # dispatch cost (still the admission stall they inflict on the host).
        ins.PREFILL_CHUNK_SECONDS.observe(time.perf_counter() - t0)
        ins.PREFILL_TOKENS.inc(c)
        return adm.off >= n

    def add_commit(self, adm: "Admission", temperature: float = 0.8,
                   topp: float = 0.9, seed: int | None = None,
                   presence: float = 0.0, frequency: float = 0.0,
                   spec_k: int | None = None) -> int:
        """Sample the first token from the finished admission and activate
        the slot. Must follow add_step returning True. `spec_k` is the
        slot's PER-REQUEST draft length for batched speculation (clamped to
        the engine's compile-time K; None keeps the engine default — the
        pre-ISSUE-11 engine-global behavior; 0 opts this slot out)."""
        assert adm.off >= len(adm.toks) and adm.logits is not None, "admission not pumped"
        slot = adm.slot
        if seed is not None:
            key = jax.random.PRNGKey(seed)
        else:
            key = jax.random.fold_in(self._base_key, self._admissions)
        self._admissions += 1
        key, sub = jax.random.split(key)
        self.keys[slot] = np.array(key)  # np.array copies (np.asarray of a jax
        # array is a read-only view; this row is mutated on every add)
        with compile_obs.LEDGER.scope(
                "commit", "b1",
                sig=lambda: compile_obs.sig_of(adm.logits)):
            tok = sample_logits(adm.logits, sub, jnp.float32(temperature),
                                jnp.float32(topp))
        first = int(np.asarray(tok)[0])
        compile_obs.note_transfer("d2h", "commit", int(tok.nbytes))
        self.active[slot] = True
        self.last_token[slot] = first
        self.temperature[slot] = temperature
        self.topp[slot] = topp
        self.presence[slot] = presence
        self.frequency[slot] = frequency
        # device carry: the host-auth vectors re-upload at the next dispatch,
        # but last_token/keys/pos are device-authoritative (the scans mutate
        # them with values the host can't mirror mid-flight), so the commit
        # writes just this slot's rows in place — other slots' carries stay
        # intact
        self._vec_dirty = True
        self._last_dev = self._last_dev.at[slot].set(first)
        self._keys_dev = self._keys_dev.at[slot].set(key)
        self._pos_dev = self._pos_dev.at[slot].set(int(self.pos[slot]))
        self.spec_k_slot[slot] = (min(int(spec_k), self.spec_k)
                                  if spec_k is not None else self.spec_k)
        if presence or frequency:
            if self._counts is None:
                self._counts = jnp.zeros((self.n_slots, self.cfg.vocab_size),
                                         jnp.int32)
            # fresh request: no sampled tokens yet (OpenAI counts exclude
            # the prompt, so recycled-slot state must not leak). Slots with
            # zero penalties never read their counts, so stale rows are
            # harmless and non-penalized admissions pay nothing.
            self._counts = self._counts.at[slot].set(0)
        if self.spec_k:
            # invariant: history[slot, pos] holds the slot's unfed token
            with compile_obs.LEDGER.scope("boundary", "hist"):
                self.history = self._hist_write(
                    self.history, jnp.int32(slot), jnp.int32(self.pos[slot]),
                    jnp.full((1,), first, jnp.int32),
                )
        return first

    def resume_commit(self, adm: "Admission", last_token: int, key,
                      temperature: float = 0.8, topp: float = 0.9,
                      presence: float = 0.0, frequency: float = 0.0,
                      counted=None, spec_k: int | None = None) -> None:
        """Activate a slot from warm-restart recovery. The admission
        re-prefilled prompt + already-emitted tokens EXCEPT the last one
        (a sampled token's KV row only exists once it is fed back); this
        commit installs that last token and the request's recorded PRNG
        `key` as the decode carry WITHOUT sampling anything new — the
        resumed stream's next token is exactly what the uninterrupted run
        would have produced. `counted` (penalized requests only) lists the
        tokens fed so far, to rebuild the on-device occurrence counts."""
        assert adm.off >= len(adm.toks), "admission not pumped"
        slot = adm.slot
        self.keys[slot] = np.asarray(key)
        self.active[slot] = True
        self.last_token[slot] = int(last_token)
        self.temperature[slot] = temperature
        self.topp[slot] = topp
        self.presence[slot] = presence
        self.frequency[slot] = frequency
        self._vec_dirty = True
        self._last_dev = self._last_dev.at[slot].set(int(last_token))
        self._keys_dev = self._keys_dev.at[slot].set(jnp.asarray(self.keys[slot]))
        self._pos_dev = self._pos_dev.at[slot].set(int(self.pos[slot]))
        self.spec_k_slot[slot] = (min(int(spec_k), self.spec_k)
                                  if spec_k is not None else self.spec_k)
        if presence or frequency:
            if self._counts is None:
                self._counts = jnp.zeros((self.n_slots, self.cfg.vocab_size),
                                         jnp.int32)
            row = np.zeros(self.cfg.vocab_size, np.int32)
            if counted:
                np.add.at(row, np.asarray(counted, np.int64), 1)
            self._counts = self._counts.at[slot].set(jnp.asarray(row))
        if self.spec_k:
            # invariant: history[slot, pos] holds the slot's unfed token
            with compile_obs.LEDGER.scope("boundary", "hist"):
                self.history = self._hist_write(
                    self.history, jnp.int32(slot), jnp.int32(self.pos[slot]),
                    jnp.full((1,), int(last_token), jnp.int32),
                )

    def add(self, slot: int, prompt_tokens: list[int], temperature: float = 0.8,
            topp: float = 0.9, start_pos: int = 0, seed: int | None = None,
            presence: float = 0.0, frequency: float = 0.0,
            abort=None) -> int:
        """Prefill `prompt_tokens` into `slot` (rows from start_pos — pass a
        cached-prefix length to reuse earlier rows, NaiveCache-style) and
        sample the first token. Other slots are untouched (masked writes).

        `seed` pins this slot's PRNG stream — same seed + prompt + params =>
        same continuation, independent of batch-mates (VERDICT r1 weak #5).
        One-shot wrapper over add_begin / add_step / add_commit.

        `abort` (optional zero-arg callable, e.g. a threading.Event's
        is_set) is polled between prefill chunks: a multi-chunk admission of
        a long prompt can be cancelled cooperatively instead of running to
        completion — raises AdmissionAborted and leaves the slot inactive
        with its cached rows invalid (do not prefix-reuse them). For direct
        library callers of add(); the serving scheduler drives the chunked
        add_begin/add_step path and checks its own cancel flag per chunk."""
        adm = self.add_begin(slot, prompt_tokens, start_pos)
        while not self.add_step(adm):
            if abort is not None and abort():
                raise AdmissionAborted(
                    f"admission into slot {slot} aborted at "
                    f"{adm.off}/{len(adm.toks)} prompt tokens")
        return self.add_commit(adm, temperature, topp, seed,
                               presence=presence, frequency=frequency)

    def _sync_vectors(self) -> None:  # dllama: allow[transfer-note] ONE aggregated note_transfer("h2d","vectors",nbytes) at the end of the fan accounts every upload above it
        """Refresh the device copies of the host-authoritative per-slot
        vectors. A no-op in steady-state decode: only admission/commit/
        release/copy mark them dirty, so the old per-chunk six-array upload
        fan happens at most once per boundary. `.copy()` is load-bearing on
        every upload: jnp.asarray can zero-copy ALIAS a numpy buffer on CPU,
        and these host arrays are mutated in place after async dispatches —
        aliasing would turn that into a read/write race."""
        if not self._vec_dirty:
            return
        # NOTE pos is NOT uploaded here: like last_token/keys it is
        # device-authoritative (spec cycles advance it by data-dependent
        # counts), so host mutation sites write their slot's _pos_dev row
        # surgically instead — a bulk upload could clobber the carry of an
        # in-flight overlapped spec cycle
        self._active_dev = jnp.asarray(self.active.copy())
        self._temps_dev = jnp.asarray(self.temperature.copy())
        self._topp_dev = jnp.asarray(self.topp.copy())
        self._pres_dev = jnp.asarray(self.presence.copy())
        self._freq_dev = jnp.asarray(self.frequency.copy())
        self._speck_dev = jnp.asarray(self.spec_k_slot.copy())
        self._limit_dev = jnp.asarray(self._row_limit())
        nbytes = (int(self._active_dev.nbytes) + int(self._temps_dev.nbytes)
                  + int(self._topp_dev.nbytes) + int(self._pres_dev.nbytes)
                  + int(self._freq_dev.nbytes) + int(self._speck_dev.nbytes)
                  + int(self._limit_dev.nbytes))
        if self.pool is not None:
            # block tables are host-authoritative like pos/active: refresh the
            # cache's device copy at the same boundaries (the pool arrays are
            # the mirrors; .copy() for the same aliasing reason as above)
            tables = jnp.asarray(self.pool.tables.copy(), jnp.int32)
            nbytes += int(tables.nbytes)
            self.cache = PagedKVCache(self.cache.k, self.cache.v, tables)
        # boundary upload accounting (ISSUE 13): this fan is the ONLY
        # legitimate steady-path upload site, and it fires at boundaries
        # only — a per-chunk rate here is the device-resident-state
        # invariant breaking (the transfer-guard strict mode would raise)
        compile_obs.note_transfer("h2d", "vectors", nbytes)
        self._vec_dirty = False

    def decode_dispatch(self, n: int, spec: bool = False) -> DecodeChunk:
        """Dispatch one fused n-step decode chunk WITHOUT waiting for its
        tokens. The jitted scan threads the device-resident carry (cache,
        last_token, pos, PRNG keys) to itself, so in steady state this
        uploads no host arrays at all and returns immediately (JAX dispatch
        is async) — the caller overlaps host scheduling work with the
        chunk's device compute and blocks only in decode_consume.

        ``spec=True`` dispatches a fused spec CHUNK of n verify cycles in
        one lax.scan'd launch instead (ISSUE 11): the returned chunk's
        `toks` is the stacked per-cycle emit tensor [n, B, K+1] and its
        per-slot counts materialize at decode_consume (which flattens the
        accepted runs to the plain [rows, B] layout) — so the serving
        scheduler's overlapped pipeline composes with speculation (chunk
        N+1's propose/verify launches off chunk N's device carry). A
        successor dispatched off an in-flight spec chunk must itself be
        spec (the host position mirror lags the data-dependent advance
        until consumption; the scheduler drains the pipeline on mode
        switches).

        Slots whose cache fills mid-chunk freeze per-row at seq_len (token
        repeats, no advance) instead of clamping the whole batch's chunk to
        the fullest slot's room; `DecodeChunk.advance` records each slot's
        true row count. Raises only when no active slot has any room."""
        faults.fire("engine.decode")
        if spec:
            if not self.spec_k:
                raise ValueError("engine built with spec=0")
            if not self.active.any():
                raise ValueError("no active slots")
            return self._spec_dispatch(max(1, int(n)))
        if not self.active.any():
            raise ValueError("no active slots")
        self._alloc_decode_rows(n)
        limit = self._row_limit()
        room = limit[self.active] - self.pos[self.active]
        n = min(n, int(room.max()))
        if n <= 0:
            raise ValueError("every active slot is at its row limit "
                             "(seq_len, or an exhausted page pool); "
                             "release first")
        self._sync_vectors()
        pos_before = self._pos_dev
        args = (
            self.params, self.cache,
            self._last_dev[:, None],
            self._pos_dev,
            self._active_dev,
            self._keys_dev,
            self._temps_dev,
            self._topp_dev,
            n,
            self.rope_cache,
            self._limit_dev,
        )
        t0 = time.perf_counter()
        t_disp = time.monotonic()  # trace clock; ~free next to perf_counter
        # steady-state contract, both halves (ISSUE 13): the compile scope
        # attributes any trace/compile this launch causes to its shape
        # bucket, and the transfer guard (strict mode) turns an implicit
        # host->device upload into an error — every operand below is a
        # device-resident carry, so a clean engine trips neither.
        guard = compile_obs.h2d_guard(self.transfer_guard)
        if self._counts is not None and (
            (self.presence[self.active] != 0).any()
            or (self.frequency[self.active] != 0).any()
        ):
            with compile_obs.LEDGER.scope(
                    "decode_pen", f"n{n}",
                    sig=lambda: compile_obs.sig_of(*args[2:])), guard:
                (toks, self.cache, self._keys_dev, self._pos_dev,
                 self._last_dev, self._counts, bad) = self._decode_pen(
                    *args, self._counts, self._pres_dev, self._freq_dev)
        else:
            with compile_obs.LEDGER.scope(
                    "decode", f"n{n}",
                    sig=lambda: compile_obs.sig_of(*args[2:])), guard:
                (toks, self.cache, self._keys_dev, self._pos_dev,
                 self._last_dev, bad) = self._decode(*args)
        start_pos = self.pos.copy()
        active = self.active.copy()
        advance = np.where(
            active, np.clip(limit - start_pos, 0, n), 0
        ).astype(np.int32)
        bad_inject = None
        if faults.flag("decode.nan"):
            # drill the NaN guard without needing genuinely poisoned
            # weights: flag the lowest active slot as if its logits went
            # non-finite — the scheduler's consume path fails that request
            bad_inject = np.zeros(self.n_slots, bool)
            bad_inject[int(np.flatnonzero(active)[0])] = True
        if self.spec_k:
            # history backfill rides the device stream off the
            # not-yet-materialized tokens (no host round-trip). Rows whose
            # full chunk would spill past the history row are skipped: their
            # slot froze mid-chunk at seq_len, where spec_eligible freezes it
            # anyway — a draft from slightly stale history is only a
            # proposal, verify rejects it. The mask is computed ON DEVICE
            # off the dispatch-time carry (identical values to the old host
            # mask for every active row — _active_dev/_pos_dev are synced
            # mirrors here), so spec engines keep steady-state decode at
            # literally zero host->device uploads (ISSUE 13).
            fits_dev = self._active_dev & (pos_before + 1 + n
                                           <= self.seq_len + 1)
            with compile_obs.LEDGER.scope("boundary", "hist_batch"):
                self.history = self._hist_write_batch(
                    self.history, toks.T, pos_before, fits_dev)
        # the host pos mirror advances arithmetically — exactly what the scan
        # computes — so it stays current without waiting for the tokens
        self.pos += advance
        self.chunk_seq += 1
        return DecodeChunk(toks=toks, n=n, start_pos=start_pos, active=active,
                           advance=advance, t0=t0, seq=self.chunk_seq,
                           t_disp=t_disp, bad=bad, bad_inject=bad_inject)

    @property
    def supports_hybrid(self) -> bool:
        """Whether hybrid_dispatch can run: the fused step's prefill half
        is the single-slot B=1 forward, which a dp-sharded batch axis
        cannot slice (same gate as _use_slot_prefill)."""
        return self._use_slot_prefill

    def hybrid_dispatch(self, n: int, adm: "Admission",
                        budget: int) -> DecodeChunk:
        """Dispatch ONE fused hybrid step (ISSUE 12): an n-step decode
        chunk for the active slots AND up to `budget` prompt tokens of the
        in-flight admission `adm`, in a single device launch. The prefill
        slice is pow2-quantized (same compile-set discipline as add_step)
        and capped at max_prefill_chunk; `adm.off`/`adm.logits` advance
        exactly as a same-sized add_step would, so add_commit /
        resume_commit work unchanged once the admission is fully pumped.
        Decode semantics are identical to decode_dispatch (per-row freeze,
        NaN guard, overlap pipelining off the device carry) — the
        admitting slot is inactive in the decode mask and every attention
        read is per-slot, so batch-mates' token streams are BIT-EXACT vs
        the phase-split path. Returns a DecodeChunk whose hybrid_slot /
        hybrid_tokens record the fused admission work."""
        faults.fire("engine.decode")
        faults.fire("engine.prefill")
        if not self.supports_hybrid:
            raise ValueError("hybrid step needs an unsharded batch axis "
                             "(dp meshes keep phase-split admission)")
        slot = adm.slot
        assert not self.active[slot], f"slot {slot} is busy"
        if not self.active.any():
            raise ValueError("no active slots to fuse with; pump the "
                             "admission with add_step instead")
        remaining = len(adm.toks) - adm.off
        if remaining <= 0:
            raise ValueError("admission already fully pumped")
        c = pow2_chunk(min(max(1, int(budget)), remaining),
                       self.max_prefill_chunk)
        self._alloc_decode_rows(n)
        limit = self._row_limit()
        room = limit[self.active] - self.pos[self.active]
        n = min(n, int(room.max()))
        if n <= 0:
            raise ValueError("every active slot is at its row limit "
                             "(seq_len, or an exhausted page pool); "
                             "release first")
        ppos = int(self.pos[slot])
        if self.spec_k:
            # prompt tokens feed the n-gram proposer exactly like add_step
            compile_obs.note_transfer("h2d", "history", c * 4)
            with compile_obs.LEDGER.scope("boundary", "hist"):
                self.history = self._hist_write(
                    self.history, jnp.int32(slot), jnp.int32(ppos),
                    jnp.asarray(adm.toks[adm.off : adm.off + c]),
                )
        self._sync_vectors()
        pos_before = self._pos_dev
        ptoks = jnp.asarray(adm.toks[adm.off : adm.off + c][None])
        compile_obs.note_transfer("h2d", "prefill", int(ptoks.nbytes))
        args = (
            self.params, self.cache,
            ptoks,
            jnp.int32(slot),
            jnp.int32(ppos),
            self._last_dev[:, None],
            self._pos_dev,
            self._active_dev,
            self._keys_dev,
            self._temps_dev,
            self._topp_dev,
            n,
            self.rope_cache,
            self._limit_dev,
        )
        t0 = time.perf_counter()
        t_disp = time.monotonic()
        # same steady-state contract as decode_dispatch: the prefill slice
        # upload happened above (an expected, counted boundary transfer);
        # the fused launch itself takes only device-resident operands, so
        # the strict transfer guard holds through hybrid serving too
        guard = compile_obs.h2d_guard(self.transfer_guard)
        if self._counts is not None and (
            (self.presence[self.active] != 0).any()
            or (self.frequency[self.active] != 0).any()
        ):
            with compile_obs.LEDGER.scope(
                    "hybrid_pen", f"p{c}.n{n}",
                    sig=lambda: compile_obs.sig_of(ptoks, *args[5:])), guard:
                (plog, toks, self.cache, self._keys_dev, self._pos_dev,
                 self._last_dev, self._counts, bad) = self._hybrid_pen(
                    *args, self._counts, self._pres_dev, self._freq_dev)
        else:
            with compile_obs.LEDGER.scope(
                    "hybrid", f"p{c}.n{n}",
                    sig=lambda: compile_obs.sig_of(ptoks, *args[5:])), guard:
                (plog, toks, self.cache, self._keys_dev, self._pos_dev,
                 self._last_dev, bad) = self._hybrid(*args)
        adm.logits = plog  # [1, V] — materializes with the chunk
        adm.off += c
        start_pos = self.pos.copy()
        active = self.active.copy()
        # the admitting slot's host pos advances with its slice (the device
        # pos carry keeps its stale inactive row — add_commit/resume_commit
        # write it surgically at activation, same contract as add_step)
        self.pos[slot] += c
        advance = np.where(
            active, np.clip(limit - start_pos, 0, n), 0
        ).astype(np.int32)
        bad_inject = None
        if faults.flag("decode.nan"):
            bad_inject = np.zeros(self.n_slots, bool)
            bad_inject[int(np.flatnonzero(active)[0])] = True
        if self.spec_k:
            # device-side fits mask, same reasoning as decode_dispatch
            fits_dev = self._active_dev & (pos_before + 1 + n
                                           <= self.seq_len + 1)
            with compile_obs.LEDGER.scope("boundary", "hist_batch"):
                self.history = self._hist_write_batch(
                    self.history, toks.T, pos_before, fits_dev)
        self.pos += advance
        self.chunk_seq += 1
        ins.PREFILL_TOKENS.inc(c)
        return DecodeChunk(toks=toks, n=n, start_pos=start_pos, active=active,
                           advance=advance, t0=t0, seq=self.chunk_seq,
                           t_disp=t_disp, bad=bad, bad_inject=bad_inject,
                           hybrid_slot=slot, hybrid_tokens=c)

    def _spec_dispatch(self, n_cycles: int) -> DecodeChunk:
        """Dispatch one fused spec CHUNK (decode_dispatch's spec=True
        body): n_cycles propose/verify cycles in a single lax.scan'd
        launch — the speculation analog of the fused n-step decode chunk,
        amortizing host dispatch overhead identically — and return WITHOUT
        waiting: the emitted tokens and per-slot counts are data-dependent
        device values that materialize in decode_consume. Eligibility,
        per-slot draft clamps, and the write mask are all resolved on
        device from the carried position EVERY cycle, so a chunk pipelined
        off an in-flight predecessor stays exact even though the host
        mirrors lag it."""
        k = self.spec_k
        # page top-up + shared-page COW for this chunk — doubled ONLY when
        # a predecessor spec chunk is still unconsumed (then the host pos
        # mirror lags the device carry by up to its rows; an under-backed
        # row merely freezes per-row on device, this keeps that the rare
        # case). Boundary/lockstep dispatches have an exact mirror and
        # must not double the pool pressure.
        lag = 2 if self._spec_inflight else 1
        self._alloc_decode_rows(lag * n_cycles * (k + 1))
        if not self.spec_eligible().any():
            raise ValueError(
                "no active slot is spec-eligible (needs room for K+1 "
                "rows); use decode() or release the full slots")
        self._sync_vectors()
        start_dev = self._pos_dev
        t0 = time.perf_counter()
        t_disp = time.monotonic()
        args = (
            self.params, self.cache, self.history,
            self._last_dev,
            self._pos_dev,
            self._active_dev,
            self._speck_dev,
            self._keys_dev,
            self._temps_dev,
            self._topp_dev,
            self.rope_cache,
            self._limit_dev,
        )
        guard = compile_obs.h2d_guard(self.transfer_guard)
        if self._counts is not None and (
            (self.presence[self.active] != 0).any()
            or (self.frequency[self.active] != 0).any()
        ):
            with compile_obs.LEDGER.scope(
                    "spec_pen", f"n{n_cycles}",
                    sig=lambda: compile_obs.sig_of(*args[3:])), guard:
                (emits, advs, nxt, self.cache, self.history, self._keys_dev,
                 self._pos_dev, drafts, bad, self._counts) = \
                    self._spec_step_pen(*args, self._counts, self._pres_dev,
                                        self._freq_dev, n_cycles)
        else:
            with compile_obs.LEDGER.scope(
                    "spec", f"n{n_cycles}",
                    sig=lambda: compile_obs.sig_of(*args[3:])), guard:
                (emits, advs, nxt, self.cache, self.history, self._keys_dev,
                 self._pos_dev, drafts, bad) = self._spec_step(*args, n_cycles)
        self._last_dev = nxt
        self._spec_inflight += 1
        active = self.active.copy()
        bad_inject = None
        if faults.flag("decode.nan"):
            bad_inject = np.zeros(self.n_slots, bool)
            bad_inject[int(np.flatnonzero(active)[0])] = True
        self.chunk_seq += 1
        # start_pos/advance are host ESTIMATES until consumption (the chunk
        # in flight below us decides the truth): advance's lower bound — one
        # bonus token per active row — feeds the scheduler's conservative
        # budget check, and both are overwritten in decode_consume
        return DecodeChunk(toks=emits, n=n_cycles,
                           start_pos=self.pos.copy(), active=active,
                           advance=np.where(active, 1, 0).astype(np.int32),
                           t0=t0, seq=self.chunk_seq, t_disp=t_disp, bad=bad,
                           bad_inject=bad_inject, spec=True, adv_dev=advs,
                           drafted_dev=drafts, start_dev=start_dev)

    def decode_consume(self, chunk: DecodeChunk) -> np.ndarray:
        """Block until the chunk's tokens are on host; fold them into the
        host mirrors and the chunk-timing metrics. Returns tokens [n, B]
        (frozen/mid-chunk-frozen slots repeat their last token — callers use
        chunk.advance for per-slot counts).

        Spec chunks (decode_dispatch(spec=True)) additionally materialize
        their data-dependent per-slot counts here: `chunk.advance` and
        `chunk.start_pos` are overwritten with the real values, the host
        pos/last_token mirrors are fixed up (slots released while the cycle
        was in flight keep their rewound state — their rows here are the
        one-chunk stop overrun), and the acceptance telemetry
        (dllama_spec_* series) is recorded."""
        toks = np.asarray(chunk.toks)
        compile_obs.note_transfer("d2h", "decode_tokens", int(toks.nbytes))
        # the transfer above is the device sync: observing here (not at
        # dispatch) keeps DECODE_CHUNK_SECONDS device-real under overlapped
        # consumption. The clock starts at the later of the chunk's dispatch
        # and the previous chunk's consumption: an overlapped dispatch lands
        # while its predecessor still runs, and billing it the predecessor's
        # tail would read as ~2x chunk time.
        now = time.perf_counter()
        start = (chunk.t0 if self._t_last_consume is None
                 else max(chunk.t0, self._t_last_consume))
        ins.DECODE_CHUNK_SECONDS.observe(now - start)
        chunk.device_s = now - start  # the roofline gauge's denominator
        self._t_last_consume = now
        tr = trace.TRACER
        if chunk.spec:
            # toks here is the stacked per-cycle emit [m, B, k+1]; flatten
            # each slot's accepted runs (cycle-major) into the same
            # [rows, B] layout a decode chunk returns, so the scheduler's
            # emit loop serves both chunk kinds unchanged
            self._spec_inflight = max(0, self._spec_inflight - 1)
            emits = toks
            advs = np.asarray(chunk.adv_dev).astype(np.int32)  # [m, B]
            drafted = np.asarray(chunk.drafted_dev).astype(np.int32)
            chunk.start_pos = np.asarray(chunk.start_dev).astype(np.int32)
            # accounted immediately after the three materializations above
            # (the transfer-note rule windows the annotation to its site)
            compile_obs.note_transfer(
                "d2h", "spec_counts",
                int(advs.nbytes) + int(drafted.nbytes)
                + int(chunk.start_pos.nbytes))
            total = advs.sum(axis=0).astype(np.int32)  # [B]
            chunk.advance = total
            chunk.adv_cycles = advs
            m_cycles, b = advs.shape
            # flatten each slot's accepted runs (cycle-major) with one
            # boolean-mask gather per emitting slot — C-speed, not an
            # O(cycles x slots) Python concat loop on the consume hot path
            keep = (np.arange(emits.shape[2])[None, None, :]
                    < advs[:, :, None])  # [m, B, k+1]
            out = np.zeros((max(1, int(total.max(initial=0))), b), np.int32)
            for s in np.flatnonzero(total):
                out[: total[s], s] = emits[:, s, :][keep[:, s, :]]
            # host mirror fixup: the chunk's advance was data-dependent, so
            # the mirrors could not move at dispatch. Slots released while
            # it was in flight (EOS found consuming the predecessor) keep
            # their rewound pos — their rows here are discarded overrun.
            upd = chunk.active & self.active
            self.pos[upd] = chunk.start_pos[upd] + total[upd]
            emitted = np.flatnonzero(upd & (total > 0))
            if emitted.size:
                self.last_token[emitted] = out[total[emitted] - 1, emitted]
            # acceptance telemetry, single-site: every consumed verify
            # cycle lands in the dllama_spec_* series AND the engine totals
            acc = advs - 1
            msk = drafted > 0
            n_drafted, n_acc = int(drafted.sum()), int(acc[msk].sum())
            n_emit = int(total.sum())
            self._spec_totals["cycles"] += m_cycles
            self._spec_totals["drafted"] += n_drafted
            self._spec_totals["accepted"] += n_acc
            self._spec_totals["emitted"] += n_emit
            ins.SPEC_CYCLES.inc(m_cycles)
            ins.SPEC_TOKENS.labels(kind="drafted").inc(n_drafted)
            ins.SPEC_TOKENS.labels(kind="accepted").inc(n_acc)
            ins.SPEC_TOKENS.labels(kind="emitted").inc(n_emit)
            # one bulk histogram update per distinct accepted length, not a
            # Python observe() per (cycle, row) sample
            for val, cnt in enumerate(np.bincount(acc[msk])):
                ins.SPEC_ACCEPTED_LENGTH.observe_n(val, int(cnt))
            ins.BATCH_OCCUPANCY.observe(int((total > 0).sum()))
            if tr.enabled:
                tr.span_at("decode.spec", chunk.t_disp, tr.now(),
                           cat="decode", track="device", chunk=chunk.seq,
                           cycles=m_cycles,
                           occupancy=int((total > 0).sum()),
                           emitted=n_emit, accepted=n_acc)
            return out
        ins.BATCH_OCCUPANCY.observe(int(chunk.active.sum()))
        if tr.enabled:
            # the chunk's device-side window: dispatch -> tokens on host.
            # Under the overlapped pipeline this span brackets the NEXT
            # chunk's dispatch span — the overlap, visible in Perfetto.
            tr.span_at("decode.device", chunk.t_disp, tr.now(),
                       cat="decode", track="device", chunk=chunk.seq,
                       n=chunk.n, occupancy=int(chunk.active.sum()))
        self.last_token[chunk.active] = toks[-1, chunk.active]
        return toks

    def decode(self, n: int) -> np.ndarray:
        """n fused decode steps across all active slots; returns tokens
        [n', B] with n' = min(n, the roomiest active slot's room). Slots
        that hit seq_len mid-chunk freeze per-row (their trailing tokens
        repeat) while batch-mates keep the full chunk — callers track
        per-slot state. Lockstep wrapper over decode_dispatch/consume."""
        return self.decode_consume(self.decode_dispatch(n))

    def spec_eligible(self) -> np.ndarray:
        """bool[B], host view: slots the next spec cycle will ADVANCE —
        active with K+1 backed rows below their row limit. Repetition
        penalties no longer freeze a slot (the counts-carrying
        _spec_step_pen variant serves them a bit-exact penalized token per
        cycle), and sampled / spec_k_slot==0 rows advance exactly 1 token
        per cycle — only rows at the context edge or an exhausted page
        pool freeze, and the scheduler alternates plain decode chunks in
        for exactly those. The authoritative per-row freeze is recomputed
        ON DEVICE from the carried position inside the cycle (this host
        view is exact at chunk boundaries, a gating heuristic while a
        cycle is in flight)."""
        room_ok = self.pos + self.spec_k + 1 <= self._row_limit()
        return self.active & room_ok

    def spec_draft_k(self) -> np.ndarray:
        """i32[B], host view: each slot's effective draft length for the
        next cycle — 0 for sampled, penalized, spec_k_slot==0, and
        ineligible rows. The serving scheduler speculates only while some
        live slot can actually accept drafts (any entry > 0); everyone
        else just rides the cycle one token at a time."""
        pen = (self.presence != 0) | (self.frequency != 0)
        return np.where(
            self.spec_eligible() & (self.temperature == 0.0) & ~pen,
            np.minimum(self.spec_k_slot, self.spec_k), 0).astype(np.int32)

    def spec_stats(self) -> dict | None:
        """Cumulative acceptance accounting (None when the engine was built
        spec=0) — the host-side mirror of the dllama_spec_* series:
        cycles/drafted/accepted/emitted plus the derived tokens-per-cycle
        speedup and mean accepted draft length."""
        if not self.spec_k:
            return None
        t = dict(self._spec_totals)
        t["k"] = self.spec_k
        cycles = t["cycles"]
        t["tokens_per_cycle"] = (round(t["emitted"] / cycles, 3)
                                 if cycles else None)
        t["accept_mean"] = (round(t["accepted"] / t["drafted"], 3)
                            if t["drafted"] else None)
        return t

    def spec_step(self) -> tuple[np.ndarray, np.ndarray]:
        """One speculative verify cycle across the batch, LOCKSTEP (the
        dispatch + consume of decode_dispatch(spec=True) in place): returns
        (tokens [B, K+1], counts [B]) where each active slot emitted
        tokens[i, :counts[i]] this cycle — 1..K+1 exact-greedy tokens for a
        temperature==0 slot up to its own spec_k_slot draft length, exactly
        1 exactly-sampled (or penalized) token otherwise. Costs ~one decode
        step (the forward is HBM-bound; K+1 rows ride the same weight
        stream), so greedy acceptance multiplies batch tok/s. Only slots
        without a K+1-row window below their limit freeze (advance them
        with decode()); sampled, penalized, and spec_k_slot==0 slots all
        ride the cycle one token at a time. The serving scheduler uses the
        split dispatch/consume form directly so cycles compose with the
        overlapped pipeline; this wrapper serves direct library callers and
        the bench. The reference decodes strictly one token per forward per
        request (dllama.cpp:69-88) and its server has no batching at all —
        this is both lifted to the serving tier at once."""
        chunk = self.decode_dispatch(1, spec=True)
        toks = self.decode_consume(chunk)  # [rows, B], rows = max advance
        emit = np.zeros((self.n_slots, self.spec_k + 1), np.int32)
        emit[:, : toks.shape[0]] = toks.T
        return emit, chunk.advance

    def release(self, slot: int, keep_rows: int | None = None) -> None:
        """Free a slot. keep_rows rewinds pos to the valid prefix (mid-chunk
        stop — including tokens a dispatched-but-unconsumed chunk overran
        past a stop: the rewound rows are never read, like rejected spec
        drafts), preserving the slot's cache for NaiveCache-style reuse.
        On the paged layout the rewind also RETURNS the tail pages past the
        kept prefix to the pool (refcount-aware: a page shared with another
        slot just loses this slot's reference); keep_rows=None means the
        rows are unspecified — every page goes back."""
        self.active[slot] = False
        self.presence[slot] = self.frequency[slot] = 0.0
        self.spec_k_slot[slot] = 0
        if keep_rows is not None:
            self.pos[slot] = keep_rows
            if self.pool is not None:
                self.pool.free_tail(slot, keep_rows)
        elif self.pool is not None:
            self.pool.free_tail(slot, 0)
            self.pos[slot] = 0
        self._pos_dev = self._pos_dev.at[slot].set(int(self.pos[slot]))
        if self.pool is not None and self.pool.audit_on_release:
            # DLLAMA_POOL_AUDIT=1 (armed suite-wide by tests/conftest.py):
            # any refcount/free-list corruption fails AT the release that
            # caused it instead of surfacing as a mystery pages-leak later
            self.pool.audit()
        self._vec_dirty = True
