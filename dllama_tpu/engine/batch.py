"""Continuous-batching engine: independent sequences sharing one compiled step.

The reference's API server is single-request, blocking (dllama-api.cpp:522-533
— SURVEY.md §7.4.6 calls this out as the tier to replace). This engine keeps
B cache *slots*, each with its own position, so requests can join (prefill one
slot while others hold), decode together in fused chunks, and leave at EOS —
the scheduling core of continuous batching. Mechanics:

* positions are an i32[B] vector: rope rows gathered per row, KV writes are
  per-row scatters, the causal mask is per-row (models/llama.forward).
* an `active` bool[B] masks cache writes: a prefill touches only the joining
  slot; finished slots stay frozen while others decode.
* sampling params are per-slot vectors (sampling.sample_logits broadcasts),
  so mixed-temperature batches share one compiled decode graph.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.engine.sampling import sample_logits
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, forward


class BatchEngine:
    def __init__(
        self,
        cfg: LlamaConfig,
        params,
        n_slots: int = 4,
        cache_dtype=jnp.bfloat16,
        max_seq_len: int | None = None,
        max_prefill_chunk: int = 128,
        seed: int = 0,
        shardings=None,  # parallel/sharding.LlamaShardings: multi-chip serving
    ):
        from dllama_tpu.ops.layers import build_rope_cache

        self.cfg = cfg
        self.params = params
        self.n_slots = n_slots
        self.seq_len = min(max_seq_len or cfg.seq_len, cfg.seq_len)
        self.max_prefill_chunk = max_prefill_chunk
        self.rope_cache = build_rope_cache(cfg, self.seq_len)
        self.cache = KVCache.create(cfg, n_slots, cache_dtype, self.seq_len)
        if shardings is not None:
            self.params = shardings.put_params(self.params)
            self.cache = shardings.put_cache(self.cache)
            self.rope_cache = shardings.put_replicated(self.rope_cache)
        self.pos = np.zeros(n_slots, np.int32)  # next cache row per slot
        self.active = np.zeros(n_slots, bool)  # slot is decoding
        self.last_token = np.zeros(n_slots, np.int32)
        self.temperature = np.zeros(n_slots, np.float32)
        self.topp = np.full(n_slots, 0.9, np.float32)
        self.key = jax.random.PRNGKey(seed)

        self._prefill_step = jax.jit(partial(self._prefill_impl, cfg), donate_argnums=(1,))
        self._decode = jax.jit(
            partial(self._decode_impl, cfg), static_argnums=(8,), donate_argnums=(1,)
        )

    # ------------------------------------------------------------- jitted fns

    @staticmethod
    def _prefill_impl(cfg, params, cache, tokens, pos_vec, active, rope):
        logits, cache = forward(cfg, params, tokens, pos_vec, cache, rope, active=active)
        return logits[:, -1], cache

    @staticmethod
    def _decode_impl(cfg, params, cache, tokens, pos_vec, active, key, temps, topps, n, rope):
        def body(carry, _):
            tok, cache, p, key = carry
            logits, cache = forward(cfg, params, tok, p, cache, rope, active=jnp.asarray(active))
            key, sub = jax.random.split(key)
            nxt = sample_logits(logits[:, -1], sub, temps, topps)[:, None]
            nxt = jnp.where(active[:, None], nxt, tok)  # frozen slots keep token
            return (nxt, cache, p + active.astype(jnp.int32), key), nxt[:, 0]

        (_, cache, _, _), toks = jax.lax.scan(
            body, (tokens, cache, pos_vec, key), None, length=n
        )
        return toks, cache

    # ------------------------------------------------------------------- api

    def free_slot(self) -> int | None:
        idle = np.flatnonzero(~self.active)
        return int(idle[0]) if idle.size else None

    def add(self, slot: int, prompt_tokens: list[int], temperature: float = 0.8,
            topp: float = 0.9, start_pos: int = 0) -> int:
        """Prefill `prompt_tokens` into `slot` (rows from start_pos — pass a
        cached-prefix length to reuse earlier rows, NaiveCache-style) and
        sample the first token. Other slots are untouched (masked writes)."""
        assert not self.active[slot], f"slot {slot} is busy"
        n = len(prompt_tokens)
        if n == 0:
            raise ValueError("prompt must be non-empty")
        if start_pos + n >= self.seq_len:
            raise ValueError(f"prompt ({start_pos}+{n}) exceeds seq_len {self.seq_len}")
        self.pos[slot] = start_pos
        onehot = np.zeros(self.n_slots, bool)
        onehot[slot] = True
        toks = np.asarray(prompt_tokens, np.int32)
        logits = None
        off = 0
        while off < n:
            # power-of-two widths: at most log2(max_chunk)+1 compiled variants
            # (same policy as InferenceEngine.prefill)
            c = min(self.max_prefill_chunk, 1 << (n - off - 1).bit_length())
            while c > n - off:
                c //= 2
            chunk = np.zeros((self.n_slots, c), np.int32)
            chunk[slot] = toks[off : off + c]
            # rope/cache row indexing needs every row's pos valid; frozen rows
            # pass their current pos (writes masked anyway).
            # .copy() is load-bearing on every host->device handoff here:
            # jnp.asarray can zero-copy ALIAS a numpy buffer on CPU, and this
            # engine mutates pos/active/last_token in place after dispatching
            # async device work — aliasing turns that into a read/write race.
            pos_vec = jnp.asarray(self.pos.copy(), jnp.int32)
            logits, self.cache = self._prefill_step(
                self.params, self.cache,
                jnp.asarray(chunk),
                pos_vec,
                jnp.asarray(onehot.copy()),
                self.rope_cache,
            )
            self.pos[slot] += c
            off += c

        self.key, sub = jax.random.split(self.key)
        first = int(np.asarray(sample_logits(logits, sub, jnp.float32(temperature), jnp.float32(topp)))[slot])
        self.active[slot] = True
        self.last_token[slot] = first
        self.temperature[slot] = temperature
        self.topp[slot] = topp
        return first

    def decode(self, n: int) -> np.ndarray:
        """n fused decode steps across all active slots; returns tokens [n, B]
        (frozen slots repeat their last token — callers track per-slot state)."""
        if not self.active.any():
            raise ValueError("no active slots")
        room = self.seq_len - int(self.pos[self.active].max())
        n = min(n, room)
        if n <= 0:
            raise ValueError("active slot at seq_len; release it first")
        self.key, sub = jax.random.split(self.key)
        toks, self.cache = self._decode(
            self.params, self.cache,
            jnp.asarray(self.last_token[:, None].copy()),
            jnp.asarray(self.pos.copy(), jnp.int32),
            jnp.asarray(self.active.copy()),
            sub,
            jnp.asarray(self.temperature.copy()),
            jnp.asarray(self.topp.copy()),
            n,
            self.rope_cache,
        )
        toks = np.asarray(toks)
        self.pos[self.active] += n
        self.last_token[self.active] = toks[-1, self.active]
        return toks

    def release(self, slot: int, keep_rows: int | None = None) -> None:
        """Free a slot. keep_rows rewinds pos to the valid prefix (mid-chunk
        stop), preserving the slot's cache for NaiveCache-style reuse."""
        self.active[slot] = False
        if keep_rows is not None:
            self.pos[slot] = keep_rows
