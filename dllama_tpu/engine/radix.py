"""Cross-request radix prefix cache over the paged KV pool (ISSUE 9).

SGLang-style RadixAttention (Zheng et al.) layered on the vLLM-style page
pool (Kwon et al.) that PRs 5 and 8 built: a GLOBAL radix tree keyed on
token ids whose nodes own refcounted page references into the engine's
:class:`~dllama_tpu.engine.batch.PagePool`. Any admitted request walks the
tree, maps the longest shared prefix for free (block-table entries copied,
page refcounts bumped, a partial boundary page shared then copy-on-written
by the existing ``ensure_writable``), prefills only the suffix, and on
commit/release inserts its own prefix back so future requests hit it. This
turns the dominant real traffic shapes — shared system prompts, few-shot
templates, multi-turn chat, agent loops re-sending history — into
O(new tokens) prefill, across requests and across slots, not just against
whatever prefix an idle slot happens to still hold.

Design constraints the page pool imposes (and how the tree meets them):

* **Page-granular edges.** KV is allocated in ``page_size``-row pages, so
  node edges are sequences of WHOLE pages: children are keyed by their
  edge's first page-sized token tuple, and edge splits happen only at page
  boundaries. Two prompts diverging *inside* a page therefore hang as
  sibling children (different first-page keys); the shared sub-page prefix
  is still exploited at lookup time as the *partial boundary*: the best
  child's first page is mapped shared and the admission's
  ``prepare_admission`` copy-on-writes it before the divergent rows are
  rewritten — rows ``[0, part)`` of the clone are free.
* **Immutability by construction.** Only FULL pages whose every row is
  already written enter the tree (a prompt's full pages at commit, the
  emitted-prefix full pages at release). Decode scatters rows strictly past
  the written prefix — including the one-chunk stop overrun, which lands at
  or past the kept-row boundary — so a tree page is never rewritten while
  shared.
* **Refcount composition.** The tree holds exactly ONE pool reference per
  owned page, alongside however many block-table references share it;
  ``PagePool.audit()`` reconciles ``refcount == table refs + tree refs``
  (the tree registers itself as the pool's ``radix_refs`` provider), so a
  leaked or duplicated node reference fails the audit like any allocator
  corruption.
* **Eviction composes with capacity-aware admission.** LRU over leaf nodes
  whose pages are not referenced by any live slot, coldest first (smallest
  tie-break): tree pages are reclaimable BEFORE a request defers or is
  rejected, and before the all-starved decode rescue truncates a running
  request. The matched path of the admission being served is protected.
* **Crash safety.** A warm restart rebuilds pool + KV buffers from scratch,
  so the tree is DROPPED with them (never stale page refs); cumulative
  accounting carries over so hit-rate telemetry survives restarts.

Thread-safety: the scheduler worker is the only mutator, but ``stats()`` /
``dump()`` / the audit provider are read from HTTP handler threads — every
method takes the POOL's reentrant lock, which also makes
``audit()``-calls-``audit_refs()`` reentrancy safe.
"""

from __future__ import annotations

import heapq
import time

from dllama_tpu.obs import instruments as ins


def _lcp(a, b) -> int:
    """Leading-equal count of two token sequences."""
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class RadixNode:
    """One edge of the tree: ``tokens`` (a whole number of pages worth of
    token ids, the path label from the parent) backed 1:1 by ``pages``
    (pool page ids — ``len(tokens) == len(pages) * page_size``)."""

    __slots__ = ("tokens", "pages", "children", "parent", "last_used")

    def __init__(self, tokens=(), pages=(), parent=None):
        self.tokens: tuple = tuple(tokens)
        self.pages: list[int] = list(pages)
        self.children: dict[tuple, RadixNode] = {}
        self.parent: RadixNode | None = parent
        self.last_used = time.monotonic()


class RadixHit:
    """``lookup()`` result: the mappable prefix. ``rows`` = full-page rows
    plus the partial-boundary rows; ``pages`` are the full shared pages;
    ``boundary`` (when ``part > 0``) is the tree page whose first ``part``
    rows match — mapped shared, then COW'd by the admission. ``path`` is
    the matched node chain, protected from eviction while this admission
    is being served."""

    __slots__ = ("rows", "pages", "part", "boundary", "path", "tokens")

    def __init__(self, rows, pages, part, boundary, path, tokens):
        self.rows = rows
        self.pages = pages
        self.part = part
        self.boundary = boundary
        self.path = path
        self.tokens = tokens


class RadixCache:
    """The global prefix tree over one :class:`PagePool`.

    Owns the ``dllama_radix_nodes`` / ``dllama_radix_pages`` gauges and the
    ``dllama_radix_lookups_total{outcome}`` / ``dllama_radix_hit_tokens_total``
    counters (single publication site). ``carry_from`` preserves the
    cumulative accounting across a warm restart (the tree itself is
    rebuilt empty against the fresh pool)."""

    def __init__(self, pool, carry_from: "RadixCache | None" = None):
        self.pool = pool
        self.page = pool.page_size
        # the POOL's RLock: tree refs and pool refcounts mutate together,
        # and audit() -> audit_refs() re-enters it from the same thread
        self._mu = pool._mu
        self.root = RadixNode()
        self.n_nodes = 0  # excluding the root
        self.n_pages = 0
        # cumulative accounting (survives warm restarts via carry_from)
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0  # prefill rows REALLY served (counted at commit)
        self.inserted_pages = 0
        self.evicted_pages = 0
        if carry_from is not None:
            self.lookups = carry_from.lookups
            self.hits = carry_from.hits
            self.hit_tokens = carry_from.hit_tokens
            self.inserted_pages = carry_from.inserted_pages
            self.evicted_pages = carry_from.evicted_pages
        # host-tier spill hook (BatchEngine._host_spill when --kv-host-pages
        # is on): called under the pool lock with (token_path_key, page_id)
        # for each last-reference page right before eviction drops it; a
        # False/failed spill degrades to the plain discard
        self.spill = None
        pool.radix_refs = self.audit_refs  # audit reconciliation hook
        self._publish()

    # ------------------------------------------------------------- internal

    def _publish(self) -> None:
        ins.RADIX_NODES.set(self.n_nodes)
        ins.RADIX_PAGES.set(self.n_pages)

    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n

    def _split(self, parent: RadixNode, child: RadixNode, k: int) -> RadixNode:
        """Split ``child``'s edge at page ``k`` (0 < k < len(pages)): the
        new prefix node keeps the first k pages, ``child`` keeps the rest
        below it. Pure re-parenting — no refcount moves."""
        page = self.page
        prefix = RadixNode(child.tokens[: k * page], child.pages[:k], parent)
        prefix.last_used = child.last_used
        child.tokens = child.tokens[k * page:]
        child.pages = child.pages[k:]
        child.parent = prefix
        prefix.children[child.tokens[:page]] = child
        parent.children[prefix.tokens[:page]] = prefix
        self.n_nodes += 1
        return prefix

    def _abs_tokens(self, node: RadixNode) -> tuple:
        """The absolute token path from the root through ``node`` (its own
        edge included) — the host-tier key space. O(depth) parent-chain
        walk; only taken on the eviction path when a spill hook is wired."""
        parts = []
        n = node
        while n is not None and n.parent is not None:
            parts.append(n.tokens)
            n = n.parent
        out: list[int] = []
        for t in reversed(parts):
            out.extend(t)
        return tuple(out)

    def _drop(self, node: RadixNode) -> int:
        """Remove a leaf; decref its pages. Returns pages actually freed."""
        before = self.pool.free_count
        for p in node.pages:
            self.pool._decref(p)
        freed = self.pool.free_count - before
        del node.parent.children[node.tokens[:self.page]]
        self.n_nodes -= 1
        self.n_pages -= len(node.pages)
        return freed

    # ------------------------------------------------------------------ api

    def lookup(self, toks, count: bool = True) -> RadixHit:
        """Longest mappable prefix of ``toks``, capped at ``len(toks) - 1``
        (at least one token must prefill to produce logits — the same rule
        the per-slot LCP scan enforced). ``count=False`` skips the lookup /
        hit accounting — the post-restore re-walk is the same logical
        lookup, not a second one."""
        page = self.page
        toks = [int(t) for t in toks]
        cap = len(toks) - 1
        now = time.monotonic()
        with self._mu:
            if count:
                self.lookups += 1
            node, depth = self.root, 0
            pages: list[int] = []
            path = [self.root]
            boundary, part = None, 0
            mid_edge = False
            while depth + page <= cap:
                child = node.children.get(tuple(toks[depth:depth + page]))
                if child is None:
                    break
                child.last_used = now
                path.append(child)
                k = 0  # >= 1 after the loop: the dict key IS page 0's tokens
                while (k < len(child.pages)
                       and depth + (k + 1) * page <= cap
                       and tuple(child.tokens[k * page:(k + 1) * page])
                       == tuple(toks[depth + k * page:depth + (k + 1) * page])):
                    k += 1
                pages.extend(child.pages[:k])
                depth += k * page
                if k < len(child.pages):
                    # stopped inside this edge (divergence, or the prompt
                    # ran out): its next page — and ONLY it — is the
                    # boundary candidate (sibling pages live at this node's
                    # START depth, not here; offering one would map KV
                    # computed at different positions)
                    mid_edge = True
                    part = _lcp(child.tokens[k * page:(k + 1) * page],
                                toks[depth:cap])
                    if part:
                        boundary = child.pages[k]
                    break
                node = child
            if boundary is None and not mid_edge:
                # stopped at a node boundary (children's first pages cover
                # exactly rows [depth, depth+page)): the best partially-
                # matching child still yields sub-page reuse. The winner
                # joins the protected path — eviction between lookup and
                # radix_map must not free the page about to be mapped.
                best = None
                for c in node.children.values():
                    n = _lcp(c.tokens[:page], toks[depth:cap])
                    if n > part:
                        part, boundary, best = n, c.pages[0], c
                if best is not None:
                    best.last_used = now
                    path.append(best)
            rows = depth + part
            if rows > 0 and count:
                self.hits += 1
        if count:
            ins.RADIX_LOOKUPS.labels(
                outcome="hit" if rows > 0 else "miss").inc()
        return RadixHit(rows=rows, pages=pages, part=part, boundary=boundary,
                        path=tuple(path), tokens=toks[:rows])

    def note_served(self, rows: int) -> None:
        """Count ``rows`` prefix rows REALLY served from the tree — called
        at the admission's commit, so an aborted/cancelled admission never
        inflates the saved-prefill accounting."""
        if rows <= 0:
            return
        with self._mu:
            self.hit_tokens += int(rows)
        ins.RADIX_HIT_TOKENS.inc(int(rows))

    def insert(self, toks, slot_pages) -> int:
        """Insert the full-page prefix of ``toks`` — KV rows backed by
        ``slot_pages``, the owning slot's block-table pages — into the
        tree. Matched existing nodes are kept (their pages already hold
        exactly these rows); the unmatched full-page tail is adopted BY
        REFERENCE: each adopted page's pool refcount bumps, making the tree
        a first-class referent that outlives the releasing slot. Returns
        the number of pages adopted."""
        page = self.page
        toks = [int(t) for t in toks]
        full = len(toks) // page
        if full <= 0:
            return 0
        now = time.monotonic()
        with self._mu:
            node, depth = self.root, 0
            while depth < full * page:
                child = node.children.get(tuple(toks[depth:depth + page]))
                if child is None:
                    break
                child.last_used = now
                k = 0
                while (k < len(child.pages)
                       and depth + (k + 1) * page <= full * page
                       and tuple(child.tokens[k * page:(k + 1) * page])
                       == tuple(toks[depth + k * page:depth + (k + 1) * page])):
                    k += 1
                depth += k * page
                if k < len(child.pages):
                    if depth < full * page:
                        # diverged mid-edge with pages still to adopt:
                        # split at the page boundary so the tail branches
                        node = self._split(node, child, k)
                    break
                node = child
            rem = full - depth // page
            if rem <= 0:
                return 0
            adopt = [int(p) for p in slot_pages[depth // page:full]]
            new = RadixNode(tuple(toks[depth:full * page]), adopt, node)
            new.last_used = now
            node.children[new.tokens[:page]] = new
            for p in adopt:
                self.pool.refcount[p] += 1
            self.n_nodes += 1
            self.n_pages += len(adopt)
            self.inserted_pages += len(adopt)
            self.pool._publish()  # shared-pages gauge may have moved
            self._publish()
            return len(adopt)

    def evict(self, need: int, protect=None) -> int:
        """Reclaim pool pages by dropping leaves — LRU (coldest
        ``last_used``) first, smallest tie-break — until ``need`` pages
        came FREE or no reclaimable leaf remains, then stop (a one-page
        shortfall must not wipe the whole tree). Leaves whose every page
        is still referenced by a live slot free nothing and are skipped
        (they stay cached); ``protect`` (a :class:`RadixHit` or an
        iterable of nodes) pins the admission-in-progress's matched path.
        Returns pages actually freed."""
        prot = protect.path if isinstance(protect, RadixHit) else (protect or ())
        prot_ids = {id(n) for n in prot}
        freed = 0
        with self._mu:
            # one tree walk seeds the heap; a dropped victim's parent is
            # re-seeded when it just became a leaf — never a full rescan
            # per victim (the pool lock is held: reclaim must stay O(n log n))
            heap = [((n.last_used, len(n.pages), id(n)), n)
                    for n in self._iter_nodes()
                    if not n.children and id(n) not in prot_ids]
            heapq.heapify(heap)
            while freed < need and heap:
                _, victim = heapq.heappop(heap)
                if not any(self.pool.refcount[p] == 1 for p in victim.pages):
                    # every page still referenced by a live slot: dropping
                    # frees nothing — keep the cache entry (refcounts of
                    # OTHER nodes' pages never change inside this loop, so
                    # skipping is final for this call)
                    continue
                parent = victim.parent
                if self.spill is not None:
                    # host-tier capture BEFORE the drop, while the pages
                    # are still allocated and their KV rows intact. Only
                    # last-reference pages spill: a shared page lives on in
                    # some slot's block table and re-enters the tree at
                    # that slot's release. Keys are absolute token paths —
                    # page i's rows encode the prefix through its last row.
                    full = self._abs_tokens(victim)
                    start = len(full) - len(victim.tokens)
                    for i, p in enumerate(victim.pages):
                        if self.pool.refcount[p] == 1:
                            self.spill(full[: start + (i + 1) * self.page], p)
                freed += self._drop(victim)
                if (parent is not self.root and not parent.children
                        and id(parent) not in prot_ids):
                    heapq.heappush(
                        heap,
                        ((parent.last_used, len(parent.pages), id(parent)),
                         parent))
            if freed:
                self.evicted_pages += freed
                self.pool._publish()
                self._publish()
        return freed

    def restore_prefix(self, toks, peek, install, take) -> int:
        """Graft host-tier pages for ``toks`` back into the tree
        (restore-on-hit, the inverse of the eviction spill). Walks like
        :meth:`insert`; wherever the resident tree runs out but the host
        tier holds the next full page of the prompt (``peek`` by absolute
        token path), ``install`` uploads it into a fresh pool page, a
        single-page node adopts that page (the tree owns its one
        reference — ``_alloc_page`` set it), and ``take`` retires the host
        copy. Stops at the first miss or failed install (peek→install→take:
        a failed device alloc never loses the only copy). Returns pages
        grafted; the caller re-walks with ``lookup(count=False)``."""
        page = self.page
        toks = [int(t) for t in toks]
        # a grafted page only helps if lookup can map it whole, and lookup
        # caps matched rows at len(toks) - 1
        limit = ((len(toks) - 1) // page) * page
        if limit <= 0:
            return 0
        grafted = 0
        now = time.monotonic()
        with self._mu:
            node, depth = self.root, 0
            while depth < limit:
                child = node.children.get(tuple(toks[depth:depth + page]))
                if child is not None:
                    k = 0
                    while (k < len(child.pages)
                           and depth + (k + 1) * page <= limit
                           and tuple(child.tokens[k * page:(k + 1) * page])
                           == tuple(toks[depth + k * page:
                                         depth + (k + 1) * page])):
                        k += 1
                    depth += k * page
                    if k < len(child.pages):
                        if depth + page > limit:
                            break
                        # diverged mid-edge with restorable room left:
                        # split at the page boundary (k >= 1 — the dict
                        # key IS page 0) so a restored sibling can graft
                        node = self._split(node, child, k)
                        continue
                    node = child
                    continue
                key = tuple(toks[:depth + page])
                payload = peek(key)
                if payload is None:
                    break
                pg = install(payload)
                if pg is None:
                    break
                new = RadixNode(tuple(toks[depth:depth + page]), [pg], node)
                new.last_used = now
                node.children[new.tokens[:page]] = new
                self.n_nodes += 1
                self.n_pages += 1
                take(key)
                grafted += 1
                node = new
                depth += page
            if grafted:
                self.pool._publish()
                self._publish()
        return grafted

    def clear(self) -> int:
        """Drop the whole tree (drain/diagnostics; a warm restart instead
        rebuilds the cache object against the fresh pool). Returns pages
        freed back to the pool."""
        with self._mu:
            before = self.pool.free_count
            for node in list(self._iter_nodes()):
                for p in node.pages:
                    self.pool._decref(p)
            self.root = RadixNode()
            self.n_nodes = 0
            self.n_pages = 0
            self.pool._publish()
            self._publish()
            return self.pool.free_count - before

    # -------------------------------------------------------- observability

    def audit_refs(self) -> tuple[dict[int, int], list[str]]:
        """Audit provider (``PagePool.audit``): per-page tree reference
        counts plus the tree's OWN invariant violations — a page owned by
        two nodes (each page must enter the tree exactly once) or an
        out-of-range page id. Runs under the shared pool lock."""
        refs: dict[int, int] = {}
        problems: list[str] = []
        with self._mu:
            n_pages = 0
            for node in self._iter_nodes():
                if len(node.tokens) != len(node.pages) * self.page:
                    problems.append(
                        f"radix node holds {len(node.tokens)} tokens for "
                        f"{len(node.pages)} pages (page_size {self.page})")
                for p in node.pages:
                    n_pages += 1
                    if not 0 <= p < self.pool.n_pages:
                        problems.append(
                            f"radix node references page {p} outside the "
                            f"pool [0, {self.pool.n_pages})")
                        continue
                    refs[p] = refs.get(p, 0) + 1
                    if refs[p] > 1:
                        problems.append(
                            f"page {p} referenced by {refs[p]} radix nodes "
                            "(each page must enter the tree exactly once)")
            if n_pages != self.n_pages:
                problems.append(
                    f"radix page count drift: gauge says {self.n_pages}, "
                    f"recount found {n_pages}")
        return refs, problems

    def stats(self) -> dict:
        """Occupancy + cumulative hit accounting (latency_summary(),
        /debug/perf, /debug/radix — and the gauges' source of truth).
        ``hit_tokens`` is the saved-prefill-rows total."""
        with self._mu:
            return {
                "nodes": self.n_nodes,
                "pages": self.n_pages,
                "lookups": self.lookups,
                "hits": self.hits,
                "hit_rate": (round(self.hits / self.lookups, 4)
                             if self.lookups else None),
                "hit_tokens": self.hit_tokens,
                "inserted_pages": self.inserted_pages,
                "evicted_pages": self.evicted_pages,
                "page_size": self.page,
            }

    def dump(self, max_nodes: int = 512) -> dict:
        """Bounded JSON tree dump for ``GET /debug/radix``: nested nodes
        with their token labels (truncated past 16), page ids, and
        last-use age. ``truncated`` flags a cut-off subtree."""
        now = time.monotonic()
        budget = [max_nodes]

        def render(node: RadixNode) -> dict:
            out: dict = {
                "n_tokens": len(node.tokens),
                "tokens": list(node.tokens[:16]),
                "pages": list(node.pages),
                "age_s": round(now - node.last_used, 3),
            }
            kids = []
            for c in sorted(node.children.values(),
                            key=lambda n: -n.last_used):
                if budget[0] <= 0:
                    out["truncated"] = True
                    break
                budget[0] -= 1
                kids.append(render(c))
            if kids:
                out["children"] = kids
            return out

        with self._mu:
            kids = []
            for c in sorted(self.root.children.values(),
                            key=lambda n: -n.last_used):
                if budget[0] <= 0:
                    break
                budget[0] -= 1
                kids.append(render(c))
            return {"nodes": self.n_nodes, "pages": self.n_pages,
                    "children": kids,
                    "truncated": self.n_nodes > max_nodes}
