"""Prompt-lookup speculative decoding: n-gram drafting + exact greedy
verification, fully on device.

The reference decodes strictly one token per forward (dllama.cpp:69-88).
On TPU a decode forward is HBM-bound — streaming the weights for ONE token
costs nearly the same as for k+1 — so verifying k drafted tokens in a
single (k+1)-wide forward is almost free, and every accepted draft
multiplies tok/s. Drafts come from the sequence itself ("prompt lookup":
continue the most recent occurrence of the trailing n-gram), so no draft
model is needed, and the output is bit-identical to plain greedy decoding:
every emitted token is the model's argmax — speculation only changes how
many forwards it takes to produce them.

TPU-native end to end:
* propose — vectorized n-gram match over the on-device token history (no
  gather loops, one masked-iota max + dynamic_slice);
* verify — one (k+1)-wide forward through the SAME ``fwd`` closure the
  engine compiled (Pallas kernels, KV writes, causal masks unchanged; the
  prefill-shaped path handles T=k+1 natively);
* accept — cumprod over the draft/argmax agreement prefix;
* the cycle loop is a ``lax.while_loop`` carried on device — zero host
  round-trips until n tokens are ready.

Rejected drafts leave stale KV rows past the live position; attention masks
rows ``> pos`` so they are never read and are overwritten when those
positions are really decoded — the same invariant behind the engine's
mid-chunk rewind (engine.generate).

This module is the single-sequence (batch=1) tier. The SERVING tier lifts
the same propose/verify scheme into continuous batching
(engine/batch.BatchEngine._spec_cycle_core): per-slot accept/reject
vectors inside a fused multi-cycle scan, per-request ``spec_k`` admission,
overlap-pipeline composition, and paged draft-write COW safety — see
ISSUE 11 / the README "Speculative decoding" section. ``propose_ngram``
below is shared by both tiers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def propose_ngram(h: jax.Array, length: jax.Array, k: int, ngram: int):
    """Draft k tokens by continuing the most recent earlier occurrence of the
    trailing `ngram` tokens of ``h[:length]``.

    h: i32[S+1] token-at-position buffer (position i holds the sequence's
    i-th token for i < length). Returns (draft i32[k], found bool). With no
    match the draft is an arbitrary in-range window — harmless, because
    verification only ever emits argmax tokens; a bad draft just means
    a = 0 accepted.
    """
    s = h.shape[0]
    idx = jnp.arange(s, dtype=jnp.int32)
    # candidate j = index of the ngram's LAST token in an earlier occurrence:
    # h[j - d] == h[length - 1 - d] for d in 0..ngram-1, and j <= length - 2
    # (strictly earlier). j >= ngram - 1 keeps the roll from wrapping.
    cond = (idx >= ngram - 1) & (idx <= length - 2)
    for d in range(ngram):
        tail = h[jnp.maximum(length - 1 - d, 0)]
        cond &= jnp.roll(h, d) == tail
    j = jnp.max(jnp.where(cond, idx, -1))
    found = j >= 0
    j = jnp.clip(j, 0, s - k - 1)
    return jax.lax.dynamic_slice(h, (j + 1,), (k,)), found


def make_spec_decode(fwd, seq_len: int, k: int, ngram: int = 2,
                     donate: bool = True):
    """Build the jittable greedy speculative decoder for one engine.

    Returned fn signature (n static):
        (params, cache, h, cur, pos, rope, n) ->
            (out i32[n+k+1], count, cycles, cache, h, pos)
    ``h``: i32[seq_len+1] positions filled up to and including ``pos`` (the
    unfed ``cur`` token sits at index pos; unknown earlier positions hold -1,
    which can never n-gram-match a real token id). Emits ``count`` tokens
    (>= n unless the context filled first) in out[:count]; each is the exact
    greedy continuation. ``cycles`` counts verify forwards — emitted/cycles
    is the speculation speedup. The updated ``h`` comes back so a chunked
    caller can thread it without host-side rebuilds.
    """

    def decode(params, cache, h, cur, pos, rope, n: int):
        out0 = jnp.zeros((n + k + 1,), jnp.int32)

        def cond_fn(carry):
            _, _, _, pos, _, cnt, _ = carry
            return (cnt < n) & (pos + k + 1 <= seq_len)

        def body_fn(carry):
            cache, h, cur, pos, out, cnt, cyc = carry
            draft, _ = propose_ngram(h, pos + 1, k, ngram)
            toks = jnp.concatenate([cur[None], draft])[None]  # [1, k+1]
            logits, cache = fwd(params, cache, toks, pos, rope, last_only=False)
            g = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)  # [k+1]
            # longest draft prefix the model agrees with; g[a] is the bonus
            # token sampled after the last accepted draft
            a = jnp.sum(jnp.cumprod((draft == g[:k]).astype(jnp.int32)))
            # g[:a+1] are the emitted tokens AND the tokens at positions
            # pos+1 .. pos+a+1 (history entries past the new live position
            # are garbage that is never read and later overwritten)
            out = jax.lax.dynamic_update_slice(out, g, (cnt,))
            h = jax.lax.dynamic_update_slice(h, g, (pos + 1,))
            return (cache, h, g[a], pos + a + 1, out, cnt + a + 1, cyc + 1)

        cache, h, cur, pos, out, cnt, cyc = jax.lax.while_loop(
            cond_fn, body_fn,
            (cache, h, cur, pos, out0, jnp.int32(0), jnp.int32(0)),
        )
        return out, cnt, cyc, cache, h, pos

    return jax.jit(decode, static_argnums=(6,),
                   donate_argnums=(1,) if donate else ())
