"""On-device token sampling: greedy / temperature / top-p nucleus.

Semantics follow the reference Sampler (tokenizer.cpp:332-453): temp==0 is
argmax; otherwise softmax(logits/temp) then plain multinomial, or top-p
truncation when 0 < topp < 1. RNG is jax.random (threefry) seeded from the
user seed rather than the reference's xorshift — sequences are seedable and
reproducible, but not bit-identical to the C++ RNG.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_logits(logits: jax.Array, key: jax.Array, temperature, topp) -> jax.Array:
    """logits f32 [B, V] -> tokens i32 [B]. Branchless in temperature/topp so
    both can be *traced* scalars — the fused decode loop and the API server
    never recompile when a request changes sampling params. Either may also be
    an [B] vector (per-slot params in the continuous-batching engine)."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.asarray(temperature, jnp.float32)
    topp = jnp.asarray(topp, jnp.float32)
    if temperature.ndim == 1:
        temperature = temperature[:, None]
    if topp.ndim == 1:
        topp = topp[:, None]
    scaled = logits / jnp.maximum(temperature, 1e-6)
    probs = jax.nn.softmax(scaled, axis=-1)
    sorted_probs = jnp.sort(probs, axis=-1, descending=True)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens while the cumulative mass *before* them is < topp
    # (i.e. include the token that first crosses topp, like sample_topp's
    # break-after-include, tokenizer.cpp:389-395)
    keep_sorted = (cum - sorted_probs) < topp
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_probs, jnp.inf), axis=-1, keepdims=True
    )
    use_topp = (topp > 0.0) & (topp < 1.0)
    masked = jnp.where(use_topp & (probs < threshold), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)
    t_is_zero = temperature == 0.0
    if t_is_zero.ndim == 2:
        t_is_zero = t_is_zero[:, 0]
    return jnp.where(t_is_zero, greedy, sampled)


@jax.jit
def sample(logits: jax.Array, key: jax.Array, temperature=0.8, topp=0.9) -> jax.Array:
    return sample_logits(logits, key, temperature, topp)


class Sampler:
    """Stateful host-side wrapper (the analog of the reference Sampler object)."""

    def __init__(self, temperature: float = 0.8, topp: float = 0.9, seed: int = 0):
        self.temperature = float(temperature)
        self.topp = float(topp)
        self.key = jax.random.PRNGKey(seed)

    def set_seed(self, seed: int) -> None:
        self.key = jax.random.PRNGKey(seed)

    def set_temp(self, temperature: float) -> None:
        self.temperature = float(temperature)

    def __call__(self, logits: jax.Array) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sample(logits, sub, self.temperature, self.topp)
