"""On-device token sampling: greedy / temperature / top-p nucleus.

Semantics follow the reference Sampler (tokenizer.cpp:332-453): temp==0 is
argmax; otherwise softmax(logits/temp) then plain multinomial, or top-p
truncation when 0 < topp < 1. RNG is jax.random (threefry) seeded from the
user seed rather than the reference's xorshift — sequences are seedable and
reproducible, but not bit-identical to the C++ RNG.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dllama_tpu.obs import compile as compile_obs


# top-p candidate-set width: nucleus sampling restricts to the approx-top-K
# logits instead of full-vocab sort (see sample_logits). At real-vocab sizes
# and topp <= 0.99 the nucleus essentially never exceeds a few dozen tokens.
# None = exact mode (ADVICE r3): full-vocab sort like the reference's nucleus
# (tokenizer.cpp:389-395) — no approx recall loss, no wide-nucleus fallback,
# at the cost of a 128k-row sort per decode step. CLI: --exact-topp.
NUCLEUS_K: int | None = 256


def apply_penalties(logits: jax.Array, counts, presence, frequency) -> jax.Array:
    """OpenAI-style repetition penalties on raw logits:
    ``mu[j] = logit[j] - presence * 1[counts[j] > 0] - frequency * counts[j]``.

    counts: [B, V] occurrence counts of each token SAMPLED in this
    completion so far (OpenAI's published formula: the prompt — and any
    KV-cached earlier turns — carries no penalty, so output never depends
    on prefix-cache state). presence/frequency: scalars or [B] vectors —
    branchless like temperature/topp so per-request values never recompile.
    The reference has no analog (its sampler is temp/top-p only,
    tokenizer.cpp:352-416); OpenAI clients send these fields routinely."""
    presence = jnp.asarray(presence, jnp.float32)
    frequency = jnp.asarray(frequency, jnp.float32)
    if presence.ndim == 1:
        presence = presence[:, None]
    if frequency.ndim == 1:
        frequency = frequency[:, None]
    c = counts.astype(jnp.float32)
    return logits - presence * (c > 0) - frequency * c


def sample_logits(logits: jax.Array, key: jax.Array, temperature, topp) -> jax.Array:
    """logits f32 [B, V] -> tokens i32 [B]. Branchless in temperature/topp so
    both can be *traced* scalars — the fused decode loop and the API server
    never recompile when a request changes sampling params. Either may also be
    an [B] vector (per-slot params in the continuous-batching engine).

    Top-p is computed over the ``approx_max_k`` top-NUCLEUS_K candidates (the
    TPU-native top-k; exact on CPU) with probabilities normalized against the
    FULL vocab, instead of the reference's full-vocab sort
    (tokenizer.cpp:389-395): an XLA sort of a 128k-vocab row per decode step
    costs more than a whole transformer layer, and a nucleus wider than 256
    tokens requires a distribution so flat that truncating it is noise. The
    kept-set rule within the candidates is the reference's break-after-include.
    If the candidates cover less than topp of the full-vocab mass (a nucleus
    wider than K — very high temperature on a large vocab), the row falls back
    to full-vocab temperature sampling rather than silently behaving as
    top-k=K. Callers that need the reference's exact semantics (no recall
    loss, no fallback) set ``NUCLEUS_K = None`` for a true full-vocab sort.
    Pure temperature sampling (topp <= 0 or >= 1) stays full-vocab
    (categorical = gumbel-argmax, no sort)."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.asarray(temperature, jnp.float32)
    topp = jnp.asarray(topp, jnp.float32)
    if temperature.ndim == 1:
        temperature = temperature[:, None]
    if topp.ndim == 1:
        topp = topp[:, None]
    scaled = logits / jnp.maximum(temperature, 1e-6)
    key_p, key_t = jax.random.split(key)

    # --- top-p among the top-K candidates, full-vocab-normalized
    if NUCLEUS_K is None:  # exact escape hatch: full-vocab descending sort
        vals, idx = jax.lax.top_k(scaled, scaled.shape[-1])
    else:
        k = min(NUCLEUS_K, logits.shape[-1])
        vals, idx = jax.lax.approx_max_k(scaled, k, recall_target=0.99,
                                         aggregate_to_topk=True)  # sorted desc
    lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
    pk = jnp.exp(vals - lse)  # true softmax probs of the candidates
    cum = jnp.cumsum(pk, axis=-1)
    # keep while cumulative mass *before* the token is < topp (include the
    # token that crosses topp — the reference's break-after-include)
    keep = (cum - pk) < topp
    masked = jnp.where(keep, vals, -jnp.inf)
    choice = jax.random.categorical(key_p, masked, axis=-1)
    tok_topp = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    # --- pure temperature sampling: full vocab, no truncation
    tok_temp = jax.random.categorical(key_t, scaled, axis=-1).astype(jnp.int32)

    # nucleus wider than K: candidates don't reach topp mass — fall back to
    # untruncated temperature sampling for that row (see docstring)
    covered = cum[:, -1:] >= topp
    use_topp = (topp > 0.0) & (topp < 1.0) & covered
    if use_topp.ndim == 2:
        use_topp = use_topp[:, 0]
    sampled = jnp.where(use_topp, tok_topp, tok_temp)
    t_is_zero = temperature == 0.0
    if t_is_zero.ndim == 2:
        t_is_zero = t_is_zero[:, 0]
    return jnp.where(t_is_zero, greedy, sampled)


@jax.jit
def sample(logits: jax.Array, key: jax.Array, temperature=0.8, topp=0.9) -> jax.Array:
    return sample_logits(logits, key, temperature, topp)


class Sampler:
    """Stateful host-side wrapper (the analog of the reference Sampler object,
    plus the OpenAI repetition-penalty fields it lacks)."""

    def __init__(self, temperature: float = 0.8, topp: float = 0.9, seed: int = 0,
                 presence: float = 0.0, frequency: float = 0.0):
        self.temperature = float(temperature)
        self.topp = float(topp)
        self.presence = float(presence)
        self.frequency = float(frequency)
        self.key = jax.random.PRNGKey(seed)

    @property
    def has_penalties(self) -> bool:
        return self.presence != 0.0 or self.frequency != 0.0

    def set_seed(self, seed: int) -> None:
        self.key = jax.random.PRNGKey(seed)

    def set_temp(self, temperature: float) -> None:
        self.temperature = float(temperature)

    def __call__(self, logits: jax.Array) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        # ledger-scoped like every jit dispatch (analysis rule jit-scope):
        # the first-token sample's compile is attributed, not "untracked"
        with compile_obs.LEDGER.scope(
                "single_sample", f"b{logits.shape[0]}",
                sig=lambda: compile_obs.sig_of(logits)):
            return sample(logits, sub, self.temperature, self.topp)
