"""On-device token sampling: greedy / temperature / top-p nucleus.

Semantics follow the reference Sampler (tokenizer.cpp:332-453): temp==0 is
argmax; otherwise softmax(logits/temp) then plain multinomial, or top-p
truncation when 0 < topp < 1. RNG is jax.random (threefry) seeded from the
user seed rather than the reference's xorshift — sequences are seedable and
reproducible, but not bit-identical to the C++ RNG.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("temperature", "topp"))
def sample(logits: jax.Array, key: jax.Array, temperature: float = 0.8, topp: float = 0.9) -> jax.Array:
    """logits f32 [B, V] -> tokens i32 [B]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if 0.0 < topp < 1.0:
        probs = jax.nn.softmax(logits, axis=-1)
        sorted_probs = jnp.sort(probs, axis=-1, descending=True)
        cum = jnp.cumsum(sorted_probs, axis=-1)
        # keep tokens while the cumulative mass *before* them is < topp
        # (i.e. include the token that first crosses topp, like sample_topp's
        # break-after-include, tokenizer.cpp:389-395)
        keep_sorted = (cum - sorted_probs) < topp
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_probs, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(probs >= threshold, logits, -jnp.inf)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class Sampler:
    """Stateful host-side wrapper (the analog of the reference Sampler object)."""

    def __init__(self, temperature: float = 0.8, topp: float = 0.9, seed: int = 0):
        self.temperature = float(temperature)
        self.topp = float(topp)
        self.key = jax.random.PRNGKey(seed)

    def set_seed(self, seed: int) -> None:
        self.key = jax.random.PRNGKey(seed)

    def set_temp(self, temperature: float) -> None:
        self.temperature = float(temperature)

    def __call__(self, logits: jax.Array) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sample(logits, sub, self.temperature, self.topp)
