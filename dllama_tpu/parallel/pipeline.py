"""Pipeline parallelism: GPipe-style stage-split inference over the 'pp' axis.

The reference has NO pipeline axis — every node executes every layer in
lockstep (SURVEY.md §2.4 positions dllama *against* layer-split designs
because on 1GbE the per-layer activation hop would dominate). On TPU the
tradeoff flips: stages map to pods/slices linked by ICI/DCN and a
`ppermute` activation hop is cheap, so PP is the axis that scales *depth*
(70B/405B across pods) where TP scales width.

Design: the stacked per-layer params and KV cache keep their layout — the
leading layer axis is simply sharded over 'pp' (stage s owns layers
[s*L/pp, (s+1)*L/pp)). Inside one jitted shard_map:

  step t: stage 0 injects microbatch t (embedding lookup), every stage runs
  its layer slice on its in-flight activation, activations hop one stage via
  non-cyclic ppermute, the last stage banks finished microbatches. After
  M + pp - 1 steps the last stage norms + projects logits, broadcast by a
  masked psum. Cache writes are masked on inactive (bubble) steps, so the
  schedule is exact, not approximate.

Microbatches split the *batch* axis (all sequences share one position, so
decode with B=1 degenerates to sequential layer-split — the PP bubble is the
price of depth; throughput serving should drive PP with B >= pp).

Composition: the shard_map is *partial-manual* — only 'pp' is a manual axis
(`axis_names={'pp'}`); tp/dp stay under GSPMD, so weights placed with
P('pp', ..., 'tp') compose stage-split with tensor-parallel automatically
(the matmul psum over 'tp' is inserted by XLA inside each stage). pp x sp is
rejected by LlamaShardings (ring attention inside a manual stage is not
supported).
"""

from __future__ import annotations

from functools import partial

import jax

from dllama_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, run_layers
from dllama_tpu.ops.layers import rms_norm
from dllama_tpu.ops.matmul import matmul
from dllama_tpu.ops.quant import QTensor


def _shift_right(x: jax.Array, pp: int) -> jax.Array:
    """Send to the next stage; stage 0 receives zeros (non-cyclic edge)."""
    return jax.lax.ppermute(x, "pp", [(i, i + 1) for i in range(pp - 1)])


def _stage_body(cfg: LlamaConfig, attn_fn, mm, layers, x, pos, k, v, rope):
    x, k, v = run_layers(cfg, layers, x, pos, k, v, rope, attn_fn, mm=mm)
    return x, k, v


def make_pp_forward(cfg: LlamaConfig, mesh: Mesh, n_micro: int = 1, attn_fn=None, mm=None):
    """Build `fn(params, tokens, pos, cache, rope_cache) -> (logits, cache)`.

    params: the standard stacked pytree, with every `layers` leaf and the
    cache sharded P('pp', ...) on the layer axis (see `pp_param_specs`).
    tokens: [B, T] with B % n_micro == 0.
    """
    pp = mesh.shape["pp"]
    if cfg.n_layers % pp != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")

    def fn(params, tokens, pos, cache: KVCache, rope_cache):
        b, t = tokens.shape
        if b % n_micro != 0:
            raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
        mbs = b // n_micro
        rope = jax.lax.dynamic_slice_in_dim(rope_cache, pos, t, axis=0)

        @partial(
            _shard_map,
            mesh=mesh,
            in_specs=(
                jax.tree.map(lambda _: P(), params["embedding"]),
                jax.tree.map(
                    lambda _: P("pp"),
                    params["layers"],
                    is_leaf=lambda l: isinstance(l, QTensor),
                ),
                P(),  # final_norm
                jax.tree.map(lambda _: P(), params["wcls"], is_leaf=lambda l: isinstance(l, QTensor)),
                P(),  # tokens
                P("pp"),  # k cache (layer axis)
                P("pp"),  # v cache
                P(),  # rope rows
            ),
            out_specs=(P(), P("pp"), P("pp")),
            axis_names=frozenset({"pp"}),  # tp/dp stay GSPMD-auto inside stages
            check_vma=False,
        )
        def pipeline(embedding, layers, final_norm, wcls, toks, k_all, v_all, rope_rows):
            stage = jax.lax.axis_index("pp")
            toks_mb = toks.reshape(n_micro, mbs, t)
            x = jnp.zeros((mbs, t, cfg.dim), embedding.dtype)
            out = jnp.zeros((n_micro, mbs, t, cfg.dim), embedding.dtype)

            for step in range(n_micro + pp - 1):
                m_in = jnp.clip(step - stage, 0, n_micro - 1)
                active = (step >= stage) & (step - stage < n_micro)
                # stage 0 injects microbatch `step` (if any); others use recv
                inject = embedding[toks_mb[jnp.clip(step, 0, n_micro - 1)]]
                x = jnp.where((stage == 0) & active, inject, x)

                # batch-slice of this stage's cache for the in-flight microbatch
                k_mb = jax.lax.dynamic_slice_in_dim(k_all, m_in * mbs, mbs, axis=1)
                v_mb = jax.lax.dynamic_slice_in_dim(v_all, m_in * mbs, mbs, axis=1)
                y, k_new, v_new = _stage_body(cfg, attn_fn, mm, layers, x, pos, k_mb, v_mb, rope_rows)
                # bubble steps must not touch the cache
                k_upd = jax.lax.dynamic_update_slice_in_dim(k_all, k_new, m_in * mbs, axis=1)
                v_upd = jax.lax.dynamic_update_slice_in_dim(v_all, v_new, m_in * mbs, axis=1)
                k_all = jnp.where(active, k_upd, k_all)
                v_all = jnp.where(active, v_upd, v_all)

                # last stage banks its finished microbatch
                m_out = step - (pp - 1)
                banked = jax.lax.dynamic_update_slice_in_dim(
                    out, y[None], jnp.clip(m_out, 0, n_micro - 1), axis=0
                )
                out = jnp.where((stage == pp - 1) & (m_out >= 0), banked, out)

                x = _shift_right(y, pp)

            h = rms_norm(out.reshape(b, t, cfg.dim), final_norm, cfg.norm_epsilon)
            logits = (mm or matmul)(h, wcls).astype(jnp.float32)
            # only the last stage holds real logits; broadcast via masked psum
            logits = jax.lax.psum(
                jnp.where(stage == pp - 1, logits, jnp.zeros_like(logits)), "pp"
            )
            return logits, k_all, v_all

        logits, k_new, v_new = pipeline(
            params["embedding"],
            params["layers"],
            params["final_norm"],
            params["wcls"],
            tokens,
            cache.k,
            cache.v,
            rope,
        )
        return logits, KVCache(k_new, v_new)

    return fn


def pp_param_specs(params) -> dict:
    """PartitionSpec tree for pp placement: layer-stacked leaves on 'pp',
    everything else replicated."""

    def rep(leaf):
        return QTensor(P(), P()) if isinstance(leaf, QTensor) else P()

    def staged(leaf):
        s = P("pp")
        return QTensor(s, s) if isinstance(leaf, QTensor) else s

    is_q = lambda l: isinstance(l, QTensor)
    return {
        "embedding": rep(params["embedding"]),
        "final_norm": P(),
        "wcls": rep(params["wcls"]),
        "layers": jax.tree.map(staged, params["layers"], is_leaf=is_q),
    }


def put_pp(params, cache: KVCache, mesh: Mesh):
    """Place params + cache for the pipeline mesh."""
    specs = pp_param_specs(params)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    cs = NamedSharding(mesh, P("pp"))
    cache = KVCache(jax.device_put(cache.k, cs), jax.device_put(cache.v, cs))
    return params, cache
