"""Sharding rules: the reference's TP decomposition as PartitionSpecs.

Maps one-to-one onto the reference's slicers (nn-core.cpp:170-238):
  sliceRowMatmul  (q/k/v/w1/w3/wcls, output-dim shard) -> P(..., 'tp') on out
  sliceColMatmul  (wo/w2, input-dim shard + merge-add) -> P(..., 'tp', ...) on in
  sliceKvCache / sliceMultiHeadAtt (head shard)        -> cache P on kv-head axis
  + the axis the reference lacks: cache seq axis on 'sp' (ring/context parallel)

Under pjit, XLA emits the collectives the reference hand-codes: the
col-matmul partial-sum exchange (SYNC_NODE_SLICES + OP_MERGE_ADD,
nn-network.cpp:521-554) becomes a reduce-scatter/all-gather pair on ICI.
"""

from __future__ import annotations

import jax

from dllama_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.ops.quant import QTensor
from dllama_tpu.models.llama import KVCache


# specs for stacked per-layer weights: leading L axis, then (in, out)
_ROW_SHARD = P(None, None, "tp")  # output-dim sharded (reference "row" slice)
_COL_SHARD = P(None, "tp", None)  # input-dim sharded (reference "col" slice)

LAYER_SPECS = {
    "wq": _ROW_SHARD,
    "wk": _ROW_SHARD,
    "wv": _ROW_SHARD,
    "w1": _ROW_SHARD,
    "w3": _ROW_SHARD,
    "wo": _COL_SHARD,
    "w2": _COL_SHARD,
    "rms_att": P(None, None),
    "rms_ffn": P(None, None),
    # MoE (expert axis on 'ep'; per-expert in/out dims keep the tp pattern):
    # leaves are [L, E, in, out] operands, gate is [L, dim, E] replicated —
    # the all-experts einsum psums over ep under GSPMD.
    "moe_gate": P(None, None, None),
    "moe_w1": P(None, "ep", None, "tp"),
    "moe_w3": P(None, "ep", None, "tp"),
    "moe_w2": P(None, "ep", "tp", None),
}


class LlamaShardings:
    """Placement rules bound to a concrete mesh."""

    def __init__(self, mesh: Mesh, cfg: LlamaConfig):
        self.mesh = mesh
        self.cfg = cfg
        tp = mesh.shape["tp"]
        sp = mesh.shape["sp"]
        pp = mesh.shape["pp"]
        if cfg.n_kv_heads % tp != 0:
            # the reference's hard requirement nNodes <= nKvHeads (app.cpp:201-203);
            # ours is divisibility of the kv-head axis.
            raise ValueError(f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={tp}")
        if cfg.seq_len % max(sp, 1) != 0:
            raise ValueError(f"seq_len={cfg.seq_len} not divisible by sp={sp}")
        if pp > 1:
            if cfg.n_layers % pp != 0:
                raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")
            if sp > 1:
                raise ValueError("pp x sp composition is not supported; use pp with tp/dp")

    def _named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def _sanitize(self, spec: P, *shapes) -> P:
        """Replicate any spec axis that does not evenly divide the leaf's dim
        (for every given shape): device placement requires exact tiling, and
        an oddly-sized tensor (e.g. a non-power-of-two vocab on wcls) should
        load replicated rather than crash — the reference simply refuses such
        configs (nNodes must divide every slice, nn-core.cpp:170-238)."""
        n = max(len(s) for s in shapes)
        axes = list(spec) + [None] * (n - len(spec))
        out = []
        for i, ax in enumerate(axes):
            if ax is not None and any(
                len(s) > i and s[i] % self.mesh.shape[ax] != 0 for s in shapes
            ):
                ax = None
            out.append(ax)
        return P(*out)

    def _expand(self, spec: P, leaf):
        """Spec for one leaf (QTensor packed/scales share one spec — both are
        [in?, out] shaped). Lazy (memmap-backed) Q40 leaves follow the same
        rule."""
        from dllama_tpu.models.formats import LazyQ40, LazyQ40Stack

        if isinstance(leaf, (QTensor, LazyQ40, LazyQ40Stack)):
            tp = self.mesh.shape["tp"]
            axes = tuple(spec)
            if isinstance(leaf, QTensor):
                kdim = leaf.scales.shape[-2]
                shapes = (leaf.packed.shape, leaf.scales.shape)
            else:
                kdim = leaf.scales_shape[-2]
                shapes = (leaf.packed_shape, leaf.scales_shape)
            if len(axes) >= 2 and axes[-2] == "tp" and kdim % tp != 0:
                # 'tp' on the contraction dim splits the 32-elem quant-block
                # axis: it must hold tp whole blocks (col-shard, moe_w2)
                raise ValueError(
                    f"Q40 col-shard needs in_dim % (32*tp) == 0; "
                    f"got {kdim * 32} with tp={tp}"
                )
            spec = self._sanitize(spec, *shapes)
            return QTensor(spec, spec)
        if hasattr(leaf, "shape"):
            spec = self._sanitize(spec, leaf.shape)
        return spec

    def param_spec(self, name: str, leaf):
        """Spec for a named param leaf ('embedding', 'wcls', 'layers.<short>')."""
        if name == "embedding":
            spec = P(None, None)  # replicated; vocab shard lives on wcls
        elif name == "final_norm":
            spec = P(None)
        elif name == "wcls":
            spec = P(None, "tp")
        else:
            spec = LAYER_SPECS[name.split(".")[-1]]
            if self.mesh.shape["pp"] > 1:
                # stage-split: the stacked layer axis shards over 'pp'
                spec = P("pp", *tuple(spec)[1:])
        return self._expand(spec, leaf)

    def param_spec_tree(self, params) -> dict:
        """A pytree of PartitionSpecs congruent with the params pytree."""
        return {
            "embedding": self.param_spec("embedding", params["embedding"]),
            "final_norm": self.param_spec("final_norm", params["final_norm"]),
            "wcls": self.param_spec("wcls", params["wcls"]),
            "layers": {
                name: self.param_spec(f"layers.{name}", leaf)
                for name, leaf in params["layers"].items()
            },
        }

    def param_put(self, name: str, leaf):
        """Shard-direct placement of one host-resident param leaf: each device
        receives only its shard — a model bigger than one chip's HBM never
        materializes on a single device (the reference's slice-then-ship,
        nn-network.cpp:775-869, without the wire). Lazy Q40 leaves go further:
        each shard's bytes are decoded straight off the `.m` memmap on demand,
        so a multi-host load never materializes the full tensor on ANY host."""
        from dllama_tpu.models.formats import LazyQ40, LazyQ40Stack
        from dllama_tpu.parallel.multihost import device_put_sharded

        spec = self.param_spec(name, leaf)
        if isinstance(leaf, (LazyQ40, LazyQ40Stack)):
            sh = self._named(spec.packed)  # QTensor(spec, spec): shared P

            def memo(fn):
                # make_array_from_callback invokes the callback once PER
                # addressable device with no dedup — replicated mesh axes
                # (dp, pp-replicated wcls) would re-decode identical bytes
                cache: dict = {}

                def cb(idx):
                    key = tuple((s.start, s.stop, s.step) for s in idx)
                    if key not in cache:
                        cache[key] = fn(*idx)
                    return cache[key]

                return cb

            packed = jax.make_array_from_callback(
                leaf.packed_shape, sh, memo(leaf.packed_shard)
            )
            scales = jax.make_array_from_callback(
                leaf.scales_shape, sh, memo(leaf.scales_shard)
            )
            return QTensor(packed, scales)
        return jax.tree.map(
            lambda x, s: device_put_sharded(x, self._named(s)),
            leaf,
            spec,
            is_leaf=lambda x: isinstance(x, P),
        )

    def put_params(self, params):
        from dllama_tpu.parallel.multihost import device_put_sharded

        specs = self.param_spec_tree(params)
        return jax.tree.map(
            lambda x, s: device_put_sharded(x, self._named(s)),
            params,
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _batch_axis(self, batch: int) -> str | None:
        # batch shards over dp only when divisible (a single sequence stays
        # replicated over dp)
        return "dp" if batch % self.mesh.shape["dp"] == 0 else None

    def cache_spec(self, batch: int) -> P:
        # [n_layers, batch, n_kv_heads, seq, head_size]
        layer_axis = "pp" if self.mesh.shape["pp"] > 1 else None
        return P(layer_axis, self._batch_axis(batch), "tp", "sp", None)

    def put_cache(self, cache: KVCache) -> KVCache:
        from dllama_tpu.parallel.multihost import device_put_sharded

        s = self._named(self.cache_spec(batch=cache.k.shape[1]))
        return KVCache(device_put_sharded(cache.k, s), device_put_sharded(cache.v, s))

    def put_replicated(self, x):
        from dllama_tpu.parallel.multihost import device_put_sharded

        return device_put_sharded(x, self._named(P()))

    def attn_fn(self, batch: int):
        """shard_map'd sequence-parallel attention when sp > 1, else None
        (plain full-cache GQA; XLA handles tp head sharding by itself)."""
        if self.mesh.shape["sp"] == 1:
            return None
        from dllama_tpu.parallel.ring_attention import make_sp_attention

        return make_sp_attention(self.mesh, self._batch_axis(batch))

    def tokens_spec(self) -> P:
        return P("dp", None)

    # ---------------------------------------------- sharded Pallas kernels
    #
    # pallas_call has no GSPMD partitioning rule, so under a mesh the fused
    # Q40 kernels must run inside shard_map: each chip executes the kernel on
    # its local weight shard and XLA only sees the manual region's collectives.
    # This keeps the reference's TP decomposition (llm.cpp:133-141) fused:
    # out-dim-sharded matmuls (wq/wk/wv/w1/w3/wcls) are embarrassingly
    # parallel, in-dim-sharded ones (wo/w2) psum their partials — the
    # SYNC_NODE_SLICES + OP_MERGE_ADD exchange (nn-network.cpp:521-554) as one
    # ICI psum per call.

    def supports_sharded_pallas(self) -> bool:
        """tp/dp meshes only: sp needs ring attention (its own shard_map) and
        pp replaces the layer scan with the stage schedule."""
        return self.mesh.shape["sp"] == 1 and self.mesh.shape["pp"] == 1

    def pallas_mms(self, batch: int):
        """(mm, mm_in) shard_map-wrapped Pallas matmuls for the model forward.

        mm:    x @ w with w sharded on the OUTPUT dim -> out sharded on 'tp'
        mm_in: x @ w with w sharded on the INPUT dim  -> psum('tp'), replicated
        Both take (x[B,T,K], w: QTensor 2-D or [L,...] stacked, layer) like
        ops.matmul.matmul; untileable shards fall back to the XLA path inside
        the manual region (ops.matmul dispatch runs per-shard).
        """
        from functools import partial

        from dllama_tpu.ops.matmul import matmul

        mesh = self.mesh
        b_ax = self._batch_axis(batch)
        pmm = partial(matmul, backend="pallas")

        def make(shard_dim: int, reduce_over_tp: bool):
            """shard_dim: weight dim carrying 'tp' (-1 out-shard, -2 in-shard)."""

            def call(x, w, layer=None):
                is_q = isinstance(w, QTensor)
                nd = w.packed.ndim if is_q else jnp.ndim(w)
                axes = [None] * nd
                axes[shard_dim] = "tp"
                wspec = P(*axes)
                wspec_t = QTensor(wspec, wspec) if is_q else wspec
                x_spec = P(b_ax, None, "tp" if reduce_over_tp else None)
                out_spec = P(b_ax, None, None if reduce_over_tp else "tp")

                def body(x, w, li=None):
                    out = pmm(x, w, li)
                    return jax.lax.psum(out, "tp") if reduce_over_tp else out

                if nd == 3:  # layer-stacked weight: the layer index rides along
                    fn = _shard_map(
                        body, mesh=mesh, in_specs=(x_spec, wspec_t, P()),
                        out_specs=out_spec, check_vma=False,
                    )
                    return fn(x, w, jnp.asarray(layer, jnp.int32))
                fn = _shard_map(
                    lambda x, w: body(x, w), mesh=mesh,
                    in_specs=(x_spec, wspec_t), out_specs=out_spec, check_vma=False,
                )
                return fn(x, w)

            return call

        return make(-1, False), make(-2, True)

    def pallas_attn(self, batch: int, interpret: bool = False):
        """Head-sharded flash attention: each chip runs the online-softmax
        kernel on its local kv-head shard (attention is per-head local — the
        reference's sliceMultiHeadAtt, nn-core.cpp:215-238)."""
        from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

        mesh = self.mesh
        b_ax = self._batch_axis(batch)

        def attn(q, k_cache, v_cache, pos_base):
            b = q.shape[0]
            pos_vec = jnp.broadcast_to(
                jnp.atleast_1d(jnp.asarray(pos_base, jnp.int32)), (b,)
            )
            fn = _shard_map(
                lambda q, k, v, p: flash_gqa_attention(q, k, v, p, interpret=interpret),
                mesh=mesh,
                in_specs=(
                    P(b_ax, None, "tp", None),   # q [B, T, Hq, hd]
                    P(b_ax, "tp", None, None),   # k cache [B, Hkv, S, hd]
                    P(b_ax, "tp", None, None),
                    P(b_ax),                     # per-row positions
                ),
                out_specs=P(b_ax, None, "tp", None),
                check_vma=False,
            )
            return fn(q, k_cache, v_cache, pos_vec)

        return attn
