"""Ring / sequence-parallel attention — the long-context capability the
reference explicitly lacks (SURVEY.md §5.7: its only lever is `--max-seq-len`
RAM clamping; each node holds the full sequence of its KV-head slice,
nn-core.cpp:170-177).

Two primitives, both exact (online-softmax rescaling, f32 accumulation):

* :func:`ring_attention` — blockwise causal attention with queries AND keys
  sharded over the `sp` axis; KV blocks rotate around the ring with
  `lax.ppermute` while each shard accumulates its queries' partial softmax.
  O(S/sp) memory per device, comm overlapped with the next block's compute by
  XLA. This is the prefill path for sequences that don't fit one device.

* :func:`sp_cache_attention` — decode/chunked-prefill attention over a KV
  *cache* whose sequence axis is sharded on `sp` (replicated queries): each
  shard computes a partial (numerator, max, denominator) over its cache slice,
  merged with one `pmax` + `psum` of per-head scalars — tiny collectives vs.
  all-gathering the cache.

Both run inside `jax.shard_map`; `NEG` is the mask value (finite, so fully
masked shards produce exp(NEG-m)=0 instead of NaN).
"""

from __future__ import annotations

import math
from functools import partial

import jax

from dllama_tpu.parallel import shard_map as _shard_map


def _axis_size(axis_name):
    """jax-version compat: jax.lax.axis_size is missing on 0.4.x —
    psum(1) over the axis is the portable spelling of its size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG = -1e30


def _block_scores(q, k, scale):
    """q [B,T,Hkv,G,d] x k [B,Hkv,S,d] -> scores f32 [B,Hkv,G,T,S]."""
    return jnp.einsum(
        "bthgd,bhsd->bhgts",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale


def _merge(acc, o, m, l):
    """Online-softmax merge of a new block's (unnormalized out, max, denom)."""
    o0, m0, l0 = acc
    m_new = jnp.maximum(m0, m)
    a0 = jnp.exp(m0 - m_new)
    a1 = jnp.exp(m - m_new)
    return (
        o0 * a0[..., None] + o * a1[..., None],
        m_new,
        l0 * a0 + l * a1,
    )


def _partial_attn(q, k, v, mask, scale):
    """-> (o_unnorm [B,Hkv,G,T,d], m [B,Hkv,G,T], l [B,Hkv,G,T])."""
    s = jnp.where(mask, _block_scores(q, k, scale), NEG)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask, p, 0.0)  # kill exp(NEG-NEG)=1 rows where all-masked
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhgts,bhsd->bhgtd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(
    q: jax.Array,  # [B, Tl, Hq, d] this shard's query block (global pos = idx*Tl + t)
    k: jax.Array,  # [B, Hkv, Sl, d] this shard's KV block (same global layout)
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Exact blockwise-causal attention over the ring; call inside shard_map.

    Sequence layout: device i of the sp axis owns tokens [i*Tl, (i+1)*Tl).
    Each of the `sp` steps attends local queries to one rotating KV block and
    merges with the running softmax state; `ppermute` shifts KV to the next
    neighbor so every (query block, kv block) pair meets exactly once.
    """
    b, tl, hq, d = q.shape
    hkv, sl = k.shape[1], k.shape[2]
    g = hq // hkv
    sp = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(d)

    qg = q.reshape(b, tl, hkv, g, d)
    q_pos = idx * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, sl), 0)

    o = jnp.zeros((b, hkv, g, tl, d), jnp.float32)
    m = jnp.full((b, hkv, g, tl), NEG, jnp.float32)
    l = jnp.zeros((b, hkv, g, tl), jnp.float32)
    acc = (o, m, l)

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    for step in range(sp):
        src = (idx - step) % sp  # owner of the KV block currently held
        if causal:
            k_pos = src * sl + jax.lax.broadcasted_iota(jnp.int32, (tl, sl), 1)
            mask = (k_pos <= q_pos)[None, None, None]
        else:
            mask = jnp.ones((1, 1, 1, tl, sl), bool)
        acc = _merge(acc, *_partial_attn(qg, k, v, mask, scale))
        if step + 1 < sp:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    o, m, l = acc
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, tl, hq, d).astype(q.dtype)


def sp_cache_attention(
    q: jax.Array,  # [B, T, Hq, d] replicated over sp
    k_cache: jax.Array,  # [B, Hkv, Sl, d] local seq shard of the cache
    v_cache: jax.Array,
    pos_base: jax.Array,  # scalar i32 — absolute position of query 0
    *,
    axis_name: str = "sp",
) -> jax.Array:
    """GQA over an sp-sharded KV cache; call inside shard_map.

    Replaces a full-cache gather with an LSE merge: pmax of per-row maxima,
    psum of the rescaled numerator/denominator (scaling-book flash-decoding
    recipe). Exact vs. single-device softmax.
    """
    b, t, hq, d = q.shape
    hkv, sl = k_cache.shape[1], k_cache.shape[2]
    g = hq // hkv
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(d)

    qg = q.reshape(b, t, hkv, g, d)
    slot = idx * sl + jax.lax.broadcasted_iota(jnp.int32, (t, sl), 1)
    limit = pos_base + jax.lax.broadcasted_iota(jnp.int32, (t, sl), 0)
    mask = (slot <= limit)[None, None, None]

    o, m, l = _partial_attn(qg, k_cache, v_cache, mask, scale)
    m_g = jax.lax.pmax(m, axis_name)
    a = jnp.exp(m - m_g)
    num = jax.lax.psum(o * a[..., None], axis_name)
    den = jax.lax.psum(l * a, axis_name)
    out = num / jnp.maximum(den, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, t, hq, d).astype(q.dtype)


def ring_cache_attention(
    q: jax.Array,  # [B, Tl, Hq, d] this shard's slice of the chunk's queries
    k_cache: jax.Array,  # [B, Hkv, Sl, d] local seq shard of the cache
    v_cache: jax.Array,
    pos_base: jax.Array,  # scalar i32 — absolute position of the chunk's query 0
    *,
    axis_name: str = "sp",
) -> jax.Array:
    """Chunked-prefill attention with queries sequence-sharded over `sp` and
    the KV *cache* ring-rotating; call inside shard_map.

    The chunk's own keys are already written into the sp-sharded cache (the
    cache update runs before attention in models/llama._layer), so each of the
    `sp` steps attends local queries to one rotating cache block — masked to
    global slots <= the query's absolute position — and merges the partial
    softmax. vs. sp_cache_attention this also parallelizes the *query* axis:
    qkv/FFN matmuls upstream shard over sp instead of being replicated, which
    is the long-context prefill capability the reference lacks (SURVEY §5.7).
    """
    b, tl, hq, d = q.shape
    hkv, sl = k_cache.shape[1], k_cache.shape[2]
    g = hq // hkv
    sp = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(d)

    qg = q.reshape(b, tl, hkv, g, d)
    q_pos = pos_base + idx * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, sl), 0)

    o = jnp.zeros((b, hkv, g, tl, d), jnp.float32)
    m = jnp.full((b, hkv, g, tl), NEG, jnp.float32)
    l = jnp.zeros((b, hkv, g, tl), jnp.float32)
    acc = (o, m, l)

    k, v = k_cache, v_cache
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    for step in range(sp):
        src = (idx - step) % sp  # owner of the cache block currently held
        slot = src * sl + jax.lax.broadcasted_iota(jnp.int32, (tl, sl), 1)
        mask = (slot <= q_pos)[None, None, None]
        acc = _merge(acc, *_partial_attn(qg, k, v, mask, scale))
        if step + 1 < sp:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    o, m, l = acc
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, tl, hq, d).astype(q.dtype)


def make_sp_attention(mesh, cache_batch_spec=None):
    """Build the shard_map-wrapped attention for llama.forward's `attn_fn` slot.

    Specs mirror LlamaShardings.cache_spec: cache [B, Hkv, S, d] ->
    P(dp?, 'tp', 'sp', None). Dispatch is static on the chunk width T:
    multi-token chunks divisible by sp take :func:`ring_cache_attention`
    (queries sharded over sp — true sequence-parallel prefill); decode and
    ragged chunks take :func:`sp_cache_attention` (replicated queries, LSE
    merge over the cache shards).
    """
    dp = cache_batch_spec
    sp = mesh.shape["sp"]

    def attn(q, k_cache, v_cache, pos_base):
        t = q.shape[1]  # static under jit
        if t > 1 and t % sp == 0:
            return _shard_map(
                partial(ring_cache_attention, axis_name="sp"),
                mesh=mesh,
                in_specs=(P(dp, "sp", "tp", None), P(dp, "tp", "sp", None),
                          P(dp, "tp", "sp", None), P()),
                out_specs=P(dp, "sp", "tp", None),
            )(q, k_cache, v_cache, pos_base)
        return _shard_map(
            partial(sp_cache_attention, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(dp, None, "tp", None), P(dp, "tp", "sp", None), P(dp, "tp", "sp", None), P()),
            out_specs=P(dp, None, "tp", None),
        )(q, k_cache, v_cache, pos_base)

    return attn
