"""Parallelism tier: mesh/sharding specs, collectives, ring attention,
pipeline and multi-host glue.

`shard_map` below is the jax-version compat accessor: newer jax exposes it
as ``jax.shard_map``; 0.4.x only has ``jax.experimental.shard_map``. Every
call site in this package imports it from here so one jax pin change cannot
strand the whole mesh tier (same pattern as ops/pallas/tiling.COMPILER_PARAMS).
"""

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # jax < 0.5
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None, **kw):
        """Adapt the modern keyword surface to 0.4.x's experimental one:
        ``axis_names`` (the MANUAL axes) becomes its complement ``auto``,
        and ``check_vma`` was called ``check_rep``."""
        if axis_names is not None:
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        if check_vma is not None:
            kw["check_rep"] = bool(check_vma)
        return _exp_shard_map(f, mesh, in_specs, out_specs, **kw)
