"""Multi-host bootstrap: every host runs the same binary over one global mesh.

The reference scales across machines with `dllama worker --port 9998` per node
plus a root that dials them (app.cpp:262-321, nn-network.cpp:254-339). The
TPU-native equivalent inverts the topology: there is no root/worker split —
every host launches the SAME command, `jax.distributed` forms the global
runtime (coordinator elected via --coordinator or TPU-pod metadata), and one
Mesh spans all chips; GSPMD collectives over ICI/DCN replace the socket mesh.

Weight loading on a multi-host mesh: each host mmaps the same `.m` file and
materializes only the shards its local chips own — Q40 matmul weights decode
per-shard byte ranges straight off the memmap (models/formats.LazyQ40 via
`jax.make_array_from_callback` in sharding.param_put); smaller replicated
tensors go through :func:`device_put_sharded` below. The root→worker weight
shipping protocol (nn-network.cpp:775-869) becomes local file reads.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

log = logging.getLogger("dllama_tpu")


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """jax.distributed.initialize with optional explicit rendezvous.

    On Cloud TPU pods all three args are discovered from metadata — run the
    same command on every host with no flags. Elsewhere (CPU/GPU fleets or
    manual TPU setups) pass --coordinator host:port --num-processes N
    --process-id I per host.
    """
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    log.info(
        "distributed: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def device_put_sharded(x, sharding):
    """Place a host-resident array with `sharding`, working on multi-host
    meshes: each process materializes only its addressable shards from its own
    full host copy (every host loads the same file — no weight shipping)."""
    if isinstance(x, jax.Array) and x.sharding == sharding:
        return x  # already placed (shard-direct load path); re-put is a no-op
    if jax.process_count() > 1:
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])
    return jax.device_put(x, sharding)
