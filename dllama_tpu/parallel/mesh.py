"""Device mesh with every parallelism axis as a first-class name.

The reference has exactly one compute-parallel axis — TP over 2^n TCP nodes
(SURVEY.md §2.4). Here all five axes exist as named mesh dimensions from day
one, so a sharding is a PartitionSpec over ('dp','pp','sp','tp','ep') instead
of hand-written slicing math (nn-core.cpp:170-238):

  dp — data parallel (batch replicas for serving)
  pp — pipeline parallel (stage-split across pods / DCN)
  sp — sequence/context parallel (KV sequence axis; ring attention)
  tp — tensor parallel (the reference's node axis; rides ICI)
  ep — expert parallel (MoE; the header's N_EXPERTS the reference never uses)
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "sp", "tp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp * self.ep

    def axis_sizes(self) -> tuple[int, ...]:
        return (self.dp, self.pp, self.sp, self.tp, self.ep)

    @classmethod
    def parse(cls, spec: str) -> "MeshConfig":
        """Parse 'tp=4,dp=2' style CLI strings."""
        kwargs = {}
        for part in spec.split(","):
            if not part:
                continue
            k, _, v = part.partition("=")
            if k not in AXES:
                raise ValueError(f"unknown mesh axis {k!r}; valid: {AXES}")
            kwargs[k] = int(v)
        return cls(**kwargs)


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build the 5-axis mesh. tp is the innermost (fastest-varying) axis so
    tensor-parallel collectives ride neighboring ICI links."""
    devices = list(devices if devices is not None else jax.devices())
    if config is None:
        config = MeshConfig(tp=len(devices))
    if config.n_devices > len(devices):
        raise ValueError(f"mesh needs {config.n_devices} devices, have {len(devices)}")
    devices = devices[: config.n_devices]
    grid = np.array(devices).reshape(config.axis_sizes())
    return Mesh(grid, AXES)


def auto_mesh_config(n_devices: int, n_kv_heads: int, want_sp: bool = False) -> MeshConfig:
    """Pick a (dp, sp, tp) factoring for n devices.

    tp is capped at n_kv_heads (the reference's nNodes <= nKvHeads rule,
    app.cpp:201-203 — each shard needs >= 1 KV head); the remainder goes to
    sp (if requested) then dp.
    """
    tp = 1
    for d in range(min(n_devices, n_kv_heads), 0, -1):
        if n_devices % d == 0 and n_kv_heads % d == 0:
            tp = d
            break
    rest = n_devices // tp
    sp = 1
    if want_sp and rest % 2 == 0:
        sp = 2
        rest //= 2
    return MeshConfig(dp=rest, sp=sp, tp=tp)
