"""Quantized collectives: the reference's Q80 activation exchange on ICI.

The reference never moves f32 activations between nodes — every
SYNC_NODE_SLICES rides the Q80-quantized ZQ pipe, and the col-matmul
"all-reduce" is an all-gather of quantized partial sums + local merge-add
(SURVEY.md §3.4, nn-network.cpp:521-554, nn-cpu-ops.cpp:838-875). These are
the shard_map-level equivalents, for use when bf16 collectives are
bandwidth-bound (measure before enabling — ICI is fast enough that bf16 is
the default; Q80 halves the payload at ~1e-2 relative error).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dllama_tpu.ops.quant import dequantize_q80_jnp, quantize_q80_jnp


def q80_all_gather(x: jax.Array, axis_name: str, axis: int = 0, tiled: bool = True) -> jax.Array:
    """all_gather(x) with the payload quantized to Q80 (codes i8 + f32 block
    scales) — 1/2 the bytes of bf16, 1/4 of f32 on the wire."""
    codes, scales = quantize_q80_jnp(x)
    codes_g = jax.lax.all_gather(codes, axis_name, axis=axis, tiled=tiled)
    scales_g = jax.lax.all_gather(scales, axis_name, axis=axis, tiled=tiled)
    return dequantize_q80_jnp(codes_g, scales_g, x.dtype)


def q80_all_reduce(x: jax.Array, axis_name: str) -> jax.Array:
    """The reference's all-reduce: all-gather Q80 partial sums, reduce locally
    (all-gather + merge-add ≡ all-reduce, SURVEY.md §3.4). Payload is the
    quantized partials; the reduction itself is f32 on-chip."""
    codes, scales = quantize_q80_jnp(x)
    codes_g = jax.lax.all_gather(codes, axis_name, axis=0, tiled=False)
    scales_g = jax.lax.all_gather(scales, axis_name, axis=0, tiled=False)
    parts = dequantize_q80_jnp(codes_g, scales_g, jnp.float32)
    return jnp.sum(parts, axis=0).astype(x.dtype)
