"""Quantized collectives: the reference's Q80 activation exchange on ICI.

The reference never moves f32 activations between nodes — every
SYNC_NODE_SLICES rides the Q80-quantized ZQ pipe, and the col-matmul
"all-reduce" is an all-gather of quantized partial sums + local merge-add
(SURVEY.md §3.4, nn-network.cpp:521-554, nn-cpu-ops.cpp:838-875). These are
the shard_map-level equivalents, for use when bf16 collectives are
bandwidth-bound (measure before enabling — ICI is fast enough that bf16 is
the default; Q80 halves the payload at ~1e-2 relative error).
"""

from __future__ import annotations

import jax

from dllama_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp

from dllama_tpu.ops.quant import dequantize_q80_jnp, quantize_q80_jnp

_F16_MAX = 65504.0


def _f16_wire(scales: jax.Array) -> jax.Array:
    """f32 block scales -> f16 for the wire, saturation-safe: a block with
    absmax > ~8.3e6 would otherwise overflow f16 to inf and poison the whole
    reduced tensor. Clamping to f16-max keeps the block merely coarser."""
    return jnp.clip(scales, -_F16_MAX, _F16_MAX).astype(jnp.float16)


def q80_all_gather(x: jax.Array, axis_name: str, axis: int = 0, tiled: bool = True) -> jax.Array:
    """all_gather(x) with the payload quantized to Q80 (codes i8 + f16 block
    scales, the reference's own NnBlockQ80 wire format) — ~1/2 the bytes of
    bf16, ~1/4 of f32 on the wire."""
    codes, scales = quantize_q80_jnp(x)
    codes_g = jax.lax.all_gather(codes, axis_name, axis=axis, tiled=tiled)
    scales_g = jax.lax.all_gather(_f16_wire(scales), axis_name, axis=axis, tiled=tiled)
    return dequantize_q80_jnp(codes_g, scales_g.astype(jnp.float32), x.dtype)


def q80_all_reduce(x: jax.Array, axis_name: str) -> jax.Array:
    """The reference's all-reduce: all-gather Q80 partial sums, reduce locally
    (all-gather + merge-add ≡ all-reduce, SURVEY.md §3.4). Payload is the
    quantized partials with f16 scales (NnBlockQ80's wire dtype; the f32→f16
    scale rounding is ~5e-4 relative, far inside Q80's ~1e-2 step); the
    reduction itself is f32 on-chip."""
    codes, scales = quantize_q80_jnp(x)
    codes_g = jax.lax.all_gather(codes, axis_name, axis=0, tiled=False)
    scales_g = jax.lax.all_gather(_f16_wire(scales), axis_name, axis=0, tiled=False)
    parts = dequantize_q80_jnp(codes_g, scales_g.astype(jnp.float32), jnp.float32)
    return jnp.sum(parts, axis=0).astype(x.dtype)


def resolve_sync(sync: str, shardings) -> str:
    """Resolve the tp activation-exchange payload ('auto' -> 'bf16'|'q80').

    The data-earned policy (VERDICT r4 next #3), from the committed
    collective-bytes record (COLLECTIVES.md). The DEFAULT stays 'bf16'
    everywhere — sync payloads are <0.1% of a decode step's HBM traffic, so
    an unmeasured latency win does not buy a lossy default — but 'auto'
    encodes the recommendation for users who want it:

    * tp=2 — q80 wins on BOTH accountings: measured post-SPMD HLO bytes
      (8b: 544 vs 1024 KB/tok/chip) AND the analytic wire model (522 vs
      762). 'auto' takes the quantized exchange.
    * tp>=4 — the accountings DISAGREE: the q80 all-gather formulation
      materializes more HLO bytes than the bf16 all-reduce (8b tp8: 2176
      vs 1024 KB) while the wire model still favors q80 (586 vs 1006).
      Real ICI cannot be timed in this environment (one tunneled chip), so
      'auto' stays on the conservative bf16 all-reduce until a multi-chip
      window re-measures; explicit '--sync q80' remains available.
    * pp meshes — the q80 col_fn is not supported there; 'auto' degrades
      to bf16 instead of raising.

    Reference analog: `--buffer-float-type q80` (app.cpp:204-205),
    recommended unconditionally there; the XLA lowering earns a narrower
    recommendation."""
    if sync not in ("auto", "bf16", "q80"):
        raise ValueError(f"sync must be 'auto', 'bf16' or 'q80', got {sync!r}")
    if sync != "auto":
        return sync
    if shardings is None:
        return "bf16"
    shape = shardings.mesh.shape
    if shape.get("pp", 1) > 1:
        return "bf16"
    return "q80" if shape["tp"] == 2 else "bf16"


def make_q80_col_matmul(mesh):
    """`--sync q80`: the runtime caller of :func:`q80_all_reduce`.

    Returns a drop-in for the wo/w2 col-sharded matmuls in models/llama._layer:
    a shard_map manual over 'tp' only (dp/sp stay GSPMD-auto) that computes the
    local partial product and exchanges it Q80-quantized — the reference's
    load-bearing ZQ-pipe trick (nn-network.cpp:521-554) as an ICI option.
    Output error is the Q80 step (~1e-2 relative), identical to the
    reference's `--buffer-float-type q80` accuracy contract.
    """
    from jax.sharding import PartitionSpec as P

    from dllama_tpu.ops.matmul import matmul
    from dllama_tpu.ops.quant import QTensor

    def body(xl, wl):
        return q80_all_reduce(matmul(xl, wl), "tp")

    def col_matmul(x, w):
        w_spec = P("tp", None)  # [in, out] with the contraction dim tp-sharded
        if isinstance(w, QTensor):
            w_spec = QTensor(w_spec, w_spec)
        return _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(None, None, "tp"), w_spec),
            out_specs=P(),
            axis_names=frozenset({"tp"}),
            check_vma=False,
        )(x, w)

    return col_matmul
