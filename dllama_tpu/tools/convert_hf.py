"""Convert a HuggingFace safetensors checkpoint to the `.m` format.

Analog of the reference converter (converter/convert-hf.py): reads
``config.json`` + ``*.safetensors`` shards lazily (one tensor materialized at
a time), applies the Q/K rope permutation, and streams tensors to disk in the
fixed `.m` plan order (llm.cpp:453-468).

Usage:
    python -m dllama_tpu.tools.convert_hf <model_dir> <weight_type> [--output out.m] [--max-seq-len N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dllama_tpu.ops.quant import parse_float_type
from dllama_tpu.tools.converter_core import (
    default_output_name,
    hf_config_to_llama,
    hf_tensor_for,
    write_model,
)


class SafetensorsDir:
    """Lazy tensor accessor over a sharded safetensors checkpoint dir."""

    def __init__(self, model_dir: str):
        from safetensors import safe_open

        self._safe_open = safe_open
        self.model_dir = model_dir
        index_path = os.path.join(model_dir, "model.safetensors.index.json")
        if os.path.exists(index_path):
            with open(index_path) as f:
                self.weight_map = json.load(f)["weight_map"]
        else:
            single = [fn for fn in sorted(os.listdir(model_dir)) if fn.endswith(".safetensors")]
            if not single:
                raise FileNotFoundError(f"no .safetensors files in {model_dir}")
            self.weight_map = {}
            for fn in single:
                with safe_open(os.path.join(model_dir, fn), framework="np") as f:
                    for key in f.keys():
                        self.weight_map[key] = fn
        self._open_file = None
        self._open_name = None

    def __contains__(self, name: str) -> bool:
        return name in self.weight_map

    def get(self, name: str):
        """Returns the tensor as float32 numpy. KeyError if absent."""
        import numpy as np

        fn = self.weight_map[name]  # KeyError propagates (tied-embedding probe)
        if self._open_name != fn:
            if self._open_file is not None:
                self._open_file.__exit__(None, None, None)
            self._open_file = self._safe_open(
                os.path.join(self.model_dir, fn), framework="np"
            ).__enter__()
            self._open_name = fn
        x = self._open_file.get_tensor(name)
        if x.dtype == np.uint16:  # bfloat16 stored raw; upcast via int shift
            x = (x.astype(np.uint32) << 16).view(np.float32)
        return x.astype(np.float32)

    def close(self) -> None:
        if self._open_file is not None:
            self._open_file.__exit__(None, None, None)
            self._open_file = None


def convert_hf(model_dir: str, weight_type_name: str, output: str | None = None,
               max_seq_len: int | None = None) -> str:
    weight_type = parse_float_type(weight_type_name)
    with open(os.path.join(model_dir, "config.json")) as f:
        hf_config = json.load(f)
    cfg = hf_config_to_llama(hf_config, weight_type)
    if max_seq_len:
        cfg = cfg.clamp_seq_len(max_seq_len)
    if output is None:
        output = default_output_name(model_dir, weight_type_name)

    src = SafetensorsDir(model_dir)
    write_model(cfg, output, lambda name: hf_tensor_for(name, cfg, src.get))
    src.close()
    return output


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("model_dir", help="HF checkpoint dir (config.json + *.safetensors)")
    p.add_argument("weight_type", choices=["q40", "q80", "f16", "f32"], help="on-disk matmul weight type")
    p.add_argument("--output", default=None, help="output .m path")
    p.add_argument("--max-seq-len", type=int, default=None, help="clamp seq_len in the header")
    args = p.parse_args(argv)
    convert_hf(args.model_dir, args.weight_type, args.output, args.max_seq_len)
    return 0


if __name__ == "__main__":
    sys.exit(main())
