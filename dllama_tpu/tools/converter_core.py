"""Checkpoint conversion core: HF/Meta state dicts -> `.m` tensor plan.

Framework-agnostic (numpy in, numpy out) so the parity tests can exercise the
exact same mapping the CLI converters use. Mirrors the reference converter's
tensor plan and Q/K permutation (convert-hf.py:11-14,51-89): HF stores Q/K in
rotate-half rope layout; the `.m` format stores the Meta *interleaved-pair*
layout, related by a per-head even/odd interleave of rows.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from dllama_tpu.models.config import ArchType, HiddenAct, LlamaConfig, RopeType
from dllama_tpu.ops.quant import FloatType, parse_float_type


def permute_rope(w: np.ndarray, n_heads: int) -> np.ndarray:
    """HF rotate-half -> Meta interleaved layout for a [n_heads*hd, in] proj.

    Row-block view per head: [hd/2 "first halves", hd/2 "second halves"] ->
    interleaved (pair i = rows i and i+hd/2). Same transform as
    convert-hf.py:11-14.
    """
    out_dim = w.shape[0]
    return (
        w.reshape(n_heads, 2, out_dim // n_heads // 2, *w.shape[1:])
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


def hf_config_to_llama(config: Mapping, weight_type: FloatType) -> LlamaConfig:
    """HF config.json -> LlamaConfig (mirrors convert-hf.py:152-195)."""
    arch = {
        "llama": ArchType.LLAMA,
        "mistral": ArchType.LLAMA,
        "mixtral": ArchType.LLAMA,
    }.get(config["model_type"])
    if arch is None:
        raise ValueError(f"unsupported arch type: {config['model_type']}")
    act = {"gelu": HiddenAct.GELU, "silu": HiddenAct.SILU}.get(config["hidden_act"])
    if act is None:
        raise ValueError(f"unsupported hidden act: {config['hidden_act']}")
    kwargs = dict(
        arch=arch,
        hidden_act=act,
        dim=config["hidden_size"],
        hidden_dim=config["intermediate_size"],
        n_layers=config["num_hidden_layers"],
        n_heads=config["num_attention_heads"],
        n_kv_heads=config["num_key_value_heads"],
        weight_type=weight_type,
        seq_len=config["max_position_embeddings"],
        vocab_size=config["vocab_size"],
        n_experts=int(config.get("num_local_experts") or 0),
        n_active_experts=int(
            config.get("num_active_local_experts") or config.get("num_experts_per_tok") or 0
        ),
        norm_epsilon=float(config.get("rms_norm_eps", 1e-5)),
    )
    if config.get("rope_theta") is not None:
        kwargs["rope_theta"] = float(config["rope_theta"])
    scaling = config.get("rope_scaling")
    if scaling is not None:
        if scaling.get("rope_type", scaling.get("type")) != "llama3":
            raise ValueError(f"unsupported rope scaling: {scaling}")
        kwargs.update(
            rope_type=RopeType.LLAMA3_1,
            rope_scaling_factor=float(scaling["factor"]),
            rope_scaling_low_freq_factor=float(scaling["low_freq_factor"]),
            rope_scaling_high_freq_factor=float(scaling["high_freq_factor"]),
            rope_scaling_orig_max_seq_len=int(scaling["original_max_position_embeddings"]),
        )
    return LlamaConfig(**kwargs)


# `.m` plan name -> HF tensor name template (convert-hf.py:51-89 order)
HF_NAME_MAP = {
    "embedding": "model.embed_tokens.weight",
    "wq": "model.layers.{l}.self_attn.q_proj.weight",
    "wk": "model.layers.{l}.self_attn.k_proj.weight",
    "wv": "model.layers.{l}.self_attn.v_proj.weight",
    "wo": "model.layers.{l}.self_attn.o_proj.weight",
    "w1": "model.layers.{l}.mlp.gate_proj.weight",
    "w2": "model.layers.{l}.mlp.down_proj.weight",
    "w3": "model.layers.{l}.mlp.up_proj.weight",
    "rms_att": "model.layers.{l}.input_layernorm.weight",
    "rms_ffn": "model.layers.{l}.post_attention_layernorm.weight",
    "final_norm": "model.norm.weight",
    "wcls": "lm_head.weight",
    # Mixtral-style sparse MoE (convert-hf.py:66-73 wrote these tensors too,
    # but the reference runtime never consumed them)
    "moe_gate": "model.layers.{l}.block_sparse_moe.gate.weight",
    "moe_w1": "model.layers.{l}.block_sparse_moe.experts.{e}.w1.weight",
    "moe_w2": "model.layers.{l}.block_sparse_moe.experts.{e}.w2.weight",
    "moe_w3": "model.layers.{l}.block_sparse_moe.experts.{e}.w3.weight",
}


def hf_tensor_for(name: str, cfg: LlamaConfig, get) -> np.ndarray:
    """Fetch + transform the HF tensor for a `.m` plan entry.

    `get(hf_name)` -> np.ndarray. Handles the Q/K rope permutation and tied
    embeddings (lm_head absent => reuse embed_tokens).
    """
    parts = name.split(".")
    if len(parts) == 3:
        _, layer, short = parts
        if short.startswith("moe_") and short != "moe_gate":
            return np.stack(
                [
                    get(HF_NAME_MAP[short].format(l=layer, e=e))
                    for e in range(cfg.n_experts)
                ],
                axis=0,
            )
        hf_name = HF_NAME_MAP[short].format(l=layer)
        x = get(hf_name)
        if short == "wq":
            x = permute_rope(x, cfg.n_heads)
        elif short == "wk":
            x = permute_rope(x, cfg.n_kv_heads)
        return x
    if name == "wcls":
        try:
            return get(HF_NAME_MAP["wcls"])
        except KeyError:
            return get(HF_NAME_MAP["embedding"])  # tied embeddings
    return get(HF_NAME_MAP[name])


def default_output_name(model_dir: str, weight_type_name: str) -> str:
    import os

    base = os.path.basename(os.path.normpath(model_dir)).lower().replace(" ", "-")
    return f"dllama_model_{base}_{weight_type_name.lower()}.m"


def write_model(cfg: LlamaConfig, output: str, get_tensor) -> str:
    """Stream the full tensor plan to `output`: header, then each tensor from
    ``get_tensor(plan_name) -> np.ndarray f32``, shape-checked and quantized
    per the plan. Shared by the HF and Meta converter CLIs."""
    import os
    import time

    from dllama_tpu.models.formats import tensor_plan, write_header, write_tensor

    plan = tensor_plan(cfg)
    t0 = time.time()
    with open(output, "wb") as f:
        write_header(f, cfg)
        for i, (name, shape, ft) in enumerate(plan):
            x = get_tensor(name)
            if tuple(x.shape) != tuple(shape):
                raise ValueError(f"{name}: expected shape {shape}, got {x.shape}")
            nbytes = write_tensor(f, x, ft)
            print(f"💾 [{i + 1}/{len(plan)}] {name} {tuple(shape)} -> {nbytes} bytes", flush=True)
    print(f"✅ Created {output} ({os.path.getsize(output) / 1e9:.2f} GB, {time.time() - t0:.1f}s)")
    return output
