"""Convert tokenizers to the `.t` format.

Analog of the reference's three converter scripts
(converter/convert-tokenizer-{hf,llama2,llama3}.py), as subcommands:

  hf <dir>         HF fast tokenizer: parses tokenizer.json directly
                   (byte-level BPE unicode aliases -> raw bytes, score = -id),
                   chat template + bos/eos from tokenizer_config.json/config.json.
  llama2 <dir>     sentencepiece tokenizer.model — parsed with a minimal
                   protobuf reader (no sentencepiece dependency), ▁ -> space.
  llama3 <path>    tiktoken-style base64 vocab + the 256 llama3 special tokens.

Usage: python -m dllama_tpu.tools.convert_tokenizer hf <dir> --name mymodel
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import re
import struct
import sys

from dllama_tpu.tokenizer.tokenizer import Tokenizer


def byte_decoder() -> dict[str, int]:
    """GPT-2 byte-level BPE unicode-alias -> byte value map (inverse of the
    printable-codepoint encoding HF fast tokenizers use for raw bytes)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for c, b in zip(cs, bs)}


def token_str_to_bytes(token: str, decoder: dict[str, int]) -> bytes:
    out = bytearray()
    for ch in token:
        b = decoder.get(ch)
        if b is not None:
            out.append(b)
        else:
            out += ch.encode("utf-8")
    return bytes(out)


# ------------------------------------------------------------------ hf


def convert_hf_tokenizer(dir_path: str) -> Tokenizer:
    with open(os.path.join(dir_path, "tokenizer.json"), encoding="utf-8") as f:
        tok_json = json.load(f)
    tok_config = {}
    config_path = os.path.join(dir_path, "tokenizer_config.json")
    if os.path.exists(config_path):
        with open(config_path, encoding="utf-8") as f:
            tok_config = json.load(f)

    if tok_json.get("model", {}).get("type") != "BPE":
        raise ValueError("only BPE tokenizer.json models are supported")

    # id -> token string, from base vocab + added_tokens (specials)
    id_to_token: dict[int, str] = {v: k for k, v in tok_json["model"]["vocab"].items()}
    added_ids = set()
    for added in tok_json.get("added_tokens", []):
        id_to_token[added["id"]] = added["content"]
        added_ids.add(added["id"])
    vocab_size = max(id_to_token) + 1

    # Two families of HF BPE tokenizer.json: GPT-2 *byte-level* (Llama-3 etc.,
    # tokens are printable-codepoint aliases of raw bytes) and *metaspace*
    # sentencepiece-style (Mistral, Llama-2-HF: U+2581 word boundary + <0xXX>
    # byte-fallback pieces). Distinguish via the pre_tokenizer/decoder config.
    def _component_types(section) -> list[str]:
        if not isinstance(section, dict):
            return []
        subs = section.get("pretokenizers") or section.get("decoders") or []
        return [section.get("type", "")] + [s.get("type", "") for s in subs if isinstance(s, dict)]

    kinds = _component_types(tok_json.get("pre_tokenizer")) + _component_types(tok_json.get("decoder"))
    byte_level = "ByteLevel" in kinds
    if not byte_level and "Metaspace" not in kinds and not any(
        "▁" in t for t in id_to_token.values()
    ):
        byte_level = True  # no metaspace evidence anywhere: treat as byte-level

    decoder = byte_decoder()
    byte_fallback = re.compile(r"<0x[0-9A-Fa-f]{2}>")
    vocab: list[bytes] = []
    scores: list[float] = []
    for i in range(vocab_size):
        token = id_to_token.get(i)
        if token is None:
            raise ValueError(f"vocabulary has a hole at id {i}")
        if i in added_ids:
            raw = token.encode("utf-8")
        elif byte_level:
            raw = token_str_to_bytes(token, decoder)
        else:
            raw = sentencepiece_piece_to_bytes(token, 6 if byte_fallback.fullmatch(token) else 1)
        vocab.append(raw)
        scores.append(-float(i))

    def token_id(name_key: str) -> int | None:
        token = tok_config.get(name_key)
        if isinstance(token, dict):
            token = token.get("content")
        if token is None:
            return None
        hits = [i for i, t in id_to_token.items() if t == token]
        return hits[0] if hits else None

    bos_id = token_id("bos_token")
    eos_id = token_id("eos_token")
    extra_eos: list[int] = []
    if bos_id is None or eos_id is None:
        with open(os.path.join(dir_path, "config.json"), encoding="utf-8") as f:
            model_config = json.load(f)
        if bos_id is None:
            bos_id = model_config.get("bos_token_id")
            if isinstance(bos_id, list):  # Llama-3.1-style list values
                bos_id = bos_id[0]
        if eos_id is None:
            eos_id = model_config.get("eos_token_id")
            if isinstance(eos_id, list):
                eos_id, extra_eos = eos_id[0], eos_id[1:]
    if bos_id is None or eos_id is None:
        raise ValueError("cannot resolve bos/eos token id")

    eos_ids = [eos_id] + extra_eos
    eot = [i for i, t in id_to_token.items() if t in ("<|eot_id|>", "<|im_end|>")]
    for tid in eot:
        if tid not in eos_ids:
            eos_ids.append(tid)

    return Tokenizer(
        vocab, scores, bos_id, eos_ids,
        chat_template=tok_config.get("chat_template"),
        special_ids=sorted(added_ids | {bos_id, *eos_ids}),
    )


# ------------------------------------------------------------------ llama2 (sentencepiece)


def parse_sentencepiece_model(path: str) -> list[tuple[str, float, int]]:
    """Minimal protobuf reader for sentencepiece ModelProto: extracts the
    repeated `pieces` field (#1), each {piece: string #1, score: float #2,
    type: enum #3 (NORMAL=1, UNKNOWN=2, CONTROL=3, USER_DEFINED=4, BYTE=6)}.
    Avoids the sentencepiece dependency entirely."""
    with open(path, "rb") as f:
        data = f.read()

    def read_varint(buf: bytes, i: int) -> tuple[int, int]:
        result = shift = 0
        while True:
            b = buf[i]
            i += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result, i
        raise ValueError("truncated varint")

    def skip_field(buf: bytes, i: int, wire: int) -> int:
        if wire == 0:
            _, i = read_varint(buf, i)
        elif wire == 1:
            i += 8
        elif wire == 2:
            n, i = read_varint(buf, i)
            i += n
        elif wire == 5:
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        return i

    pieces: list[tuple[str, float, int]] = []
    i = 0
    while i < len(data):
        tag, i = read_varint(data, i)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:  # repeated SentencePiece
            n, i = read_varint(data, i)
            sub, j = data[i : i + n], 0
            piece, score, ptype = "", 0.0, 1  # type defaults to NORMAL
            while j < len(sub):
                tag2, j = read_varint(sub, j)
                f2, w2 = tag2 >> 3, tag2 & 7
                if f2 == 1 and w2 == 2:
                    ln, j = read_varint(sub, j)
                    piece = sub[j : j + ln].decode("utf-8")
                    j += ln
                elif f2 == 2 and w2 == 5:
                    score = struct.unpack("<f", sub[j : j + 4])[0]
                    j += 4
                elif f2 == 3 and w2 == 0:
                    ptype, j = read_varint(sub, j)
                else:
                    j = skip_field(sub, j, w2)
            pieces.append((piece, score, ptype))
            i += n
        else:
            i = skip_field(data, i, wire)
    if not pieces:
        raise ValueError(f"no sentencepiece pieces found in {path}")
    return pieces


LLAMA2_CHAT_TEMPLATE = (
    "{% if messages[0]['role'] == 'system' %}{% set loop_messages = messages[1:] %}"
    "{% set system_message = messages[0]['content'] %}{% else %}"
    "{% set loop_messages = messages %}{% set system_message = false %}{% endif %}"
    "{% for message in loop_messages %}"
    "{% if loop.index0 == 0 and system_message != false %}"
    "{% set content = '<<SYS>>\\n' + system_message + '\\n<</SYS>>\\n\\n' + message['content'] %}"
    "{% else %}{% set content = message['content'] %}{% endif %}"
    "{% if message['role'] == 'user' %}{{ bos_token + '[INST] ' + content.strip() + ' [/INST]' }}"
    "{% elif message['role'] == 'assistant' %}{{ ' ' + content.strip() + ' ' + eos_token }}"
    "{% endif %}{% endfor %}"
)


def sentencepiece_piece_to_bytes(piece: str, ptype: int) -> bytes:
    """Piece string -> raw bytes: BYTE-type '<0xXX>' fallback pieces become the
    literal byte (so byte-level seeding in Tokenizer.encode covers all input);
    metaspace U+2581 becomes an ordinary space; everything else is UTF-8."""
    if ptype == 6 and re.fullmatch(r"<0x[0-9A-Fa-f]{2}>", piece):
        return bytes([int(piece[3:5], 16)])
    return piece.replace("\u2581", " ").encode("utf-8")


def convert_llama2_tokenizer(dir_path: str) -> Tokenizer:
    pieces = parse_sentencepiece_model(os.path.join(dir_path, "tokenizer.model"))
    vocab = [sentencepiece_piece_to_bytes(p, t) for p, _, t in pieces]
    scores = [s for _, s, _ in pieces]
    # specials: CONTROL (<s>, </s>), UNKNOWN (<unk>), USER_DEFINED pieces \u2014
    # everything else (incl. BYTE fallbacks) stays in the merge vocabulary
    special_ids = [i for i, (_, _, t) in enumerate(pieces) if t in (2, 3, 4)]
    bos_id, eos_id = 1, 2  # sentencepiece llama2 convention (<s>, </s>)
    return Tokenizer(vocab, scores, bos_id, [eos_id],
                     chat_template=LLAMA2_CHAT_TEMPLATE, special_ids=special_ids)


# ------------------------------------------------------------------ llama3 (tiktoken)

N_LLAMA3_SPECIALS = 256
LLAMA3_NAMED_SPECIALS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|reserved_special_token_2|>",
    "<|reserved_special_token_3|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|reserved_special_token_4|>",
    "<|eot_id|>",
]
LLAMA3_CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    " + message['content'] | trim + '<|eot_id|>' %}"
    "{% if loop.index0 == 0 %}{% set content = bos_token + content %}{% endif %}"
    "{{ content }}{% endfor %}{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}"
)


def convert_llama3_tokenizer(model_path: str) -> Tokenizer:
    vocab: list[bytes] = []
    scores: list[float] = []
    with open(model_path, encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            b64, rank = line.split(" ")
            vocab.append(base64.b64decode(b64))
            scores.append(-float(rank))
    n_base = len(vocab)
    specials = LLAMA3_NAMED_SPECIALS + [
        f"<|reserved_special_token_{i}|>" for i in range(5, N_LLAMA3_SPECIALS - 5)
    ]
    for i, token in enumerate(specials):
        vocab.append(token.encode("utf-8"))
        scores.append(-float(n_base + i))
    bos_id, eos_id, chat_eos_id = n_base, n_base + 1, n_base + 9
    return Tokenizer(vocab, scores, bos_id, [eos_id, chat_eos_id],
                     chat_template=LLAMA3_CHAT_TEMPLATE,
                     special_ids=list(range(n_base, len(vocab))))


# ------------------------------------------------------------------ cli


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="Convert tokenizers to the .t format")
    sub = p.add_subparsers(dest="kind", required=True)
    for kind, path_help in (
        ("hf", "dir with tokenizer.json [+ tokenizer_config.json, config.json]"),
        ("llama2", "dir with sentencepiece tokenizer.model"),
        ("llama3", "path to the tiktoken-style tokenizer.model"),
    ):
        sp = sub.add_parser(kind)
        sp.add_argument("path", help=path_help)
        sp.add_argument("--name", default=None, help="output name (dllama_tokenizer_<name>.t)")
        sp.add_argument("--output", default=None, help="explicit output path")
    args = p.parse_args(argv)

    if args.kind == "hf":
        tok = convert_hf_tokenizer(args.path)
    elif args.kind == "llama2":
        tok = convert_llama2_tokenizer(args.path)
    else:
        tok = convert_llama3_tokenizer(args.path)

    name = args.name or args.kind
    output = args.output or f"dllama_tokenizer_{name}.t"
    tok.save(output)
    print(f"📄 BosId: {tok.bos_id} EosIds: {tok.eos_ids}")
    print(f"📄 VocabSize: {len(tok.vocab)} (regular {tok.regular_vocab_size})")
    print(f"✅ Created {output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
