"""Convert a Meta Llama checkpoint (``consolidated.*.pth``) to `.m`.

Analog of the reference converter (converter/convert-llama.py). Meta shards
are megatron-style slices of each tensor: wq/wk/wv/w1/w3/output concatenate on
the output dim (0), wo/w2 and tok_embeddings on the input dim (1), 1-D norm
weights are replicated. Meta's Q/K layout is already the interleaved-pair rope
layout the `.m` format uses, so no permutation is needed (unlike HF).

Usage:
    python -m dllama_tpu.tools.convert_llama <model_dir> <weight_type> [--output out.m]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

import numpy as np

from dllama_tpu.models.config import ArchType, HiddenAct, LlamaConfig, RopeType
from dllama_tpu.ops.quant import parse_float_type
from dllama_tpu.tools.converter_core import default_output_name, write_model

# `.m` plan short name -> (Meta name template, shard concat axis or None)
META_NAME_MAP = {
    "embedding": ("tok_embeddings.weight", 1),
    "wq": ("layers.{l}.attention.wq.weight", 0),
    "wk": ("layers.{l}.attention.wk.weight", 0),
    "wv": ("layers.{l}.attention.wv.weight", 0),
    "wo": ("layers.{l}.attention.wo.weight", 1),
    "w1": ("layers.{l}.feed_forward.w1.weight", 0),
    "w2": ("layers.{l}.feed_forward.w2.weight", 1),
    "w3": ("layers.{l}.feed_forward.w3.weight", 0),
    "rms_att": ("layers.{l}.attention_norm.weight", None),
    "rms_ffn": ("layers.{l}.ffn_norm.weight", None),
    "final_norm": ("norm.weight", None),
    "wcls": ("output.weight", 0),
}


def derive_hidden_dim(params: dict, w1_shard_rows: int, n_shards: int) -> int:
    """Meta params.json has no hidden_dim; it's implied by the checkpoint."""
    return w1_shard_rows * n_shards


def meta_params_to_config(params: dict, hidden_dim: int, weight_type) -> LlamaConfig:
    if params.get("vocab_size", -1) < 1:
        raise ValueError("vocab_size is invalid, please update params.json")
    if params.get("max_seq_len") is None:
        raise ValueError("max_seq_len is required, please update params.json")
    kwargs = dict(
        arch=ArchType.LLAMA,
        hidden_act=HiddenAct.SILU,
        dim=params["dim"],
        hidden_dim=hidden_dim,
        n_layers=params["n_layers"],
        n_heads=params["n_heads"],
        n_kv_heads=params.get("n_kv_heads") or params["n_heads"],
        weight_type=weight_type,
        seq_len=params["max_seq_len"],
        vocab_size=params["vocab_size"],
        norm_epsilon=float(params.get("norm_eps", 1e-5)),
    )
    if params.get("rope_theta") is not None:
        kwargs["rope_theta"] = float(params["rope_theta"])
    scaling = params.get("rope_scaling") or (params.get("use_scaled_rope") and {})
    if isinstance(scaling, dict) and (scaling or params.get("use_scaled_rope")):
        kwargs.update(
            rope_type=RopeType.LLAMA3_1,
            rope_scaling_factor=float(scaling.get("factor", 8.0)),
            rope_scaling_low_freq_factor=float(scaling.get("low_freq_factor", 1.0)),
            rope_scaling_high_freq_factor=float(scaling.get("high_freq_factor", 4.0)),
            rope_scaling_orig_max_seq_len=int(
                scaling.get("original_max_position_embeddings", 8192)
            ),
        )
    return LlamaConfig(**kwargs)


class MetaCheckpoint:
    """Lazy accessor over consolidated.*.pth shards (mmap'd, no full load)."""

    def __init__(self, model_dir: str):
        import torch

        self._torch = torch
        self.shards = []
        for p in sorted(Path(model_dir).glob("consolidated.*.pth")):
            self.shards.append(torch.load(p, map_location="cpu", mmap=True, weights_only=True))
        if not self.shards:
            raise FileNotFoundError(f"no consolidated.*.pth in {model_dir}")

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    def w1_shard_rows(self) -> int:
        return self.shards[0]["layers.0.feed_forward.w1.weight"].shape[0]

    def get(self, short: str, layer: int | None = None) -> np.ndarray:
        name_tmpl, axis = META_NAME_MAP[short]
        name = name_tmpl.format(l=layer)
        parts = [s[name] for s in self.shards]
        if len(parts) == 1 or parts[0].dim() == 1:
            t = parts[0]
        else:
            t = self._torch.cat(parts, dim=axis)
        return t.to(dtype=self._torch.float32).numpy()


def convert_llama(model_dir: str, weight_type_name: str, output: str | None = None) -> str:
    weight_type = parse_float_type(weight_type_name)
    with open(os.path.join(model_dir, "params.json")) as f:
        params = json.load(f)
    ckpt = MetaCheckpoint(model_dir)
    hidden_dim = derive_hidden_dim(params, ckpt.w1_shard_rows(), ckpt.n_shards)
    cfg = meta_params_to_config(params, hidden_dim, weight_type)
    if output is None:
        output = default_output_name(model_dir, weight_type_name)

    def get_tensor(name: str) -> np.ndarray:
        parts = name.split(".")
        layer = int(parts[1]) if len(parts) == 3 else None
        short = parts[-1] if len(parts) == 3 else name
        return ckpt.get(short, layer)

    return write_model(cfg, output, get_tensor)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("model_dir", help="Meta checkpoint dir (params.json + consolidated.*.pth)")
    p.add_argument("weight_type", choices=["q40", "q80", "f16", "f32"])
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)
    convert_llama(args.model_dir, args.weight_type, args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
