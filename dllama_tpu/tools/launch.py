"""Model zoo downloader/launcher — the reference's launch.py role.

Registry of prequantized `.m`/`.t` artifacts (the Distributed Llama model zoo
on HuggingFace, launch.py:15-46 — multi-part files use aa/ab/... suffixes),
resumable downloads, and a ready-to-run command for this framework's CLI.

Usage:
  python -m dllama_tpu.tools.launch list
  python -m dllama_tpu.tools.launch download llama3_2_1b_instruct_q40 [--dir models/]
  python -m dllama_tpu.tools.launch run llama3_2_1b_instruct_q40      # print cmd

Zero-egress environments: `download` fails fast with a clear message; every
other subcommand works offline.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys


def _parts(n: int) -> list[str]:
    """aa, ab, ac, ... multi-part suffixes (split -d style used by the zoo)."""
    return [chr(97 + i // 26) + chr(97 + i % 26) for i in range(n)]


_HF = "https://huggingface.co/b4rtaz"


@dataclasses.dataclass(frozen=True)
class ZooModel:
    name: str
    model_urls: tuple[str, ...]
    tokenizer_url: str
    size_gb: float
    extra_flags: tuple[str, ...] = ("--max-seq-len", "4096")

    @property
    def model_file(self) -> str:
        return f"dllama_model_{self.name}.m"

    @property
    def tokenizer_file(self) -> str:
        return f"dllama_tokenizer_{self.name}.t"


def _m(repo: str, model: str, tok: str, size_gb: float, name: str, n_parts: int = 1) -> ZooModel:
    base = f"{_HF}/{repo}/resolve/main"
    if n_parts == 1:
        urls = (f"{base}/{model}?download=true",)
    else:
        urls = tuple(f"{base}/{model}{s}?download=true" for s in _parts(n_parts))
    return ZooModel(name, urls, f"{base}/{tok}?download=true", size_gb)


MODELS: dict[str, ZooModel] = {
    m.name: m
    for m in [
        _m("Llama-3_2-1B-Q40-Instruct-Distributed-Llama",
           "dllama_model_llama3.2-1b-instruct_q40.m", "dllama_tokenizer_llama3_2.t",
           1.7, "llama3_2_1b_instruct_q40"),
        _m("Llama-3_2-3B-Q40-Instruct-Distributed-Llama",
           "dllama_model_llama3.2-3b-instruct_q40.m", "dllama_tokenizer_llama3_2.t",
           3.4, "llama3_2_3b_instruct_q40"),
        _m("Llama-3_1-8B-Q40-Instruct-Distributed-Llama",
           "dllama_model_llama3.1_instruct_q40.m", "dllama_tokenizer_llama_3_1.t",
           6.3, "llama3_1_8b_instruct_q40"),
        _m("Llama-3_3-70B-Q40-Instruct-Distributed-Llama",
           "dllama_model_llama-3.3-70b_q40", "dllama_tokenizer_llama-3.3-70b.t",
           40.0, "llama3_3_70b_instruct_q40", n_parts=11),
        _m("Llama-3_1-405B-Q40-Instruct-Distributed-Llama",
           "dllama_model_llama31_405b_q40_", "dllama_tokenizer_llama_3_1.t",
           238.0, "llama3_1_405b_instruct_q40", n_parts=56),
        _m("DeepSeek-R1-Distill-Llama-8B-Distributed-Llama",
           "dllama_model_deepseek-r1-distill-llama-8b_q40.m",
           "dllama_tokenizer_deepseek-r1-distill-llama-8b.t",
           6.3, "deepseek_r1_distill_llama_8b_q40"),
    ]
}


def download_file(urls: list[str] | tuple[str, ...], path: str, progress=print) -> str:
    """Concatenate all (multi-part) urls into `path`, resuming a finished file.

    Network access goes through urllib only here — callers in zero-egress
    environments get a clean error instead of a hang."""
    if os.path.isfile(path) and os.path.getsize(path) > 0:
        progress(f"✅ {path} exists ({os.path.getsize(path) / 1e9:.2f} GB), skipping")
        return path
    from urllib.error import URLError
    from urllib.request import urlopen

    tmp = path + ".part"
    done = 0
    try:
        with open(tmp, "wb") as f:
            for i, url in enumerate(urls):
                progress(f"📥 [{i + 1}/{len(urls)}] {url.split('?')[0]}")
                with urlopen(url, timeout=60) as r:
                    while True:
                        chunk = r.read(1 << 22)
                        if not chunk:
                            break
                        f.write(chunk)
                        done += len(chunk)
    except (URLError, OSError, TimeoutError) as e:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise SystemExit(
            f"❌ download failed ({e}). No network here? Fetch the files on a "
            f"connected machine and place them at {path}"
        ) from e
    os.replace(tmp, path)
    progress(f"✅ {path} ({done / 1e9:.2f} GB)")
    return path


def run_command(model: ZooModel, directory: str, mode: str = "chat") -> list[str]:
    return [
        sys.executable, "-m", "dllama_tpu", mode,
        "--model", os.path.join(directory, model.model_file),
        "--tokenizer", os.path.join(directory, model.tokenizer_file),
        *model.extra_flags,
    ]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="dllama-tpu model zoo")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list")
    for c in ("download", "run"):
        sp = sub.add_parser(c)
        sp.add_argument("model", choices=sorted(MODELS))
        sp.add_argument("--dir", default="models")
        sp.add_argument("--mode", default="chat", choices=["chat", "inference", "serve"])
    args = p.parse_args(argv)

    if args.cmd == "list":
        for name, m in MODELS.items():
            print(f"{name:40s} {m.size_gb:7.1f} GB  {len(m.model_urls)} part(s)")
        return 0

    model = MODELS[args.model]
    if args.cmd == "download":
        os.makedirs(args.dir, exist_ok=True)
        download_file(model.model_urls, os.path.join(args.dir, model.model_file))
        download_file([model.tokenizer_url], os.path.join(args.dir, model.tokenizer_file))
        print("🚀 run it with:")
    print(" ".join(run_command(model, args.dir, getattr(args, "mode", "chat"))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
