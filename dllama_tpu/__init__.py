"""dllama-tpu: a TPU-native tensor-parallel LLM inference framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of Distributed Llama
(reference: /root/reference, KMouratidis/distributed-llama): Llama-family
inference with Q40 block-quantized weights, Q80-quantized activation exchange,
tensor/sequence/data parallelism over a `jax.sharding.Mesh`, an OpenAI-compatible
HTTP server, CLI frontends, and HF/Meta checkpoint converters.

Layer map (see SURVEY.md §7.2 for what each replaces in the reference):
  ops/        quantization primitives + compute kernels (jnp reference + Pallas TPU)
  parallel/   mesh axes (dp/tp/sp/pp/ep), shardings, quantized collectives, ring attention
  models/     Llama graph + `.m` model-file format
  engine/     compiled prefill/decode steps, KV cache, sampler, host driver
  tokenizer/  `.t` format, byte-level BPE, streaming decode, chat templates, EOS detection
  serve/      OpenAI-compatible HTTP API server
  cli/        `inference` / `chat` / `serve` frontends
  tools/      HF / Meta / tokenizer converters, model downloader
"""

__version__ = "0.1.0"
