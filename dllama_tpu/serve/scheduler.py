"""Continuous-batching scheduler: one worker thread drives a BatchEngine,
request threads stream tokens from per-request queues.

This is the serving tier above the reference's single-request blocking server
(dllama-api.cpp:522-533): requests join a running batch whenever a slot is
free (masked single-slot prefill), decode together in fused device chunks,
and leave at EOS/budget — other requests never wait for a whole completion,
only for chunk boundaries.

Token-level stops (EOS ids, budget) are handled here; *string* stop sequences
need decoded text, so the request handler runs its EosDetector on the stream
and calls cancel() — generation overruns by at most one chunk.

**Per-slot prefix cache** (the batched-tier NaiveCache, dllama-api.cpp:264-309):
released slots keep their KV rows and the token history that produced them.
Admission matches a new request's prompt against every idle slot's history and
prefills only the delta from the matched position (BatchEngine.add's
start_pos) — the second turn of a conversation re-encodes the whole chat but
only computes the new tokens. Matching is at the TOKEN level, which subsumes
the reference's whole-message matching: any retokenization drift just means
no reuse, never wrong output (rows past the matched position are rewritten).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from dllama_tpu.engine.batch import BatchEngine

log = logging.getLogger("dllama_tpu.serve")

_END = object()  # sentinel on the token queue; payload = finish reason


@dataclass
class Request:
    prompt: list[int]
    temperature: float
    topp: float
    max_tokens: int
    eos_ids: frozenset[int]
    seed: int | None = None
    presence: float = 0.0
    frequency: float = 0.0
    out: queue.Queue = field(default_factory=queue.Queue)
    produced: int = 0
    slot: int = -1
    finish_reason: str | None = None
    cancelled: threading.Event = field(default_factory=threading.Event)
    # latency marks (time.monotonic): the serving-tier observability the
    # reference's per-token console lines provide (dllama.cpp:82-87)
    submitted_at: float = 0.0
    first_token_at: float | None = None
    finished_at: float | None = None

    @property
    def ttft_ms(self) -> float | None:
        """Time to first token (includes queueing + prefill)."""
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.submitted_at) * 1000.0

    @property
    def itl_ms(self) -> float | None:
        """Mean inter-token latency after the first token."""
        if self.finished_at is None or self.first_token_at is None or self.produced < 2:
            return None
        return (self.finished_at - self.first_token_at) * 1000.0 / (self.produced - 1)

    def tokens(self):
        """Blocking iterator over generated tokens (ends on EOS/budget/cancel)."""
        while True:
            item = self.out.get()
            if item is _END or isinstance(item, Exception):
                if isinstance(item, Exception):
                    raise item
                return
            yield item


class Scheduler:
    def __init__(self, engine: BatchEngine, chunk: int = 4, admit_timeout: float = 0.05,
                 admit_interleave: bool = True,
                 admit_stall_budget_ms: float = 250.0,
                 admit_ttft_deadline_ms: float | None = None):
        self.engine = engine
        self.chunk = chunk
        self.admit_timeout = admit_timeout
        # interleaved admission (VERDICT r3 weak #5): pump prefill chunks of a
        # joining prompt BETWEEN decode chunks instead of running the whole
        # chunked prefill synchronously — a 2 Ki-token admission no longer
        # stalls every decoding slot for its full prefill. False = legacy
        # synchronous admission (the A/B baseline, experiments/abench.py).
        self.admit_interleave = admit_interleave
        # pacing (VERDICT r4 weak #3: fixed 1-chunk pacing cost joiners 5-6x
        # TTFT on slow chunks): each admission visit keeps pumping prefill
        # chunks until ~budget ms elapsed, so decoders stall at most
        # budget + one chunk while joiner TTFT approaches the synchronous
        # floor whenever chunks are fast (always, on a TPU). 0 restores
        # strict one-chunk-per-decode pacing.
        self.admit_stall_budget_ms = float(admit_stall_budget_ms)
        # optional hard TTFT bound: an admission older than this pumps to
        # completion regardless of the stall budget (decoders eat one big
        # stall rather than the joiner waiting forever behind a slow batch)
        self.admit_ttft_deadline_ms = admit_ttft_deadline_ms
        self.pending: queue.Queue[Request] = queue.Queue()
        self.slots: dict[int, Request] = {}
        # admissions being pumped chunk-by-chunk: [(req, Admission), ...];
        # their slots are reserved (not engine.active) until commit
        self._inflight: list = []
        # per-slot token history whose KV rows are live (prefix-cache key);
        # len(slot_tokens[s]) always == engine.pos[s] for idle slots
        self.slot_tokens: dict[int, list[int]] = {}
        self.reused_prefix_tokens = 0  # total prompt tokens served from cache
        # decode-gap observability (VERDICT r3 #4): wall-time between
        # consecutive decode chunks whenever admission work ran in between —
        # the stall decoding slots actually experienced
        self._admit_gaps_ms: list[float] = []
        # mixed-batch speculation: when some active slot is spec-ineligible
        # (near seq_len or penalized), spec cycles freeze it — alternate spec
        # with plain decode chunks so it still advances (toggle state)
        self._spec_tick = False
        self._completed: list[Request] = []  # ring of recent requests (metrics)
        self._metrics_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="dllama-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------- api

    def submit(self, prompt, temperature, topp, max_tokens, eos_ids,
               seed: int | None = None, presence: float = 0.0,
               frequency: float = 0.0) -> Request:
        req = Request(list(prompt), float(temperature), float(topp), int(max_tokens),
                      frozenset(eos_ids), seed=seed, presence=float(presence),
                      frequency=float(frequency), submitted_at=time.monotonic())
        self.pending.put(req)
        self._wake.set()
        return req

    def latency_summary(self) -> dict:
        """Aggregate TTFT / inter-token latency over completed requests, plus
        the admission-stall record: the max/mean decode-to-decode gap that
        admission work (prefill chunks, commits) inserted between fused decode
        chunks — what batch-mates' ITL actually degrades by during a join."""
        with self._metrics_lock:
            done = list(self._completed)
            gaps = list(self._admit_gaps_ms)
        ttfts = [r.ttft_ms for r in done if r.ttft_ms is not None]
        itls = [r.itl_ms for r in done if r.itl_ms is not None]
        mean = lambda xs: sum(xs) / len(xs) if xs else None
        return {
            "completed": len(done),
            "ttft_ms_mean": mean(ttfts),
            "itl_ms_mean": mean(itls),
            "reused_prefix_tokens": self.reused_prefix_tokens,
            "admission_gaps": len(gaps),
            "admission_stall_ms_max": max(gaps) if gaps else None,
            "admission_stall_ms_mean": mean(gaps),
        }

    def reset_latency_stats(self) -> None:
        """Drop accumulated latency/stall samples (benches call this after
        their compile-warmup phase so first-compile gaps don't pollute the
        measured record). Also rewinds the loop's decode-gap anchor so the
        first post-reset gap cannot span back to a pre-reset decode chunk."""
        with self._metrics_lock:
            self._completed.clear()
            self._admit_gaps_ms.clear()
        self._t_dec_end = None

    def cancel(self, req: Request) -> None:
        req.cancelled.set()
        self._wake.set()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)

    # ------------------------------------------------------------------ loop

    def _finish(self, req: Request, reason: str, keep_rows: int | None = None) -> None:
        if req.slot >= 0:
            self.engine.release(req.slot, keep_rows)
            if keep_rows is not None:
                # only the first keep_rows tokens have live KV rows (the last
                # emitted token was sampled but never fed back)
                self.slot_tokens[req.slot] = self.slot_tokens.get(req.slot, [])[:keep_rows]
            else:
                self.slot_tokens[req.slot] = []  # unknown state: never reuse
            self.slots.pop(req.slot, None)
            req.slot = -1
        req.finish_reason = req.finish_reason or reason
        req.finished_at = time.monotonic()
        with self._metrics_lock:
            self._completed.append(req)
            del self._completed[:-256]  # bound the ring
        req.out.put(_END)

    def _emit(self, req: Request, token: int, row_at_emit: int) -> bool:
        """Queue one token; returns True when the request just finished."""
        if req.first_token_at is None:
            req.first_token_at = time.monotonic()
        req.out.put(int(token))
        req.produced += 1
        if req.slot >= 0:
            self.slot_tokens.setdefault(req.slot, []).append(int(token))
        if token in req.eos_ids:
            self._finish(req, "stop", keep_rows=row_at_emit)
            return True
        if req.produced >= req.max_tokens:
            self._finish(req, "length", keep_rows=row_at_emit)
            return True
        return False

    def _pick_slot(self, prompt: list[int]) -> tuple[int | None, int, int | None]:
        """(slot, reusable_prefix_len, donor): the idle slot whose cached
        token history shares the longest full prefix with `prompt`. When a
        DIFFERENT slot (idle or actively decoding) holds a longer matching
        prefix, the cheapest idle slot is chosen and `donor` names the slot
        whose KV rows should be copied in first (cross-slot prefix share —
        e.g. a common system prompt cached once serves every slot). Slots
        reserved by in-flight admissions are neither destinations nor donors
        (their rows are mid-overwrite)."""
        reserved = {adm.slot for _, adm, _ in self._inflight}
        idle = [
            s for s in range(self.engine.n_slots)
            if not self.engine.active[s] and s not in reserved
        ]
        if not idle:
            return None, 0, None

        def shared(s: int) -> int:
            cached = self.slot_tokens.get(s, [])
            # reusable rows = LONGEST COMMON PREFIX (not all-or-nothing: a
            # shared system prompt with a divergent tail still reuses the
            # common part), capped so at least one prompt token remains to
            # prefill (stale rows past it are masked); an ACTIVE donor's
            # last emitted token has no KV row yet
            n = min(len(cached), len(prompt) - 1)
            if self.engine.active[s]:
                n = min(n, len(cached) - 1)
            if n <= 0:
                return 0
            neq = np.nonzero(np.asarray(prompt[:n]) != np.asarray(cached[:n]))[0]
            return int(neq[0]) if neq.size else n

        # cross-slot donors need the engine's slot-copy primitive (dp meshes
        # shard the batch axis, where donor search stays within idle slots)
        cross_ok = getattr(self.engine, "supports_cross_slot_copy", False)
        donors = [s for s in range(self.engine.n_slots) if s not in reserved] if cross_ok else idle
        lcp = {s: shared(s) for s in donors}
        best_idle = max(idle, key=lcp.__getitem__)
        best_any = max(donors, key=lcp.__getitem__)
        if lcp[best_any] > lcp[best_idle]:
            dst = min(idle, key=lambda s: len(self.slot_tokens.get(s, [])))
            return dst, lcp[best_any], best_any
        if lcp[best_idle] > 0:
            return best_idle, lcp[best_idle], None
        return min(idle, key=lambda s: len(self.slot_tokens.get(s, []))), 0, None

    def _admit_starts(self) -> None:
        """Pop pending requests into in-flight admissions while slots allow."""
        reserved = len(self._inflight)
        while not self.pending.empty():
            if int((~self.engine.active).sum()) - reserved <= 0:
                return
            try:
                req = self.pending.get_nowait()
            except queue.Empty:
                return
            if req.cancelled.is_set():
                req.finish_reason = "cancelled"
                req.out.put(_END)
                continue
            if len(req.prompt) >= self.engine.seq_len:
                # reject BEFORE slot search or any donor copy: a hopeless
                # admission must not evict a slot's cached prefix (nor pay
                # the per-slot LCP scan)
                req.out.put(ValueError(
                    f"prompt ({len(req.prompt)}) exceeds seq_len {self.engine.seq_len}"
                ))
                continue
            slot, reuse, donor = self._pick_slot(req.prompt)
            try:
                if donor is not None and donor != slot and reuse > 0:
                    # cross-slot share: materialize the donor's prefix rows
                    # in the destination before the delta prefill
                    self.engine.copy_prefix_rows(donor, slot, reuse)
                    self.slot_tokens[slot] = list(
                        self.slot_tokens.get(donor, [])[:reuse]
                    )
                adm = self.engine.add_begin(slot, req.prompt[reuse:], start_pos=reuse)
            except Exception as e:  # bad request (too long, …) — fail just this one
                log.exception("admission rejected")
                req.out.put(e)
                continue
            req.slot = slot
            self._inflight.append((req, adm, reuse))
            reserved += 1

    def _abort_admission(self, req, adm, reason) -> None:
        # rows past start_pos may be partially overwritten: the old history
        # no longer describes the slot's KV contents — and _finish must not
        # preserve them (keep_rows=None) nor miss the metrics ring
        self.slot_tokens[adm.slot] = []
        if isinstance(reason, Exception):
            req.out.put(reason)
            reason = "error"
        self._finish(req, reason)

    def _pump_admissions(self) -> bool:
        """Advance in-flight admissions: when interleaving, pump prefill
        chunks of the head admission until the stall budget is spent (decode
        chunks run between calls); when not, the whole queue. An admission
        past the TTFT deadline ignores the budget and pumps to completion.
        Returns True if any admission work ran."""
        worked = False
        t0 = time.monotonic()
        while self._inflight:
            req, adm, reuse = self._inflight[0]
            if req.cancelled.is_set():
                self._inflight.pop(0)
                self._abort_admission(req, adm, "cancelled")
                continue
            try:
                done = self.engine.add_step(adm)
                if self.slots and adm.logits is not None:
                    # sync whenever decoders could stall: JAX dispatch is
                    # async, so without this the pacing clock AND the
                    # admission-gap metric would see host dispatch time only
                    # (near zero on TPU) while the chunk's device time
                    # silently serialized into the next decode chunk —
                    # under-pacing the budget and mis-attributing the stall.
                    # Applied in every admission mode so the sync/strict/
                    # paced A/B compares like with like; the chunk must
                    # finish before the next decode chunk anyway (same
                    # device stream). With no decoders there is no stall to
                    # attribute and dispatch stays pipelined.
                    jax.block_until_ready(adm.logits)
                worked = True
                if done:
                    first = self.engine.add_commit(adm, req.temperature, req.topp,
                                                   seed=req.seed,
                                                   presence=req.presence,
                                                   frequency=req.frequency)
                    self._inflight.pop(0)
                    self.reused_prefix_tokens += reuse  # rows actually served
                    self.slot_tokens[adm.slot] = list(req.prompt)
                    self.slots[adm.slot] = req
                    self._emit(req, first, int(self.engine.pos[adm.slot]))
            except Exception as e:
                log.exception("prefill failed")
                self._inflight.pop(0)
                self._abort_admission(req, adm, e)
                continue
            if not (self.admit_interleave and self.slots):
                continue  # no decoders to protect: drain the queue
            # evaluated AFTER the chunk ran (and its device sync), so an
            # admission that crosses the deadline during the chunk is
            # honored this visit, not one decode chunk late
            overdue = (
                self.admit_ttft_deadline_ms is not None
                and (time.monotonic() - req.submitted_at) * 1000.0
                >= self.admit_ttft_deadline_ms
            )
            if done and overdue:
                # an overdue admission just committed under the deadline
                # override: yield a decode chunk before touching the next
                # head, so a burst of overdue joiners costs one prefill per
                # visit — never the sum of all of them — regardless of how
                # much budget the override left unspent
                return worked
            if (time.monotonic() - t0) * 1000.0 < self.admit_stall_budget_ms:
                continue  # cheap so far: keep pumping
            if not done and overdue:
                # TTFT deadline: finish THIS admission despite the budget
                continue
            # stall budget spent: let a decode chunk run now
            return worked
        return worked

    def _run(self) -> None:
        # end of the previous decode chunk (stall metric); instance attribute
        # so reset_latency_stats can rewind it from the caller's thread
        self._t_dec_end = None
        while not self._stop.is_set():
            self._admit_starts()
            admitted = self._pump_admissions()
            for slot, req in list(self.slots.items()):
                if req.cancelled.is_set():
                    self._finish(req, "cancelled", keep_rows=int(self.engine.pos[slot]))
                elif int(self.engine.pos[slot]) >= self.engine.seq_len:
                    self._finish(req, "length")
            if not self.slots:
                self._t_dec_end = None
                if not self._inflight:
                    self._wake.wait(timeout=self.admit_timeout)
                    self._wake.clear()
                continue
            if admitted and self._t_dec_end is not None:
                # decode-to-decode gap attributable to admission work
                gap_ms = (time.monotonic() - self._t_dec_end) * 1000.0
                with self._metrics_lock:
                    self._admit_gaps_ms.append(gap_ms)
                    del self._admit_gaps_ms[:-256]
            start_rows = {s: int(self.engine.pos[s]) for s in self.slots}
            # speculative cycle when some slot can profit: greedy (sampled
            # slots never accept drafts), K+1 rows of cache room, and no
            # repetition penalties (spec acceptance compares raw argmax;
            # penalized sampling rides the counts-carrying decode path).
            # Ineligible slots are frozen by spec_step, not poisoned — a
            # mixed batch alternates spec cycles with plain decode chunks so
            # frozen slots still advance to their finish (no livelock) while
            # eligible ones keep multi-token acceptance on their cycles.
            use_spec = False
            if getattr(self.engine, "spec_k", 0):
                elig = self.engine.spec_eligible()  # the engine's freeze rule
                use_spec = any(
                    elig[s] and float(self.engine.temperature[s]) == 0.0
                    for s in self.slots
                )
                if use_spec and not all(elig[s] for s in self.slots):
                    self._spec_tick = not self._spec_tick
                    use_spec = self._spec_tick
            try:
                if use_spec:
                    emit_toks, adv = self.engine.spec_step()
                else:
                    toks = self.engine.decode(self.chunk)
            except Exception as e:
                log.exception("decode failed; failing all in-flight requests")
                for req in list(self.slots.values()):
                    req.out.put(e)
                    self._finish(req, "error")
                continue
            self._t_dec_end = time.monotonic()
            for slot, req in list(self.slots.items()):
                n = int(adv[slot]) if use_spec else toks.shape[0]
                for i in range(n):
                    # row written when sampling token i: start + i (+1 = prefix len)
                    tok = emit_toks[slot, i] if use_spec else toks[i, slot]
                    if self._emit(req, tok, start_rows[slot] + i + 1):
                        break
        for req, adm, _ in self._inflight:
            self._abort_admission(req, adm, "shutdown")
        for req in list(self.slots.values()):
            self._finish(req, "shutdown")
