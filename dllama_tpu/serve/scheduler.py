"""Continuous-batching scheduler: one worker thread drives a BatchEngine,
request threads stream tokens from per-request queues.

This is the serving tier above the reference's single-request blocking server
(dllama-api.cpp:522-533): requests join a running batch whenever a slot is
free (masked single-slot prefill), decode together in fused device chunks,
and leave at EOS/budget — other requests never wait for a whole completion,
only for chunk boundaries.

Token-level stops (EOS ids, budget) are handled here; *string* stop sequences
need decoded text, so the request handler runs its EosDetector on the stream
and calls cancel() — generation overruns by at most one chunk. With the
overlapped pipeline (the default: chunk N+1 dispatches off chunk N's
device-side carry before chunk N's tokens are consumed), token-level stops
inherit the same one-chunk overrun contract: the in-flight chunk keeps
decoding a just-finished slot, its tokens are discarded at consumption, and
release(keep_rows=) rewinds the slot to the truly-emitted prefix.

**Self-healing** (ISSUE 6): with ``restart_max > 0`` a worker crash
warm-restarts the engine in-process — decode state and the KV page pool are
rebuilt against the still-resident weights (no model reload), queued
requests survive untouched, and in-flight streams resume bit-exact by
re-prefilling prompt + emitted tokens with their recorded PRNG key
(`_try_restart`). Budget-bounded (``restart_max`` within
``restart_window_s``, capped exponential backoff); budget exhausted falls
back to the PR 1 permanent-unhealthy contract. Per-request deadlines
(``timeout_s``) shed expired queued requests before prefill and finish
running ones with ``finish_reason="timeout"`` at a chunk boundary; the
decode NaN guard fails a request whose logits go non-finite without
touching its batch-mates.

**Prefix reuse** comes in two flavors, selected by the engine:

* **Radix prefix cache** (ISSUE 9, the paged default — engine/radix): a
  GLOBAL radix tree over the KV page pool replaces the resident-slot scan as
  the reuse mechanism. Admission walks the tree and maps the longest shared
  prefix by page refcount (zero copies; a partial boundary page is
  copy-on-written by the existing admission COW), commit/release insert the
  request's own prefix back, and released slots hand every page to the tree —
  so reuse survives slot churn and works across requests that never shared a
  slot. Capacity pressure reclaims LRU tree leaves before a request defers.
* **Per-slot prefix cache** (the batched-tier NaiveCache,
  dllama-api.cpp:264-309 — dense layouts / --radix-cache off): released slots
  keep their KV rows and the token history that produced them. Admission
  matches a new request's prompt against every idle slot's history and
  prefills only the delta from the matched position (BatchEngine.add's
  start_pos).

Either way, matching is at the TOKEN level, which subsumes the reference's
whole-message matching: any retokenization drift just means no reuse, never
wrong output (rows past the matched position are rewritten).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from dllama_tpu.engine.batch import BatchEngine
from dllama_tpu.obs import compile as compile_obs
from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import perf
from dllama_tpu.obs import trace
from dllama_tpu.utils import faults
from dllama_tpu.utils import locks

log = logging.getLogger("dllama_tpu.serve")

_END = object()  # sentinel on the token queue; payload = finish reason


class SchedulerRejected(RuntimeError):
    """Base of the admission-control rejections: the request never entered
    the queue and running generations are unperturbed. `retry_after_s` is the
    client hint the API tier forwards as a Retry-After header."""

    retry_after_s: float = 1.0


class QueueFull(SchedulerRejected):
    """Shed under load: pending depth reached --max-queue (HTTP 429)."""


class SchedulerDraining(SchedulerRejected):
    """Admission stopped for a graceful shutdown (HTTP 503)."""

    retry_after_s = 5.0


class SchedulerUnhealthy(SchedulerRejected):
    """The worker thread crashed or is gone; nothing can serve this
    request (HTTP 503 — readiness is down too, so balancers drain us)."""

    retry_after_s = 10.0


@dataclass
class Request:
    prompt: list[int]
    temperature: float
    topp: float
    max_tokens: int
    eos_ids: frozenset[int]
    seed: int | None = None
    presence: float = 0.0
    frequency: float = 0.0
    # per-request speculation (ISSUE 11): draft length this request's slot
    # runs at (body `spec_k` / --spec-k serving default, clamped to the
    # engine's compile-time K at submit; 0 = plain decode for this request
    # even while batch-mates speculate). spec_cycles/spec_tokens accumulate
    # the request's own acceptance record — `timings()` derives its
    # realized per-request speedup (tokens per verify forward) from them.
    spec_k: int = 0
    spec_cycles: int = 0
    spec_tokens: int = 0
    out: queue.Queue = field(default_factory=queue.Queue)
    produced: int = 0
    slot: int = -1
    finish_reason: str | None = None
    # serving-tier request id (api -> scheduler -> engine): the correlation
    # key between X-Request-Id response headers, log lines, and admissions
    req_id: str = ""
    # what finish_reason a cancel() should record: the API tier releases a
    # slot via cancel() BOTH for real client cancellations and for streams
    # that ended on a string stop-sequence — the latter is a SUCCESS and must
    # not pollute the finished{reason="cancelled"} counter
    cancel_reason: str = "cancelled"
    cancelled: threading.Event = field(default_factory=threading.Event)
    # per-request deadline (body `timeout_s` / X-Request-Timeout header):
    # expired-in-queue requests are shed before prefill, running ones finish
    # with finish_reason="timeout" at the next chunk boundary. deadline_at
    # is the absolute monotonic deadline (submit time + timeout_s).
    timeout_s: float | None = None
    deadline_at: float | None = None
    # scheduling class & tenant (ISSUE 12): `priority` (0=low, 1=normal,
    # 2=high — body `priority` field) picks strictly between classes at
    # admission AND marks a running low-priority request preemptible by a
    # higher-priority waiter; `tenant` (body field) keys the weighted
    # fair queue WITHIN a class ("" = the anonymous shared tenant).
    priority: int = 1
    tenant: str = ""
    # preempt-to-pages (ISSUE 12): True between a chunk-boundary suspension
    # and the re-commit that resumes the stream — the request sits in the
    # backlog with resume_tokens/resume_key recorded (the same machinery
    # warm-restart resume uses) while its KV pages stay referenced by the
    # radix tree (paged) or its kept slot rows (dense)
    preempted: bool = False
    # WFQ billing latch: a request's (prompt + max_tokens)/weight cost is
    # charged to its tenant's virtual time ONCE — resumes/rejoins after
    # preemption or deferral must not pay again
    wfq_charged: bool = False
    # warm-restart recovery (set by Scheduler._try_restart, consumed at
    # re-admission): resume_tokens are the tokens already emitted to the
    # client — all but the last are re-prefilled (teacher-forced), the last
    # becomes the decode carry's fed token; resume_key is the request's
    # PRNG key advanced to the interruption point, so a resumed sampled
    # stream is bit-exact. `recovered` marks the request for the
    # requests_recovered counter at its post-restart (re)commit.
    resume_tokens: list[int] | None = None
    resume_key: object | None = None
    recovered: bool = False
    # PRNG advances already baked into engine.keys[slot] at the last
    # (re)commit: 0 after a fresh add_commit (the row holds the commit-time
    # key), produced-1 after a resume_commit (the row holds a key
    # pre-advanced to the interruption point). A SECOND warm restart must
    # replay only the advances since — replaying the cumulative `produced`
    # would double-count the pre-first-crash tokens and silently break the
    # bit-exact-resume guarantee for sampled streams.
    key_advances: int = 0
    # latency marks (time.monotonic): the serving-tier observability the
    # reference's per-token console lines provide (dllama.cpp:82-87)
    submitted_at: float = 0.0
    admitted_at: float | None = None  # popped from the queue for admission
    first_token_at: float | None = None
    finished_at: float | None = None

    @property
    def ttft_ms(self) -> float | None:
        """Time to first token (includes queueing + prefill)."""
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.submitted_at) * 1000.0

    @property
    def itl_ms(self) -> float | None:
        """Mean inter-token latency after the first token."""
        if self.finished_at is None or self.first_token_at is None or self.produced < 2:
            return None
        return (self.finished_at - self.first_token_at) * 1000.0 / (self.produced - 1)

    def timings(self) -> dict:
        """The per-request latency summary clients get back (the `timings`
        object of non-stream responses and the final SSE event) and the
        flight recorder records — all from the same marks the /metrics
        histograms observe, so the three views cannot disagree. Fields not
        yet known (unadmitted, unfinished) are None."""
        qw = (None if self.admitted_at is None
              else round((self.admitted_at - self.submitted_at) * 1000.0, 3))
        ttft = self.ttft_ms
        e2e = (None if self.finished_at is None
               else round((self.finished_at - self.submitted_at) * 1000.0, 3))
        out = {"queue_wait_ms": qw,
               "ttft_ms": None if ttft is None else round(ttft, 3),
               "e2e_ms": e2e, "decode_tokens": self.produced}
        if self.timeout_s is not None:
            # deadline accounting rides the same summary: what was asked,
            # and whether the deadline (not EOS/budget) ended the request
            out["timeout_s"] = self.timeout_s
            out["deadline_exceeded"] = self.finish_reason == "timeout"
        if self.spec_k > 0:
            # per-request speculation record: tokens per verify forward IS
            # the realized speedup over one-token-per-forward decoding
            out["spec"] = {
                "spec_k": self.spec_k,
                "cycles": self.spec_cycles,
                "tokens": self.spec_tokens,
                "tokens_per_cycle": (round(self.spec_tokens
                                           / self.spec_cycles, 3)
                                     if self.spec_cycles else None),
            }
        return out

    def tokens(self, poll=None, poll_s: float = 0.25):
        """Blocking iterator over generated tokens (ends on EOS/budget/cancel).

        `poll` (optional zero-arg callable) runs every `poll_s` seconds of
        WAITING — i.e. also while no tokens are flowing at all (queued behind
        a full batch, mid-prefill, stalled device), which is exactly when a
        disconnect probe matters most. Whatever it raises propagates."""
        while True:
            if poll is None:
                item = self.out.get()
            else:
                try:
                    item = self.out.get(timeout=poll_s)
                except queue.Empty:
                    poll()
                    continue
            if item is _END or isinstance(item, Exception):
                if isinstance(item, Exception):
                    raise item
                return
            yield item

    def poll_tokens(self) -> tuple[list[int], bool]:
        """NON-blocking drain of the token queue — the aio front-end's SSE
        pump seam (one thread multiplexes every stream, so nothing may
        block). Returns ``(tokens, done)``: every token available right
        now, and whether the stream has ended (EOS/budget/cancel/timeout —
        ``finish_reason`` is authoritative once True). A queued exception
        (shed/shutdown/crash) raises exactly like :meth:`tokens`; tokens
        drained before it are lost to the caller the same way the blocking
        iterator loses them (the request is terminal either way)."""
        toks: list[int] = []
        while True:
            try:
                item = self.out.get_nowait()
            except queue.Empty:
                return toks, False
            if item is _END:
                return toks, True
            if isinstance(item, Exception):
                raise item
            toks.append(item)


class Scheduler:
    def __init__(self, engine: BatchEngine, chunk: int = 4, admit_timeout: float = 0.05,
                 admit_interleave: bool = True,
                 admit_stall_budget_ms: float = 250.0,
                 admit_ttft_deadline_ms: float | None = None,
                 max_queue: int = 0,
                 stall_deadline_s: float = 0.0,
                 overlap: bool = True,
                 restart_max: int = 0,
                 restart_window_s: float = 60.0,
                 restart_backoff_s: float = 0.5,
                 slo_ttft_ms: float | None = None,
                 slo_itl_ms: float | None = None,
                 prefill_budget: int | str = "auto",
                 preempt: str = "auto",
                 tenant_weights: dict[str, float] | None = None,
                 warmup: str = "off"):
        self.engine = engine
        self.chunk = chunk
        self.admit_timeout = admit_timeout
        # overlapped decode pipeline (--overlap): dispatch chunk N+1 off
        # chunk N's device-side carry BEFORE consuming chunk N's tokens, so
        # the per-chunk Python work (emit loops, EOS/budget checks, metrics)
        # runs while the device computes. Token-level stops then lag by at
        # most ONE chunk — the same overrun contract string stops already
        # have above — with overrun tokens discarded and release(keep_rows=)
        # rewound to the truly-emitted prefix. False restores the lockstep
        # loop (dispatch+consume per iteration); token streams are
        # bit-identical either way. Speculative cycles compose (ISSUE 11):
        # they dispatch/consume through the same split — cycle N+1's
        # propose/verify launches off cycle N's device carry, and the
        # data-dependent emit counts materialize at consumption.
        self.overlap = bool(overlap)
        # bounded admission (load shedding): submit() raises QueueFull once
        # the pending queue holds this many requests — the API tier turns it
        # into 429 + Retry-After. 0 = unbounded (the pre-supervision behavior).
        self.max_queue = int(max_queue)
        # interleaved admission (VERDICT r3 weak #5): pump prefill chunks of a
        # joining prompt BETWEEN decode chunks instead of running the whole
        # chunked prefill synchronously — a 2 Ki-token admission no longer
        # stalls every decoding slot for its full prefill. False = legacy
        # synchronous admission (the A/B baseline, experiments/abench.py).
        self.admit_interleave = admit_interleave
        # pacing (VERDICT r4 weak #3: fixed 1-chunk pacing cost joiners 5-6x
        # TTFT on slow chunks): each admission visit keeps pumping prefill
        # chunks until ~budget ms elapsed, so decoders stall at most
        # budget + one chunk while joiner TTFT approaches the synchronous
        # floor whenever chunks are fast (always, on a TPU). 0 restores
        # strict one-chunk-per-decode pacing.
        self.admit_stall_budget_ms = float(admit_stall_budget_ms)
        # optional hard TTFT bound: an admission older than this pumps to
        # completion regardless of the stall budget (decoders eat one big
        # stall rather than the joiner waiting forever behind a slow batch)
        self.admit_ttft_deadline_ms = admit_ttft_deadline_ms
        self.pending: queue.Queue[Request] = queue.Queue()
        # scheduling backlog (ISSUE 12): the worker drains `pending` (the
        # thread-safe intake) into this list at every boundary and picks by
        # POLICY — priority classes strictly first, weighted fair queueing
        # across tenants within a class (virtual finish times in
        # `_tenant_vt`), FIFO within a tenant — instead of the old global
        # FIFO pop. Preempted requests also park here until capacity and
        # priority let them resume.
        self._backlog: list[Request] = []
        # per-tenant WFQ virtual finish tags + the global virtual clock
        # (start-time fair queueing): each admission is charged
        # (prompt + max_tokens) / weight from max(own tag, clock), and the
        # clock advances to that start — idle time banks no credit
        self._tenant_vt: dict[str, float] = {}
        self._vt_now = 0.0
        self.tenant_weights = dict(tenant_weights or {})
        # capacity-aware admission (paged KV layout): the head request the
        # page pool cannot yet cover, parked here (NOT back in the backlog —
        # its admission was already selected by policy and later picks wait
        # behind it). Retried every boundary; released pages / evicted idle
        # caches un-defer it.
        self._deferred: Request | None = None
        self.slots: dict[int, Request] = {}
        # admissions being pumped chunk-by-chunk: [(req, Admission), ...];
        # their slots are reserved (not engine.active) until commit
        self._inflight: list = []
        # per-slot token history whose KV rows are live (prefix-cache key
        # on the legacy path; resume-token record for warm restart on both);
        # len(slot_tokens[s]) always == engine.pos[s] for idle slots
        self.slot_tokens: dict[int, list[int]] = {}
        self.reused_prefix_tokens = 0  # total prompt tokens served from cache
        # cross-request radix prefix cache (ISSUE 9, engine/radix): when the
        # engine carries one, the GLOBAL tree replaces the resident-slot LCP
        # scan as the reuse mechanism — admission walks the tree and maps the
        # shared prefix by refcount, commit/release insert prefixes back, and
        # released slots hand every page to the tree (idle slots stay empty).
        # Dense layouts (no page pool) keep the legacy per-slot scan.
        self._radix = getattr(engine, "radix", None)
        # decode-gap observability (VERDICT r3 #4): wall-time between
        # consecutive decode chunks whenever admission work ran in between —
        # the stall decoding slots actually experienced
        self._admit_gaps_ms: list[float] = []
        # inter-chunk host gap: time from one chunk's tokens materializing to
        # the next chunk's dispatch — the device-idle window host scheduling
        # inserts. ~0 under overlap (chunk N+1 dispatches before chunk N is
        # consumed); the lockstep A/B baseline shows the real gap. Mirrors
        # the dllama_decode_host_gap_seconds histogram.
        self._host_gap_ms: list[float] = []
        self._t_consumed: float | None = None
        self._last_gap_ms: float | None = None  # latest host gap (trace arg)
        # gated spec/decode alternation: per-slot eligibility (ISSUE 11)
        # lets sampled, penalized, and non-spec traffic ride spec cycles
        # one token at a time, so the only slots a cycle still freezes are
        # those WITHOUT a K+1-row verify window (context edge, exhausted
        # page pool). While one of those is live, spec cycles alternate
        # with plain decode chunks (toggle state) so it still advances.
        self._spec_tick = False
        self._completed: list[Request] = []  # ring of recent requests (metrics)
        self._metrics_lock = locks.make_lock("scheduler.metrics")
        ins.SLOTS_TOTAL.set(engine.n_slots)
        self._wake = threading.Event()
        self._stop = threading.Event()
        # ---- self-healing (warm restart): on a worker crash, tear down
        # decode state + page pool, rebuild against the still-resident
        # weights (no model reload) and re-enter the loop — at most
        # --restart-max times within --restart-window-s, with exponential
        # backoff (restart_backoff_s * 2^(attempt-1)). 0 keeps the PR 1
        # behavior: any crash is permanent-unhealthy, the external
        # supervisor owns the restart.
        self.restart_max = int(restart_max)
        self.restart_window_s = float(restart_window_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self._restarts: list[float] = []  # monotonic stamps inside the window
        self.restart_count = 0  # lifetime total (health/observability)
        # requests that survived a restart, awaiting re-admission at the
        # queue head (mid-stream resumes first, in submission order)
        self._recover: list[Request] = []
        # ---- supervision state (all read by health(), written by the worker
        # or watchdog; plain attribute stores are atomic under the GIL)
        self.crashed: BaseException | None = None  # worker died with this
        self.join_failed = False  # shutdown() could not join the worker
        self._draining = threading.Event()  # admission stopped for drain
        self.stalled = False  # watchdog verdict: a chunk blew the deadline
        self.stall_count = 0  # total watchdog trips (stalled may recover)
        # ---- SLO & saturation observability (ISSUE 7, obs/perf.py): the
        # time ledger attributes every second of the worker loop to one
        # exclusive state (dllama_scheduler_time_seconds_total{state} — the
        # per-state totals partition loop wall time by construction), and
        # the aggregator joins sliding-window TTFT/ITL/e2e quantiles, SLO
        # burn/attainment accounting (--slo-ttft-ms / --slo-itl-ms), and
        # roofline/goodput attribution of consumed decode chunks priced by
        # the engine's cost model. Both feed GET /debug/perf and /metrics.
        self.ledger = perf.TimeLedger(counter=ins.SCHEDULER_TIME)
        cost_model = (engine.chunk_cost_model()
                      if hasattr(engine, "chunk_cost_model") else None)
        self.perf = perf.PerfAggregator(
            slo=perf.SloPolicy(
                None if slo_ttft_ms is None else float(slo_ttft_ms),
                None if slo_itl_ms is None else float(slo_itl_ms)),
            cost_model=cost_model)
        # ---- hybrid chunked prefill (ISSUE 12, --prefill-budget): when a
        # request is admitting WHILE others decode, each device chunk is a
        # FUSED hybrid step (engine.hybrid_dispatch) that co-processes up
        # to `_budget_now` prompt tokens alongside the decode rows — one
        # launch, no separate prefill dispatch stalling the decoders. This
        # replaces the interleaved-admission pacing as the mechanism that
        # protects decoders during a join ("auto"/N; the admit_interleave /
        # admit_stall_budget_ms knobs now only govern the legacy
        # prefill_budget=0 phase-split path, kept as the A/B baseline).
        if prefill_budget is None:
            prefill_budget = "auto"
        if isinstance(prefill_budget, str) and prefill_budget != "auto":
            prefill_budget = int(prefill_budget)
        self.prefill_budget = prefill_budget  # "auto" | int (0 = legacy)
        self._hybrid_on = (prefill_budget != 0
                           and getattr(engine, "supports_hybrid", False))
        self._budget_ctl = None
        if not self._hybrid_on:
            self._budget_now = 0
            ins.PREFILL_BUDGET.set(0)
        elif prefill_budget == "auto":
            # SLO-driven: the windowed ITL headroom against --slo-itl-ms
            # shrinks/grows the budget online (holds the start value when
            # no ITL target is configured)
            self._budget_ctl = perf.PrefillBudgetController(
                self.perf.slo,
                hi=max(64, int(getattr(engine, "max_prefill_chunk", 256))))
            self._budget_now = self._budget_ctl.current
        else:
            self._budget_now = max(1, int(prefill_budget))
            ins.PREFILL_BUDGET.set(self._budget_now)
        # ---- preempt-to-pages (ISSUE 12, --preempt): a running request may
        # be suspended at a chunk boundary when a STRICTLY higher-priority
        # request is waiting and blocked (no free slot, or the deferred
        # head is capacity-starved). Suspension releases the slot while the
        # pages stay referenced — radix tree (paged) or kept rows (dense) —
        # and the stream resumes byte-identical via the warm-restart resume
        # machinery. "auto" = on; "off" disables.
        if preempt not in ("auto", "on", "off"):
            raise ValueError(f"preempt must be auto|on|off, got {preempt!r}")
        self._preempt_on = preempt != "off"
        self.preempt_count = 0  # lifetime totals (latency_summary/health)
        self.resume_count = 0
        # ---- compile observability (ISSUE 13, obs/compile): declare THIS
        # scheduler's expected compiled-shape universe into the engine's
        # contract (decode/spec at {1, chunk}, hybrid at every pow2 budget
        # slice) so any off-contract compile classifies unexpected; with
        # --warmup auto, precompile the whole universe BEFORE the worker
        # starts — the first real request then pays zero compile.
        if warmup not in ("auto", "off"):
            raise ValueError(f"warmup must be auto|off, got {warmup!r}")
        self.warmup = warmup
        self.warmup_report: dict | None = None
        hybrid_hi = 0
        if self._hybrid_on:
            hybrid_hi = (self._budget_ctl.hi if self._budget_ctl is not None
                         else self._budget_now)
        if hasattr(engine, "declare_serving_buckets"):
            engine.declare_serving_buckets(chunk=self.chunk,
                                           hybrid_budget_hi=hybrid_hi)
        if warmup == "auto":
            if getattr(engine, "_shardings", None) is not None:
                log.warning("--warmup auto needs an unsharded engine; "
                            "skipping the precompile pass")
            elif hasattr(engine, "warmup"):
                self.warmup_report = engine.warmup(
                    chunk=self.chunk, hybrid_budget_hi=hybrid_hi)
        # worker heartbeat: stamped once per loop iteration. A device call
        # that hangs stops the heartbeat while work exists — which is exactly
        # the condition the watchdog turns into "stalled".
        self._heartbeat = time.monotonic()
        self._thread = threading.Thread(target=self._run, name="dllama-scheduler", daemon=True)
        self._thread.start()
        # stall watchdog: marks the server unhealthy when the worker goes
        # silent mid-work for longer than the deadline (a hung device chunk,
        # a wedged collective). Detection only — there is no safe preemption
        # of a dispatched XLA computation; the operator (or the pod
        # supervisor watching /health) owns the restart.
        self.stall_deadline_s = float(stall_deadline_s)
        self._watchdog = None
        if self.stall_deadline_s > 0:
            self._watchdog = threading.Thread(
                target=self._watch, name="dllama-watchdog", daemon=True)
            self._watchdog.start()

    # ------------------------------------------------------------------- api

    def submit(self, prompt, temperature, topp, max_tokens, eos_ids,
               seed: int | None = None, presence: float = 0.0,
               frequency: float = 0.0, req_id: str = "",
               timeout_s: float | None = None,
               spec_k: int | None = None,
               priority: int = 1, tenant: str = "",
               resume_tokens=None) -> Request:
        self.check_admission()
        # per-request speculation: None keeps the engine default (every
        # greedy request speculates at the engine's K — the pre-ISSUE-11
        # behavior and the --spec-k serving default); explicit values clamp
        # to the compile-time K, 0 opts this request out entirely
        cap = int(getattr(self.engine, "spec_k", 0))
        spec_k = cap if spec_k is None else max(0, min(int(spec_k), cap))
        req = Request(list(prompt), float(temperature), float(topp), int(max_tokens),
                      frozenset(eos_ids), seed=seed, presence=float(presence),
                      frequency=float(frequency), submitted_at=time.monotonic(),
                      req_id=req_id, spec_k=spec_k,
                      priority=int(priority), tenant=str(tenant))
        if resume_tokens:
            # cross-replica failover (ISSUE 16): the router replays a dead
            # upstream's journal here. Stamp the same resume record a warm
            # restart builds (_record_resume), except the key chain starts
            # from the REQUEST seed: this replica never held the stream, so
            # the post-commit key is reconstructed as advance(PRNGKey(seed),
            # n) — commit's own split is advance #1, each emitted decode
            # token past the first is one more. Greedy streams ignore the
            # key entirely, so an unseeded greedy resume pins seed 0.
            n = len(resume_tokens)
            req.resume_tokens = [int(t) for t in resume_tokens]
            req.produced = n
            req.key_advances = n - 1
            req.resume_key = self._advance_key(
                jax.random.PRNGKey(int(seed) if seed is not None else 0), n)
            req.recovered = True
        if timeout_s is not None and timeout_s > 0:
            req.timeout_s = float(timeout_s)
            req.deadline_at = req.submitted_at + req.timeout_s
        # flight-recorder record BEFORE the queue put: the worker may pop and
        # admit the request before this thread runs again
        trace.TRACER.req_submit(req.req_id, prompt_tokens=len(req.prompt),
                                t=req.submitted_at)
        self.pending.put(req)
        ins.REQUESTS_ADMITTED.inc()
        ins.QUEUE_DEPTH.set(self._queue_depth())
        if self.crashed is not None or not self._thread.is_alive():
            # lost the race with a worker crash: _fail_all may already have
            # drained the queue, so this request could sit there forever —
            # raise instead of handing back a Request nobody will serve
            raise SchedulerUnhealthy(
                f"scheduler worker died during submit ({self.crashed!r})")
        self._wake.set()
        return req

    def check_admission(self) -> None:
        """Admission control, cheapest check first; raises a
        SchedulerRejected subclass when this scheduler must not take new
        work. Rejected requests never touch the queue, so running
        generations see no perturbation at all. Also used by the API tier
        to shed STREAM requests before their response headers go out."""
        if self.crashed is not None or not self._thread.is_alive():
            ins.REQUESTS_SHED.labels(reason="unhealthy").inc()
            raise SchedulerUnhealthy(
                f"scheduler worker is dead ({self.crashed!r}); refusing work")
        if self.stalled:
            # the watchdog says the worker is wedged mid-chunk: queueing more
            # work would strand more clients. The flag clears if heartbeats
            # resume, and 503+Retry-After tells callers to come back then.
            ins.REQUESTS_SHED.labels(reason="unhealthy").inc()
            raise SchedulerUnhealthy(
                "scheduler worker is stalled (device chunk past "
                "--stall-deadline-s); refusing work")
        if self._draining.is_set():
            ins.REQUESTS_SHED.labels(reason="draining").inc()
            raise SchedulerDraining("scheduler is draining; no new requests")
        # a capacity-deferred head request left the queue but still owes
        # service: it counts against the shed bound, so a queue backed up
        # behind pool exhaustion sheds at the same depth as any other backlog
        depth = self._queue_depth()
        if self.max_queue and depth >= self.max_queue:
            ins.REQUESTS_SHED.labels(reason="queue_full").inc()
            raise QueueFull(
                f"admission queue full ({depth} >= "
                f"--max-queue {self.max_queue})")
        try:
            faults.fire("scheduler.queue")
        except faults.InjectedFault as e:
            # the drill impersonates overflow, so it counts as overflow
            ins.REQUESTS_SHED.labels(reason="queue_full").inc()
            raise QueueFull(str(e)) from e

    def _busy(self) -> bool:
        """Whether the worker owes anyone progress (watchdog gating: an idle
        worker parked on its wake event must never read as stalled).

        Container occupancy alone is NOT enough: during admission start and
        commit the worker briefly holds a request in NO container (popped
        from the backlog / in-flight list, slot not yet assigned) while
        doing milliseconds of device work — a cross-thread drain() polling
        exactly then used to read the system as idle and cut the request
        mid-commit (found by the DLLAMA_LOCK_AUDIT timing perturbation,
        ISSUE 14). The time ledger's exclusive state closes the window: the
        worker is only truly idle when it says so."""
        return (bool(self.slots) or bool(self._inflight)
                or bool(self._recover) or bool(self._backlog)
                or self._deferred is not None or not self.pending.empty()
                or self.ledger.state() not in ("idle", None))

    def health(self) -> dict:
        """Liveness + readiness snapshot for the API tier's /health.

        `live`   — the worker thread can still make progress (alive, not
                   crashed, not known-wedged): false means restart me.
        `ready`  — admit new work here: false while draining, saturated, or
                   not live (balancers should route away, not kill).
        The rest is the observability payload: queue depth, busy slots, and
        the age of the worker's last heartbeat."""
        qdepth = self._queue_depth()
        live = (self._thread.is_alive() and self.crashed is None
                and not self.join_failed and not self.stalled)
        saturated = bool(self.max_queue) and qdepth >= self.max_queue
        return {
            "live": live,
            "ready": live and not self._draining.is_set() and not saturated,
            "queue_depth": qdepth,
            "max_queue": self.max_queue,
            "busy_slots": int(np.asarray(self.engine.active).sum()),
            "n_slots": self.engine.n_slots,
            "in_flight_admissions": len(self._inflight),
            # paged KV pool occupancy (None on the dense layout); a deferred
            # head request is the capacity-wait signal operators watch
            "kv_pages": self.engine.kv_page_stats()
            if hasattr(self.engine, "kv_page_stats") else None,
            "admission_deferred": self._deferred is not None,
            "last_step_age_s": round(time.monotonic() - self._heartbeat, 3),
            "stall_deadline_s": self.stall_deadline_s,
            "stalled": self.stalled,
            "stall_count": self.stall_count,
            "draining": self._draining.is_set(),
            "crashed": repr(self.crashed) if self.crashed is not None else None,
            "join_failed": self.join_failed,
            # warm-restart supervision: lifetime restarts, the budget, and
            # how many recovered requests still await re-admission
            "restarts": self.restart_count,
            "restart_max": self.restart_max,
            "recovering": len(self._recover),
            # hybrid chunked prefill + preemption (ISSUE 12): the live
            # per-chunk budget (0 = legacy phase-split), lifetime
            # preempt/resume totals, and how many suspended requests are
            # parked in the backlog awaiting resume
            "prefill_budget": self._budget_now,
            "preemptions": self.preempt_count,
            "resumed": self.resume_count,
            "preempted_waiting": sum(
                1 for r in list(self._backlog) if r.preempted),
            # compile observability (ISSUE 13): operators see a recompile
            # storm from the health probe without scraping /metrics —
            # `unexpected` > 0 means the compiled-shape contract broke
            "compile": {
                "warmup": self.warmup,
                "warmed_buckets": (None if self.warmup_report is None
                                   else self.warmup_report["compiled"]),
                "full_coverage": (None if self.warmup_report is None
                                  else self.warmup_report["full_coverage"]),
                "compiles": compile_obs.LEDGER.total_compiles(),
                "unexpected_compiles": compile_obs.LEDGER.total_unexpected(),
            },
        }

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: stop admission (submit raises SchedulerDraining),
        let in-flight and already-queued requests finish, then shut down.
        Returns True when everything completed inside the timeout; False
        means stragglers were cut off by shutdown."""
        self._draining.set()
        self._wake.set()
        trace.TRACER.event("drain.begin", cat="lifecycle", track="scheduler",
                           timeout_s=float(timeout_s))
        deadline = time.monotonic() + max(0.0, timeout_s)
        clean = False
        while time.monotonic() < deadline:
            if not self._busy():
                clean = True
                break
            if self.crashed is not None or not self._thread.is_alive():
                break  # nothing will ever finish; stop waiting
            time.sleep(0.02)
        if not clean:
            log.warning("drain timeout (%.1fs): %d slots / %d admissions / "
                        "%d queued still in flight — shutting down anyway",
                        timeout_s, len(self.slots), len(self._inflight),
                        self.pending.qsize())
        trace.TRACER.event("drain.end", cat="lifecycle", track="scheduler",
                           clean=clean)
        pool = getattr(self.engine, "pool", None)
        if pool is not None:
            # allocator integrity check at the lifecycle boundary: a drain
            # that leaks pages (or drove refcounts inconsistent) is reported
            # here — and counted — even when the serving run looked clean
            report = pool.audit(raise_on_fail=False)
            if not report["ok"]:
                log.error("kv page-pool audit FAILED at drain: %s",
                          "; ".join(report["problems"]))
        self.shutdown()
        return clean

    def latency_summary(self) -> dict:
        """Aggregate TTFT / inter-token latency over completed requests, plus
        the admission-stall record: the max/mean decode-to-decode gap that
        admission work (prefill chunks, commits) inserted between fused decode
        chunks — what batch-mates' ITL actually degrades by during a join.

        This is the host-side per-SCHEDULER convenience view; the same marks
        feed the process-wide metrics registry (`_observe_finish`) that
        `GET /metrics` exposes as dllama_ttft_seconds / dllama_itl_seconds /
        dllama_e2e_latency_seconds histograms — one observation point, two
        read paths."""
        with self._metrics_lock:
            done = list(self._completed)
            gaps = list(self._admit_gaps_ms)
            hgaps = list(self._host_gap_ms)
        ttfts = [r.ttft_ms for r in done if r.ttft_ms is not None]
        itls = [r.itl_ms for r in done if r.itl_ms is not None]
        mean = lambda xs: sum(xs) / len(xs) if xs else None
        # tail latency from the sliding-window estimator (obs/perf): a mean
        # alone hides exactly the requests the SLO work exists for
        def q_ms(w, q):
            v = w.quantile(q)
            return None if v is None else round(v * 1000.0, 3)
        return {
            "completed": len(done),
            "ttft_ms_mean": mean(ttfts),
            "ttft_ms_p50": q_ms(self.perf.ttft, 0.5),
            "ttft_ms_p95": q_ms(self.perf.ttft, 0.95),
            "itl_ms_mean": mean(itls),
            "itl_ms_p50": q_ms(self.perf.itl, 0.5),
            "itl_ms_p95": q_ms(self.perf.itl, 0.95),
            "reused_prefix_tokens": self.reused_prefix_tokens,
            "admission_gaps": len(gaps),
            "admission_stall_ms_max": max(gaps) if gaps else None,
            "admission_stall_ms_mean": mean(gaps),
            "decode_host_gaps": len(hgaps),
            "decode_host_gap_ms_max": max(hgaps) if hgaps else None,
            "decode_host_gap_ms_mean": mean(hgaps),
            # paged KV pool occupancy (None on the dense layout) — the same
            # numbers the dllama_kv_pages_{total,used,shared} gauges export
            "kv_pages": self.engine.kv_page_stats()
            if hasattr(self.engine, "kv_page_stats") else None,
            # radix prefix-cache accounting (None when off/dense): hit_tokens
            # is the saved-prefill-rows total the dllama_radix_* series export
            "radix": self.engine.radix_stats()
            if hasattr(self.engine, "radix_stats") else None,
            # speculative-decoding acceptance record (None when the engine
            # was built spec=0) — the dllama_spec_* series' host-side view:
            # tokens_per_cycle is the realized batch speedup per forward
            "spec": self.engine.spec_stats()
            if hasattr(self.engine, "spec_stats") else None,
            # hybrid chunked prefill + preemption (ISSUE 12): the live
            # budget and the lifetime preempt/resume record — the host-side
            # view of dllama_prefill_budget_tokens / dllama_preemptions_
            # total / dllama_resumed_total
            "hybrid": {
                "prefill_budget": self._budget_now,
                "mode": ("off" if not self._hybrid_on
                         else ("auto" if self._budget_ctl is not None
                               else "fixed")),
                "preemptions": self.preempt_count,
                "resumed": self.resume_count,
            },
            # compile-ledger record (ISSUE 13): lifetime compiles/seconds
            # and the unexpected (off-contract) count — the host-side view
            # of the dllama_jit_* series; `warmup` names the boot mode
            "compile": dict(compile_obs.LEDGER.summary(),
                            warmup_mode=self.warmup),
        }

    def reset_latency_stats(self) -> None:
        """Drop accumulated latency/stall samples (benches call this after
        their compile-warmup phase so first-compile gaps don't pollute the
        measured record). Also rewinds the loop's decode-gap anchor so the
        first post-reset gap cannot span back to a pre-reset decode chunk."""
        with self._metrics_lock:
            self._completed.clear()
            self._admit_gaps_ms.clear()
            self._host_gap_ms.clear()
        self._t_dec_end = None
        self._t_consumed = None
        # fresh sliding windows too: warmup-compile latencies must not sit
        # in the p95 for the next minute of a bench leg (same policy and
        # cost model; attribute swap is atomic for concurrent scrapes)
        self.perf = perf.PerfAggregator(slo=self.perf.slo,
                                        cost_model=self.perf.cost_model)

    def cancel(self, req: Request, reason: str = "cancelled") -> None:
        """Release a request's slot. `reason` becomes the finish_reason when
        the request is still live — "cancelled" for real client
        cancellations (the default), "stop" when the API tier is releasing
        a stream that already ended on a string stop-sequence (a success).
        A no-op for requests that already finished."""
        req.cancel_reason = reason
        req.cancelled.set()
        self._wake.set()

    #: how long shutdown() waits for the worker before declaring it wedged
    #: (attribute, not constant: fault drills shrink it instead of sleeping)
    join_timeout_s: float = 10.0

    #: ceiling on the exponential restart backoff (attribute, not constant:
    #: the chaos soak shrinks it so hundreds of injected crashes stay fast)
    restart_backoff_max_s: float = 5.0

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=self.join_timeout_s)
        if self._thread.is_alive():
            # a worker that won't die is almost certainly wedged inside a
            # device call; it is daemonic so the process can still exit, but
            # the engine must be considered unusable — say so loudly and let
            # /health report it instead of silently returning
            self.join_failed = True
            log.warning(
                "scheduler worker failed to join within %.1fs (thread %r, "
                "alive=%s, %d slots / %d admissions still held) — engine "
                "state is unrecoverable; /health reports live=false",
                self.join_timeout_s, self._thread.name,
                self._thread.is_alive(), len(self.slots), len(self._inflight))

    # ------------------------------------------------------------------ loop

    def _observe_finish(self, req: Request) -> None:
        """The single registry write point for a terminal request: finish
        counter + TTFT/ITL/e2e histograms from the request's latency marks —
        the same marks the `_completed` ring (latency_summary's per-scheduler
        view) records, so /metrics and the summary cannot disagree. Also the
        single flight-recorder finish point: every terminal path (normal,
        cancel, crash, shutdown, admission reject) flows through here."""
        ins.REQUESTS_FINISHED.labels(reason=req.finish_reason or "unknown").inc()
        trace.TRACER.req_end(req.req_id, req.finish_reason or "unknown",
                             t=req.finished_at, **req.timings())
        if req.first_token_at is not None:
            ins.TTFT_SECONDS.observe(req.first_token_at - req.submitted_at)
        if req.finished_at is not None:
            ins.E2E_SECONDS.observe(req.finished_at - req.submitted_at)
        itl = req.itl_ms
        if itl is not None:
            ins.ITL_SECONDS.observe(itl / 1000.0)
        # the SLO/goodput join (obs/perf): same marks as the histograms
        # above, so the windowed quantiles, the burn counters, and /metrics
        # cannot disagree about what this request experienced
        self.perf.observe_finish(
            finish_reason=req.finish_reason or "unknown",
            ttft_ms=req.ttft_ms, itl_ms=itl,
            e2e_ms=(None if req.finished_at is None
                    else (req.finished_at - req.submitted_at) * 1000.0),
            tokens=req.produced)

    def _finish(self, req: Request, reason: str, keep_rows: int | None = None) -> None:
        if req.slot >= 0:
            if self._radix is not None:
                # the tree is the cache: insert the trustworthy emitted
                # prefix (full pages adopt a tree reference), then hand the
                # slot's every page back — idle slots stay empty, and reuse
                # for future requests comes from the tree, not the slot.
                # keep_rows=None means the rows are unspecified (error/NaN/
                # crash paths): nothing enters the tree.
                if keep_rows:
                    self.engine.radix_insert(
                        req.slot, self.slot_tokens.get(req.slot, [])[:keep_rows])
                self.engine.release(req.slot, None)
                self.slot_tokens[req.slot] = []
            else:
                self.engine.release(req.slot, keep_rows)
                if keep_rows is not None:
                    # only the first keep_rows tokens have live KV rows (the
                    # last emitted token was sampled but never fed back)
                    self.slot_tokens[req.slot] = self.slot_tokens.get(req.slot, [])[:keep_rows]
                else:
                    self.slot_tokens[req.slot] = []  # unknown state: never reuse
            self.slots.pop(req.slot, None)
            req.slot = -1
        req.finish_reason = req.finish_reason or reason
        req.finished_at = time.monotonic()
        with self._metrics_lock:
            self._completed.append(req)
            del self._completed[:-256]  # bound the ring
        self._observe_finish(req)
        ins.BUSY_SLOTS.set(len(self.slots))
        req.out.put(_END)

    def _emit(self, req: Request, token: int, row_at_emit: int) -> bool:
        """Queue one token; returns True when the request just finished."""
        if req.first_token_at is None:
            req.first_token_at = time.monotonic()
            trace.TRACER.req_first_token(req.req_id, t=req.first_token_at)
        req.out.put(int(token))
        req.produced += 1
        ins.TOKENS_GENERATED.inc()
        if req.slot >= 0:
            self.slot_tokens.setdefault(req.slot, []).append(int(token))
        if token in req.eos_ids:
            self._finish(req, "stop", keep_rows=row_at_emit)
            return True
        if req.produced >= req.max_tokens:
            self._finish(req, "length", keep_rows=row_at_emit)
            return True
        return False

    def _pick_slot(self, prompt: list[int]) -> tuple[int | None, int, int | None]:
        """(slot, reusable_prefix_len, donor): the idle slot whose cached
        token history shares the longest full prefix with `prompt`. When a
        DIFFERENT slot (idle or actively decoding) holds a longer matching
        prefix, the cheapest idle slot is chosen and `donor` names the slot
        whose KV rows should be copied in first (cross-slot prefix share —
        e.g. a common system prompt cached once serves every slot). Slots
        reserved by in-flight admissions are neither destinations nor donors
        (their rows are mid-overwrite)."""
        reserved = {adm.slot for _, adm, _ in self._inflight}
        idle = [
            s for s in range(self.engine.n_slots)
            if not self.engine.active[s] and s not in reserved
        ]
        if not idle:
            return None, 0, None

        # cross-slot donors need the engine's slot-copy primitive (dp meshes
        # shard the batch axis, where donor search stays within idle slots)
        cross_ok = getattr(self.engine, "supports_cross_slot_copy", False)
        donors = [s for s in range(self.engine.n_slots) if s not in reserved] if cross_ok else idle
        lcp = self._lcp_lengths(prompt, donors)
        best_idle = max(idle, key=lcp.__getitem__)
        best_any = max(donors, key=lcp.__getitem__)
        if lcp[best_any] > lcp[best_idle]:
            dst = min(idle, key=lambda s: len(self.slot_tokens.get(s, [])))
            return dst, lcp[best_any], best_any
        if lcp[best_idle] > 0:
            return best_idle, lcp[best_idle], None
        return min(idle, key=lambda s: len(self.slot_tokens.get(s, []))), 0, None

    def _lcp_lengths(self, prompt: list[int], donors: list[int]) -> dict[int, int]:
        """Longest-common-prefix length of `prompt` against every donor
        slot's cached token history, in ONE padded-matrix comparison (the
        per-slot np.nonzero scan was O(B·len) Python work on the admission
        path). Reusable rows = LONGEST COMMON PREFIX (not all-or-nothing: a
        shared system prompt with a divergent tail still reuses the common
        part), capped so at least one prompt token remains to prefill (stale
        rows past it are masked); an ACTIVE donor's last emitted token has
        no KV row yet, hence its extra -1 cap."""
        caps = {}
        for s in donors:
            cached = self.slot_tokens.get(s, [])
            n = min(len(cached), len(prompt) - 1)
            if self.engine.active[s]:
                n = min(n, len(cached) - 1)
            caps[s] = max(n, 0)
        width = max(caps.values(), default=0)
        if width <= 0:
            return dict.fromkeys(donors, 0)
        # pad with -1 (never a token id) so rows shorter than the widest cap
        # mismatch past their own cap by construction
        mat = np.full((len(donors), width), -1, np.int64)
        for i, s in enumerate(donors):
            if caps[s]:
                mat[i, : caps[s]] = self.slot_tokens[s][: caps[s]]
        hit = mat == np.asarray(prompt[:width], np.int64)[None, :]
        # leading run of equalities: cumprod zeroes everything at and past
        # the first mismatch, so the row sum IS the LCP length
        lens = np.cumprod(hit, axis=1).sum(axis=1)
        return {s: int(n) for s, n in zip(donors, lens)}

    def _queue_depth(self) -> int:
        """Requests owed service but not yet admitted: the pending intake
        queue, the policy backlog (incl. preempted requests awaiting
        resume), the capacity-deferred head, and any restart-recovered
        requests awaiting re-admission (one definition for the gauge,
        /health, and the --max-queue shed bound — they must not
        disagree)."""
        return (self.pending.qsize() + len(self._backlog)
                + (1 if self._deferred is not None else 0)
                + len(self._recover))

    def _reclaim_pages(self, needed: int) -> bool:
        """Free KV pages for the all-starved decode rescue: LRU radix-tree
        leaves when the tree is the cache, idle slots' retained pages on
        the legacy path. Returns True when anything came free."""
        if self._radix is not None:
            return self.engine.radix_evict(needed) > 0
        return self._evict_idle_pages(needed, set())

    def _evict_idle_pages(self, needed: int, exclude: set) -> bool:
        """Paged prefix-cache reclaim: drop idle slots' cached pages
        (smallest caches first — the cheapest reuse to lose) until `needed`
        pages came free, then STOP — a one-page shortfall must not wipe
        every cached prefix. `exclude` protects the chosen destination and
        donor. Returns True when anything was freed."""
        reserved = {adm.slot for _, adm, _ in self._inflight}
        victims = sorted(
            (s for s in range(self.engine.n_slots)
             if not self.engine.active[s] and s not in reserved
             and s not in exclude and self.slot_tokens.get(s)),
            key=lambda s: len(self.slot_tokens.get(s, [])),
        )
        freed = 0
        for s in victims:
            if freed >= needed:
                break
            freed += self.engine.drop_slot_pages(s)
            self.slot_tokens[s] = []
        return freed > 0

    def _shed_timeout(self, req: Request, where: str = "queued") -> None:
        """Terminal 'timeout' finish for a not-yet-admitted request: shed
        BEFORE prefill — no slot, no pages, no device work spent on a
        request whose client stopped waiting. A timeout is a clean terminal
        finish, not an error: the stream just ends with
        finish_reason="timeout"."""
        ins.REQUESTS_SHED.labels(reason="timeout").inc()
        trace.TRACER.event("request.timeout", cat="deadline",
                           track="requests", req_id=req.req_id, where=where)
        # _finish handles the rest (slot is -1: no release) — crucially the
        # _completed ring append, so queue-expired timeouts show up in
        # latency_summary() exactly like decode-boundary ones
        self._finish(req, "timeout")

    def _shed_expired_queued(self) -> None:
        """Deadline sweep over requests the worker has NOT admitted yet:
        the pending queue, the capacity-deferred head, and the restart-
        recover list. The pop path below also checks deadlines, but a
        saturated server (every slot busy, or a parked deferred head) can
        go entire requests without popping anything — timeout_s must bound
        the client's wait even when no slot ever frees. Runs once per
        chunk boundary, same granularity as the running-request check."""
        now = time.monotonic()

        def expired(r: Request) -> bool:
            return r.deadline_at is not None and now >= r.deadline_at

        dead: list[Request] = []
        with self.pending.mutex:
            q = self.pending.queue
            if any(expired(r) for r in q):
                dead.extend(r for r in q if expired(r))
                keep = [r for r in q if not expired(r)]
                q.clear()
                q.extend(keep)
        if any(expired(r) for r in self._backlog):
            # the policy backlog too — incl. preempted requests whose
            # deadline passed while suspended (a clean 'timeout' finish;
            # their already-emitted tokens stand)
            dead.extend(r for r in self._backlog if expired(r))
            self._backlog = [r for r in self._backlog if not expired(r)]
        if self._deferred is not None and expired(self._deferred):
            dead.append(self._deferred)
            self._deferred = None
        if any(expired(r) for r in self._recover):
            dead.extend(r for r in self._recover if expired(r))
            self._recover = [r for r in self._recover if not expired(r)]
        for req in dead:
            self._shed_timeout(req)

    # --------------------------------------- scheduling policy (ISSUE 12)

    def _drain_pending(self) -> None:
        """Move intake-queue arrivals into the policy backlog (worker-side
        only; submit() keeps the thread-safe Queue as its entry point)."""
        while True:
            try:
                self._backlog.append(self.pending.get_nowait())
            except queue.Empty:
                return

    def _tenant_weight(self, tenant: str) -> float:
        return max(float(self.tenant_weights.get(tenant, 1.0)), 1e-6)

    def _select_next(self) -> Request | None:
        """Policy pick from the backlog: the highest priority class
        present; within it the tenant with the smallest WFQ virtual time;
        within a tenant, FIFO. Pops and returns the pick (None when the
        backlog is empty). Cancelled/expired entries are popped too — the
        caller's existing terminal handling covers them."""
        if not self._backlog:
            return None
        best_i = 0
        best_key = None
        for i, r in enumerate(self._backlog):
            key = (-int(r.priority), self._tenant_vt.get(r.tenant, 0.0), i)
            if best_key is None or key < best_key:
                best_key, best_i = key, i
        return self._backlog.pop(best_i)

    def _charge_tenant(self, req: Request) -> None:
        """Start-time fair queueing charge at admission: the request's
        start tag is max(its tenant's own finish tag, the global virtual
        clock `_vt_now`), its tenant's finish tag advances by
        (prompt + max_tokens) / weight from there, and the clock advances
        to the start tag. A tenant returning from idle therefore gets one
        immediate pick and then competes from 'now' — idle time banks no
        credit, which is what bounds any backlogged tenant's wait to its
        fair share (the starvation bound the tests drive). Charged ONCE
        per request lifetime: a preempted request resuming (or a deferred
        head rejoining the backlog) was already paid for; billing it again
        would compound the very deprioritization that suspended it."""
        if req.wfq_charged:
            return
        req.wfq_charged = True
        # start-time fair queueing: the admission's start tag is
        # max(tenant's own finish tag, the global virtual clock) and the
        # clock advances to that start — a tenant returning from idle is
        # snapped to 'now' (one immediate pick, then fair share; idle time
        # banks no credit), while a fresh system stays at clock 0 so
        # weights bite from the first admission
        own = self._tenant_vt.get(req.tenant, 0.0)
        start = max(own, self._vt_now)
        cost = (len(req.prompt) + max(int(req.max_tokens), 1))
        self._tenant_vt[req.tenant] = (
            start + cost / self._tenant_weight(req.tenant))
        self._vt_now = start

    def _record_resume(self, req: Request, slot: int) -> bool:
        """Stamp `req` with its bit-exact resume record off `slot`'s
        settled state: the emitted tokens and the PRNG key advanced to the
        interruption point — advanced by the tokens emitted SINCE the last
        (re)commit only (after a prior resume, keys[slot] is already an
        advanced key; replaying the cumulative produced-1 would
        double-count and silently break sampled-stream resume). The ONE
        definition site for the resume invariant, shared by preemption and
        warm-restart recovery. Returns False when the emit records
        disagree (no trustworthy resume exists)."""
        emitted = self.slot_tokens.get(slot, [])[len(req.prompt):]
        if req.produced < 1 or len(emitted) != req.produced:
            return False
        req.resume_tokens = list(emitted)
        req.resume_key = self._advance_key(
            self.engine.keys[slot], req.produced - 1 - req.key_advances)
        req.key_advances = req.produced - 1
        return True

    def _preempt(self, req: Request, reason: str) -> bool:
        """Suspend a RUNNING request at this (settled) chunk boundary:
        record its resume point — emitted tokens + PRNG key advanced to the
        interruption, exactly the warm-restart resume record — then release
        the slot while the KV pages stay referenced: the radix tree adopts
        the written prefix on the paged layout (resume later maps it back
        by refcount, near-zero recompute — only a partial boundary page
        re-prefills), the kept slot rows serve the same role on dense. The
        request parks in the backlog; policy decides when it resumes.
        Returns False when the request has no trustworthy resume record
        (safer to let it run)."""
        slot = req.slot
        if not self._record_resume(req, slot):
            return False
        req.preempted = True
        rows = int(self.engine.pos[slot])
        if self._radix is not None:
            self.engine.radix_insert(slot, self.slot_tokens[slot][:rows])
            self.engine.release(slot, None)
            self.slot_tokens[slot] = []
        else:
            self.engine.release(slot, rows)
            self.slot_tokens[slot] = self.slot_tokens.get(slot, [])[:rows]
        self.slots.pop(slot, None)
        req.slot = -1
        self._backlog.append(req)
        self.preempt_count += 1
        ins.BUSY_SLOTS.set(len(self.slots))
        ins.PREEMPTIONS.labels(reason=reason).inc()
        trace.TRACER.event("request.preempted", cat="scheduling",
                           track="requests", req_id=req.req_id,
                           reason=reason, tokens=req.produced)
        log.info("preempted request (reason=%s, %d tokens emitted; pages "
                 "stay referenced)", reason, req.produced,
                 extra=trace.log_extra(req.req_id))
        return True

    def _maybe_preempt(self) -> None:
        """Boundary preemption check: when a STRICTLY higher-priority
        request is waiting and blocked — no free slot (reason='slot'), or
        the capacity-deferred head out-ranks a runner (reason='capacity') —
        suspend the lowest-priority running request (most recently admitted
        among ties: least sunk work lost). At most one preemption per
        boundary; admission this same boundary reuses the freed slot and
        pages."""
        if not self._preempt_on or not self.slots:
            return
        now = time.monotonic()
        waiting = [r for r in self._backlog + self._recover
                   + ([self._deferred] if self._deferred is not None else [])
                   if not r.cancelled.is_set()
                   and (r.deadline_at is None or now < r.deadline_at)]
        if not waiting:
            return
        top = max(waiting, key=lambda r: int(r.priority))
        victims = [r for r in self.slots.values()
                   if int(r.priority) < int(top.priority)
                   and not r.cancelled.is_set()]
        if not victims:
            return
        reserved = {adm.slot for _, adm, _ in self._inflight}
        free_slots = sum(1 for s in range(self.engine.n_slots)
                         if not self.engine.active[s] and s not in reserved)
        if free_slots <= 0:
            reason = "slot"
        elif (self._deferred is not None
              and int(self._deferred.priority) >= int(top.priority)):
            # a slot is free but the highest-priority waiter is parked on
            # KV-page capacity: freeing a low-priority runner's pages (its
            # release hands them to the tree, where admission reclaim can
            # evict them) is the only lever besides waiting
            reason = "capacity"
        else:
            return
        victim = min(victims,
                     key=lambda r: (int(r.priority),
                                    -(r.admitted_at or 0.0)))
        self._preempt(victim, reason)

    def _admit_starts(self, boundary: bool = True) -> None:
        """Pop pending requests into in-flight admissions while slots allow.

        ``boundary=False`` is the overlapped-loop fast path (hybrid only):
        admission STARTS are safe off an in-flight non-spec chunk —
        add_begin's device work is surgical per-row/page updates composed
        on the carry, and the admitting slot is inactive in the chunk — so
        a new request's first hybrid slice dispatches as the very next
        successor instead of draining the pipeline first. Preemption is
        skipped there (releasing a RUNNING slot needs settled mirrors).

        Paged layout: admission capacity is FREE PAGES, not free slots — a
        request whose prompt (+ one decode page) the pool cannot cover first
        reclaims idle slots' cached pages, and if still short is parked in
        `_deferred` (FIFO head; later requests wait behind it) until
        releases free capacity. Shedding still applies while it waits: the
        deferred request counts toward --max-queue depth."""
        self._drain_pending()
        self._shed_expired_queued()
        if boundary:
            self._maybe_preempt()
        if (self._deferred is not None and self._backlog
                and max(int(r.priority) for r in self._backlog)
                > int(self._deferred.priority)):
            # priority-inversion guard: a capacity-parked lower-priority
            # head must not gate a higher-priority arrival — it rejoins the
            # policy backlog and competes from there (its pages were never
            # held; deferral is a wait, not a reservation)
            self._backlog.append(self._deferred)
            self._deferred = None
        reserved = len(self._inflight)
        while (self._recover or self._deferred is not None
               or self._backlog):
            if int((~self.engine.active).sum()) - reserved <= 0:
                return
            from_recover = False
            if self._recover:
                # restart-recovered requests re-admit FIRST (they are the
                # oldest work in the system); mid-stream resumes re-prefill
                # prompt + emitted tokens below
                req = self._recover.pop(0)
                from_recover = True
            elif self._deferred is not None:
                req, self._deferred = self._deferred, None
            else:
                req = self._select_next()
                if req is None:
                    return
                self._charge_tenant(req)
            if req.cancelled.is_set():
                req.finish_reason = req.cancel_reason
                req.finished_at = time.monotonic()
                self._observe_finish(req)
                req.out.put(_END)
                continue
            if (req.deadline_at is not None
                    and time.monotonic() >= req.deadline_at):
                # expired between the sweep and the pop: same shed path
                self._shed_timeout(req)
                continue
            # the rows this admission must write: the prompt — plus, for a
            # restart resume, every already-emitted token except the last
            # (a sampled token's KV row only exists once it is fed back;
            # the last one becomes the decode carry via resume_commit)
            toks = (req.prompt if req.resume_tokens is None
                    else req.prompt + req.resume_tokens[:-1])
            if len(toks) >= self.engine.seq_len:
                # reject BEFORE slot search or any donor copy: a hopeless
                # admission must not evict a slot's cached prefix (nor pay
                # the per-slot LCP scan)
                req.finish_reason = "error"
                req.finished_at = time.monotonic()
                self._observe_finish(req)
                req.out.put(ValueError(
                    f"prompt ({len(toks)}) exceeds seq_len {self.engine.seq_len}"
                ))
                continue
            pool = getattr(self.engine, "pool", None)
            if (pool is not None
                    and self.engine.min_pages_for(len(toks)) > pool.n_pages):
                # never-fits reject: the prompt's pages (+ the decode
                # reserve) must ALL be resident at once, and reused/shared
                # prefix pages still occupy pool pages — so the bound is
                # absolute, independent of any cached prefix. Deferring such
                # a request would deadlock the FIFO head forever; reject it
                # like the seq_len check.
                req.finish_reason = "error"
                req.finished_at = time.monotonic()
                self._observe_finish(req)
                req.out.put(ValueError(
                    f"prompt ({len(toks)}) needs "
                    f"{self.engine.min_pages_for(len(toks))} KV pages; "
                    f"the pool holds {pool.n_pages}"))
                continue
            rhit = None
            if self._radix is not None:
                # radix reuse: the GLOBAL tree, not resident slots, is the
                # prefix cache — any idle slot serves (they are all empty),
                # the walk finds the longest mappable prefix, and capacity
                # shortfalls reclaim LRU tree leaves (the matched path is
                # protected) before the request parks
                taken = {adm.slot for _, adm, _ in self._inflight}
                slot = next(s for s in range(self.engine.n_slots)
                            if not self.engine.active[s] and s not in taken)
                reuse, rhit = self.engine.radix_lookup(toks)
                deficit = self.engine.radix_admission_deficit(len(toks), reuse)
                if deficit > 0 and self.engine.radix_evict(deficit, rhit) > 0:
                    deficit = self.engine.radix_admission_deficit(len(toks),
                                                                  reuse)
                cross = False
            else:
                slot, reuse, donor = self._pick_slot(toks)
                cross = donor is not None and donor != slot and reuse > 0
                deficit = self.engine.admission_deficit(slot, reuse,
                                                        len(toks), cross)
                if deficit > 0:
                    # pool short: reclaim just enough idle cache (keeping the
                    # destination and donor — their rows are this admission's
                    # reuse), then re-pick (eviction may change the best donor)
                    if self._evict_idle_pages(deficit, {slot, donor}):
                        slot, reuse, donor = self._pick_slot(toks)
                        cross = donor is not None and donor != slot and reuse > 0
                    deficit = self.engine.admission_deficit(slot, reuse,
                                                            len(toks), cross)
            if deficit > 0:
                # still short: every missing page is held by RUNNING
                # requests — park at the head until releases free them.
                # A recovered request parks back at the recover head
                # (the _deferred box may already hold the pre-crash
                # queue head — never overwrite it).
                if from_recover:
                    self._recover.insert(0, req)
                else:
                    self._deferred = req
                return
            try:
                if rhit is not None and reuse:
                    # map the tree prefix into the slot by refcount: block
                    # table written, zero copies; a partial boundary page is
                    # copy-on-written inside add_begin's prepare_admission
                    self.engine.radix_map(slot, rhit)
                elif cross:
                    # cross-slot share: materialize the donor's prefix rows
                    # in the destination before the delta prefill
                    self.engine.copy_prefix_rows(donor, slot, reuse)
                    self.slot_tokens[slot] = list(
                        self.slot_tokens.get(donor, [])[:reuse]
                    )
                adm = self.engine.add_begin(slot, toks[reuse:],
                                            start_pos=reuse, req_id=req.req_id)
            except Exception as e:  # bad request (too long, …) — fail just this one
                log.exception("admission rejected",
                              extra=trace.log_extra(req.req_id))
                # the slot's cache state is unknown: a paged add_begin may
                # have freed + partially reallocated its pages before
                # failing (e.g. a pool.alloc fault mid-grow), so the old
                # token-history claim could map reused prompts onto
                # uninitialized rows. Drop the claim and the pages — safe,
                # merely losing this slot's prefix reuse.
                self.slot_tokens[slot] = []
                if hasattr(self.engine, "drop_slot_pages"):
                    self.engine.drop_slot_pages(slot)
                req.finish_reason = "error"
                req.finished_at = time.monotonic()
                self._observe_finish(req)
                req.out.put(e)
                continue
            req.slot = slot
            req.admitted_at = time.monotonic()
            trace.TRACER.req_admitted(req.req_id, slot=slot,
                                      reused_tokens=reuse, t=req.admitted_at)
            self._inflight.append((req, adm, reuse))
            reserved += 1

    def _abort_admission(self, req, adm, reason) -> None:
        # rows past start_pos may be partially overwritten: the old history
        # no longer describes the slot's KV contents — and _finish must not
        # preserve them (keep_rows=None) nor miss the metrics ring
        self.slot_tokens[adm.slot] = []
        if isinstance(reason, Exception):
            # reason BEFORE the put: a client reads finish_reason the moment
            # the exception lands on its queue — it must never see None
            req.finish_reason = "error"
            req.out.put(reason)
            reason = "error"
        self._finish(req, reason)

    def _commit_admission(self, req: Request, adm, reuse: int) -> None:
        """Commit the HEAD in-flight admission (fully pumped): activate the
        slot, emit the first token (fresh admissions) or install the resume
        carry (restart/preemption resumes), insert radix prefixes, and do
        the recovery/resume accounting. Callable from the boundary pump AND
        opportunistically from the overlapped loop while the admission's
        last (non-spec) chunk is still in flight — the admitting slot is
        inactive in that chunk and every commit-side device write is a
        surgical per-row update off the carry, so committing early is
        value-safe and saves a full pipeline drain (the joiner's first
        token goes out as soon as its logits materialize, and running
        streams never eat the boundary's idle window)."""
        self.ledger.transition("commit")
        # popped ONCE, up front: a failure anywhere below leaves the tuple
        # in the CALLER's hands (its except aborts this request), never a
        # second pop eating the NEXT admission's entry
        assert self._inflight and self._inflight[0][1] is adm
        self._inflight.pop(0)
        if req.resume_tokens is not None:
            # restart/preemption resume: install the last emitted token and
            # the recorded PRNG key as the decode carry — no new token is
            # sampled, so the client's stream continues exactly where it
            # was cut
            self.engine.resume_commit(
                adm, req.resume_tokens[-1], req.resume_key,
                req.temperature, req.topp,
                presence=req.presence, frequency=req.frequency,
                counted=(req.resume_tokens[:-1]
                         if (req.presence or req.frequency)
                         else None),
                spec_k=req.spec_k)
            self.slot_tokens[adm.slot] = (list(req.prompt)
                                          + list(req.resume_tokens))
            self.slots[adm.slot] = req
            if self._radix is not None:
                # resumed streams re-enter the tree too: rows written =
                # prompt + all but the unfed last resume token (so a SECOND
                # resume of a shared prefix maps instead of re-prefilling)
                if reuse:
                    self._radix.note_served(reuse)
                self.engine.radix_insert(
                    adm.slot,
                    list(req.prompt) + list(req.resume_tokens[:-1]))
            trace.TRACER.req_prefill_done(
                req.req_id, tokens=len(adm.toks) + reuse,
                reused=reuse)
        else:
            first = self.engine.add_commit(adm, req.temperature,
                                           req.topp,
                                           seed=req.seed,
                                           presence=req.presence,
                                           frequency=req.frequency,
                                           spec_k=req.spec_k)
            self.reused_prefix_tokens += reuse  # rows really served
            ins.REUSED_PREFIX_TOKENS.inc(reuse)
            self.slot_tokens[adm.slot] = list(req.prompt)
            self.slots[adm.slot] = req
            if self._radix is not None:
                # saved-prefill accounting at commit (rows REALLY served),
                # and the prompt's full pages enter the tree NOW —
                # concurrent requests sharing a system prompt hit it while
                # this one is still decoding
                if reuse:
                    self._radix.note_served(reuse)
                self.engine.radix_insert(adm.slot, req.prompt)
            trace.TRACER.req_prefill_done(
                req.req_id, tokens=len(req.prompt), reused=reuse)
            self._emit(req, first, int(self.engine.pos[adm.slot]))
        if req.recovered:
            # counted at the moment the request really made it back into a
            # slot (not at restart time — it could still fail or cancel
            # during re-admission)
            req.recovered = False
            ins.REQUESTS_RECOVERED.inc()
            trace.TRACER.event("request.recovered",
                               cat="supervision", track="requests",
                               req_id=req.req_id,
                               tokens=req.produced)
        elif req.preempted:
            # a preempted request is back in a slot and its stream
            # continues (byte-identical to uninterrupted)
            req.preempted = False
            self.resume_count += 1
            ins.RESUMED.inc()
            trace.TRACER.event("request.resumed",
                               cat="scheduling", track="requests",
                               req_id=req.req_id,
                               tokens=req.produced)

    def _commit_ready_inflight(self) -> None:
        """Opportunistic early commit (overlapped loop): while the chunk in
        flight is a plain/hybrid (non-spec) chunk, a fully-pumped head
        admission can commit NOW — blocking only on its own logits (which
        materialize with that chunk) instead of draining the pipeline for a
        whole boundary. Spec chunks are excluded: their data-dependent
        position advance must settle before any host-side slot activation
        touches shared state."""
        while self._inflight:
            req, adm, reuse = self._inflight[0]
            now = time.monotonic()
            if (adm.off < len(adm.toks) or req.cancelled.is_set()
                    or (req.deadline_at is not None
                        and now >= req.deadline_at)):
                return  # mid-pump or needs abort handling at a boundary
            try:
                self._commit_admission(req, adm, reuse)
            except Exception as e:
                log.exception("commit failed",
                              extra=trace.log_extra(req.req_id))
                # _commit_admission pops up front, so the head here is the
                # NEXT admission — pop only if the failure preceded the pop
                if self._inflight and self._inflight[0][1] is adm:
                    self._inflight.pop(0)
                self._abort_admission(req, adm, e)

    def _hybrid_now(self) -> bool:
        """Whether in-flight admissions ride fused hybrid chunks right now:
        the hybrid step is enabled AND there are decoders to fuse with
        (with no decoders the legacy pump IS the fast path — nothing to
        protect, prefill at full speed)."""
        return self._hybrid_on and bool(self.slots)

    def _pump_admissions(self) -> bool:
        """Advance in-flight admissions. Under the hybrid step (ISSUE 12)
        an admission's prefill rides the fused decode chunks instead —
        this pump then only COMMITS fully-pumped admissions (and applies
        the hard TTFT-deadline override). On the legacy phase-split path
        (--prefill-budget 0, or no decoders): when interleaving, pump
        prefill chunks of the head admission until the stall budget is
        spent (decode chunks run between calls); when not, the whole
        queue. An admission past the TTFT deadline ignores the budget and
        pumps to completion. Returns True if any admission work ran."""
        worked = False
        t0 = time.monotonic()
        while self._inflight:
            req, adm, reuse = self._inflight[0]
            if req.cancelled.is_set():
                self._inflight.pop(0)
                self._abort_admission(req, adm, "cancelled")
                continue
            if (req.deadline_at is not None
                    and time.monotonic() >= req.deadline_at):
                # deadline crossed mid-prefill: stop spending chunks on it —
                # the slot's partial rows are abandoned like a cancel's
                self._inflight.pop(0)
                trace.TRACER.event("request.timeout", cat="deadline",
                                   track="requests", req_id=req.req_id,
                                   where="prefill")
                self._abort_admission(req, adm, "timeout")
                continue
            pumped = adm.off >= len(adm.toks)
            if not pumped and self._hybrid_now():
                # the fused hybrid chunks carry this prefill (budget tokens
                # per chunk, _dispatch_chunk) — nothing to pump here unless
                # the hard TTFT deadline says finish it NOW despite the
                # decoders (the one pacing override that survives hybrid)
                overdue = (
                    self.admit_ttft_deadline_ms is not None
                    and (time.monotonic() - req.submitted_at) * 1000.0
                    >= self.admit_ttft_deadline_ms)
                if not overdue:
                    return worked
            try:
                tr = trace.TRACER
                done = pumped
                if not pumped:
                    t_ch = tr.now() if tr.enabled else 0.0
                    self.ledger.transition("prefill")
                    done = self.engine.add_step(adm)
                    if self.slots and adm.logits is not None:
                        # sync whenever decoders could stall: JAX dispatch is
                        # async, so without this the pacing clock AND the
                        # admission-gap metric would see host dispatch time
                        # only (near zero on TPU) while the chunk's device
                        # time silently serialized into the next decode
                        # chunk — under-pacing the budget and mis-attributing
                        # the stall. Applied in every admission mode so the
                        # sync/strict/paced A/B compares like with like; the
                        # chunk must finish before the next decode chunk
                        # anyway (same device stream). With no decoders there
                        # is no stall to attribute and dispatch stays
                        # pipelined.
                        jax.block_until_ready(adm.logits)
                    if tr.enabled:
                        tr.span_at("prefill.chunk", t_ch, tr.now(),
                                   cat="prefill", track="scheduler",
                                   req_id=req.req_id, slot=adm.slot,
                                   off=int(adm.off), total=len(adm.toks))
                    worked = True
                if done:
                    self._commit_admission(req, adm, reuse)
            except Exception as e:
                log.exception("prefill failed",
                              extra=trace.log_extra(req.req_id))
                # add_step failures leave the head in place; a commit
                # failure reaches here with it already popped by
                # _commit_admission — pop only our own tuple, never the
                # next admission's
                if self._inflight and self._inflight[0][1] is adm:
                    self._inflight.pop(0)
                self._abort_admission(req, adm, e)
                continue
            if not (self.admit_interleave and self.slots):
                continue  # no decoders to protect: drain the queue
            # evaluated AFTER the chunk ran (and its device sync), so an
            # admission that crosses the deadline during the chunk is
            # honored this visit, not one decode chunk late
            overdue = (
                self.admit_ttft_deadline_ms is not None
                and (time.monotonic() - req.submitted_at) * 1000.0
                >= self.admit_ttft_deadline_ms
            )
            if done and overdue:
                # an overdue admission just committed under the deadline
                # override: yield a decode chunk before touching the next
                # head, so a burst of overdue joiners costs one prefill per
                # visit — never the sum of all of them — regardless of how
                # much budget the override left unspent
                return worked
            if (time.monotonic() - t0) * 1000.0 < self.admit_stall_budget_ms:
                continue  # cheap so far: keep pumping
            if not done and overdue:
                # TTFT deadline: finish THIS admission despite the budget
                continue
            # stall budget spent: let a decode chunk run now
            return worked
        return worked

    def _fail_req(self, req: Request, exc: BaseException) -> None:
        """Crash-path finish: mark the request failed and unblock its
        consumer WITHOUT touching the engine (whose state is unknown after a
        worker crash — release()/donated buffers may be invalid)."""
        req.finish_reason = "error"
        req.finished_at = time.monotonic()
        with self._metrics_lock:
            self._completed.append(req)
            del self._completed[:-256]
        self._observe_finish(req)
        req.out.put(exc)
        req.out.put(_END)

    def _fail_all(self, exc: BaseException) -> None:
        """Fail every queue a client could be blocked on: in-flight
        admissions, decoding slots, the capacity-deferred head, and the
        pending queue. The whole point of supervision — nobody hangs
        forever on a dead worker."""
        for req, _adm, _ in self._inflight:
            self._fail_req(req, exc)
        self._inflight.clear()
        if self._deferred is not None:
            self._fail_req(self._deferred, exc)
            self._deferred = None
        for req in self._recover:
            self._fail_req(req, exc)
        self._recover = []
        for req in self._backlog:
            self._fail_req(req, exc)
        self._backlog = []
        for req in list(self.slots.values()):
            self._fail_req(req, exc)
        self.slots.clear()
        while True:
            try:
                req = self.pending.get_nowait()
            except queue.Empty:
                break
            self._fail_req(req, exc)

    def _watch(self) -> None:
        """Stall watchdog body: flag `stalled` when the worker has owed
        progress for longer than the deadline without a heartbeat. Recovers
        (clears the flag) if heartbeats resume — stall_count keeps the
        incident record either way."""
        poll = max(0.01, min(0.25, self.stall_deadline_s / 4.0))
        while not self._stop.is_set():
            time.sleep(poll)
            if self.crashed is not None:
                return  # crash supervision already owns the health verdict
            age = time.monotonic() - self._heartbeat
            if self._busy() and age > self.stall_deadline_s:
                if not self.stalled:
                    self.stalled = True
                    self.stall_count += 1
                    ins.WATCHDOG_STALLS.inc()
                    trace.TRACER.event("watchdog.stall", cat="supervision",
                                       track="scheduler", age_s=round(age, 3))
                    log.error(
                        "watchdog: scheduler worker silent for %.2fs with "
                        "work in flight (deadline %.2fs) — device chunk "
                        "presumed hung; /health reports live=false",
                        age, self.stall_deadline_s)
            elif self.stalled and age <= self.stall_deadline_s:
                self.stalled = False
                ins.WATCHDOG_RECOVERIES.inc()
                trace.TRACER.event("watchdog.recover", cat="supervision",
                                   track="scheduler")
                log.warning("watchdog: worker heartbeat resumed; clearing "
                            "stall flag (%d total stalls)", self.stall_count)

    def _run(self) -> None:
        """Supervised worker entry: any escape from the serving loop first
        attempts a warm restart under the --restart-max budget (decode state
        + page pool rebuilt against resident weights, surviving requests
        recovered, the loop re-entered); with no budget — or a restart that
        itself dies — it falls back to PR 1 semantics: every in-flight
        request fails fast (finish_reason='error', queues unblocked) and
        /health flips permanently unhealthy."""
        try:
            while True:
                try:
                    self._loop()
                    return
                except BaseException as e:  # noqa: BLE001 — supervision must be total
                    try:
                        if self._try_restart(e):
                            continue
                    except BaseException as e2:  # noqa: BLE001 — restart died too
                        log.exception("warm restart failed; giving up")
                        e = e2
                    self.crashed = e
                    log.exception("scheduler worker crashed; failing all "
                                  "in-flight requests and marking /health "
                                  "unhealthy")
                    self._fail_all(e)
                    return
        finally:
            # stop the ledger clock with the worker: the tail of the last
            # state is billed and wall_s() freezes, keeping the partition
            # invariant (sum of states == wall) true for a dead worker too
            self.ledger.close()

    #: one jitted fori_loop shared by every restart: replaying a 4000-token
    #: stream must cost ONE dispatch, not 4000 serial split() round-trips
    #: on the worker thread while every recovered request waits
    _advance_key_fn = staticmethod(jax.jit(lambda key, n: jax.lax.fori_loop(
        0, n, lambda _, k: jax.random.split(k)[0], key)))

    @classmethod
    def _advance_key(cls, key0, n: int) -> np.ndarray:
        """Replay the decode scan's per-token threefry advance: the
        device-side key after emitting n decode tokens is split(key)[0]
        applied n times to the last (re)commit-time key (BatchEngine.keys
        row). The live carry is lost with the crashed chunk, but its value
        is a pure function of the start key and the emitted-token count —
        which is what makes resumed sampled streams bit-exact."""
        key = jax.numpy.asarray(np.asarray(key0), jax.numpy.uint32)
        return np.asarray(cls._advance_key_fn(key, jax.numpy.int32(n)))

    def _try_restart(self, exc: BaseException) -> bool:
        """Warm restart after a worker crash. Returns False when the budget
        (--restart-max within --restart-window-s) is spent or restarts are
        disabled — the caller then applies the permanent-unhealthy path.

        Recovery semantics: queued + capacity-deferred requests survive
        untouched; mid-prefill admissions restart their prefill from
        scratch; mid-stream requests resume by re-prefilling prompt +
        already-emitted tokens with their recorded PRNG key and position
        (bit-exact continuation — clients see no duplicate or dropped
        tokens); requests whose state cannot be trusted fail individually
        with finish_reason='error'."""
        if self.restart_max <= 0 or self._stop.is_set():
            return False
        now = time.monotonic()
        self._restarts = [t for t in self._restarts
                          if now - t < self.restart_window_s]
        if len(self._restarts) >= self.restart_max:
            log.error("restart budget exhausted (%d within --restart-window-s"
                      " %.1fs); staying down", self.restart_max,
                      self.restart_window_s)
            return False
        self._restarts.append(now)
        # from here until _loop() re-anchors the ledger, every instant —
        # backoff sleep, recovery bookkeeping, engine rebuild — is restart
        # time, not whatever state the crash interrupted
        self.ledger.transition("restart_backoff")
        self.restart_count += 1
        attempt = len(self._restarts)
        ins.ENGINE_RESTARTS.inc()
        trace.TRACER.event("engine.restart", cat="supervision",
                           track="scheduler", attempt=attempt,
                           error=repr(exc))
        log.warning("scheduler worker crashed (%r); warm restart %d/%d "
                    "(window %.1fs)", exc, attempt, self.restart_max,
                    self.restart_window_s)
        faults.fire("engine.restart")  # drill: a restart that itself dies
        # exponential backoff, capped: repeated crashes inside one window
        # space their restarts out without ever sleeping unboundedly (the
        # budget, not the backoff, is what gives up)
        delay = min(self.restart_backoff_s * (2 ** min(attempt - 1, 10)),
                    self.restart_backoff_max_s)
        deadline = now + delay
        while time.monotonic() < deadline and not self._stop.is_set():
            # heartbeat-stamped backoff sleep: the watchdog must read
            # "restarting" as progress, not as a hung device chunk
            self._heartbeat = time.monotonic()
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
        # ---- collect the recovery set BEFORE touching the engine (the
        # host-side records are intact; only device state is suspect)
        recover: list[Request] = []
        for slot, req in sorted(self.slots.items(),
                                key=lambda kv: kv[1].submitted_at):
            ok = self._record_resume(req, slot)
            req.slot = -1
            if not ok:
                # bookkeeping drift between the emit records — resuming
                # could duplicate or drop tokens; fail this one request
                self._fail_req(req, RuntimeError(
                    "request not recoverable across engine restart "
                    "(emitted-token record disagrees with produced "
                    f"{req.produced})"))
                continue
            req.recovered = True
            recover.append(req)
        self.slots.clear()
        for req, _adm, _ in self._inflight:
            # mid-prefill: no tokens reached the client yet — re-prefill the
            # whole prompt (their partially-written rows died with the cache)
            req.slot = -1
            req.recovered = True
            recover.append(req)
        self._inflight.clear()
        self.slot_tokens.clear()
        # ---- rebuild decode state + page pool against resident weights
        self.engine.warm_restart()
        pool = getattr(self.engine, "pool", None)
        if pool is not None:
            pool.audit()  # a fresh pool failing audit means the rebuild is
            # broken — crash the restart (budget-accounted) rather than
            # serve from a corrupt allocator
        self._recover = recover + self._recover
        self._t_dec_end = None
        self._t_consumed = None
        self._heartbeat = time.monotonic()
        self._wake.set()
        log.warning("warm restart complete: %d request(s) recovered for "
                    "re-admission, %d queued untouched",
                    len(recover), self.pending.qsize())
        return True

    def _needs_boundary(self, inflight_chunk=None) -> bool:
        """True when the next chunk must wait for a fully-consumed pipeline:
        admission work (a prefill must not race the in-flight chunk's
        donated cache, and commit/release need settled host mirrors), a
        pending cancel, a slot at the cache edge, or an emptied batch.
        Speculative cycles pipeline like plain chunks (their data-dependent
        counts materialize at consumption; _dispatch_chunk drains the
        pipeline itself on a spec<->plain mode switch). The overlapped loop
        then consumes its in-flight chunk WITHOUT dispatching a successor,
        and the next iteration runs the boundary work on settled state —
        admission pumps are serialized at chunk consumption points."""
        if self._stop.is_set():
            return True
        if (not self.slots or self._deferred is not None
                or self._recover or self._backlog
                or not self.pending.empty()):
            return True
        if self._inflight:
            # hybrid admissions ride the pipelined chunks — no boundary
            # needed while the head is mid-prefill and healthy. Commit,
            # abort (cancel/deadline), and the TTFT-deadline override all
            # need settled state, so those drain the pipeline.
            if not self._hybrid_now():
                return True
            req, adm, _ = self._inflight[0]
            now0 = time.monotonic()
            if (adm.off >= len(adm.toks) or req.cancelled.is_set()
                    or (req.deadline_at is not None
                        and now0 >= req.deadline_at)
                    or (self.admit_ttft_deadline_ms is not None
                        and (now0 - req.submitted_at) * 1000.0
                        >= self.admit_ttft_deadline_ms)):
                return True
        now = time.monotonic()
        if any(r.cancelled.is_set()
               or (r.deadline_at is not None and now >= r.deadline_at)
               for r in self.slots.values()):
            # a pending cancel OR an expired per-request deadline needs
            # boundary work: "running requests finish with
            # finish_reason='timeout' at the next chunk boundary"
            return True
        # row limit = seq_len on dense; on paged also each slot's allocated
        # pages — a slot AT its limit needs boundary work (finish at the
        # context edge, or page top-up/starvation handling on the pool)
        limit = (self.engine._row_limit()
                 if hasattr(self.engine, "_row_limit") else None)
        if any(int(self.engine.pos[s]) >= (self.engine.seq_len if limit is None
                                           else int(limit[s]))
               for s in self.slots):
            return True
        if inflight_chunk is not None:
            # budget finishes are host-predictable (unlike EOS): when EVERY
            # live request exhausts max_tokens within the chunk already in
            # flight, a successor would be pure discarded overrun — don't
            # burn a device chunk on it (a fixed-budget batch would pay one
            # wasted chunk per drain otherwise). For a spec chunk the real
            # counts are still on device, so use the OPTIMISTIC per-slot
            # bound (n cycles x K+1): skipping a successor that turns out
            # needed costs one boundary trip; dispatching a pure-overrun
            # chunk costs a whole wasted device launch.
            if inflight_chunk.spec:
                bound = inflight_chunk.n * (int(self.engine.spec_k) + 1)
                return all(req.produced + bound >= req.max_tokens
                           for req in self.slots.values())
            return all(
                req.produced + int(inflight_chunk.advance[slot]) >= req.max_tokens
                for slot, req in self.slots.items()
            )
        return False

    def _observe_host_gap(self, pipeline_empty: bool,
                          exclude_s: float = 0.0) -> None:
        """Inter-chunk host gap, stamped at every chunk dispatch: how long
        the device sat idle on SCHEDULING overhead between chunks. A
        dispatch into an EMPTY pipeline pays the wall time since the
        previous chunk's tokens materialized minus `exclude_s` (admission/
        boundary work — that stall is ADMISSION_STALL_SECONDS's story, and
        polluting this series with it would drown the per-chunk signal); a
        dispatch while a chunk is still in flight pays nothing — the device
        never went idle, which is the overlap win the A/B measures."""
        if self._t_consumed is None:
            self._last_gap_ms = None
            return
        gap_s = (max(0.0, time.monotonic() - self._t_consumed - exclude_s)
                 if pipeline_empty else 0.0)
        ins.DECODE_HOST_GAP_SECONDS.observe(gap_s)
        # stashed for the decode.dispatch span's host_gap_ms arg — the trace
        # shows per-chunk what the histogram shows in aggregate
        self._last_gap_ms = gap_s * 1000.0
        with self._metrics_lock:
            self._host_gap_ms.append(gap_s * 1000.0)
            del self._host_gap_ms[:-256]

    def _dispatch_chunk(self, pipeline_empty: bool = True,
                        exclude_gap_s: float = 0.0, inflight=None):
        """Start the next device chunk — a plain fused decode chunk, or
        ONE speculative verify cycle when some live slot can accept drafts
        (per-request spec_k > 0, greedy, a K+1-row verify window). Spec
        cycles flow through the same decode_dispatch/decode_consume split
        as plain chunks (ISSUE 11), so the overlapped pipeline composes
        with speculation: cycle N+1's propose/verify launches off cycle
        N's device carry while the host emits N's tokens. Returns (chunk,
        slots snapshot); or None when `inflight` (the unconsumed
        predecessor) is of the OTHER mode — the host position mirror only
        settles when a spec cycle is consumed, so a spec<->plain switch
        drains the pipeline for one iteration instead of dispatching off
        unsettled state.

        A decode/spec failure here is NOT a per-request problem: the jitted
        step donates the KV cache, so an exception mid-chunk leaves the
        engine's buffers in an indeterminate state. It escalates to the
        supervision wrapper — every in-flight request (including ones whose
        tokens ride the unconsumed chunk) fails fast with
        finish_reason='error' and /health goes unhealthy (the process
        supervisor owns the restart)."""
        # hybrid step (ISSUE 12): while the head admission is mid-prefill
        # and decoders exist, every chunk is a FUSED hybrid dispatch that
        # carries up to `_budget_now` of its prompt tokens — no separate
        # prefill launch ever stalls the decode cadence. Hybrid chunks are
        # plain (non-spec) chunks; an in-flight spec chunk drains through
        # the same mode-switch bail as spec<->plain.
        hyb_adm = None
        if self._hybrid_now() and self._inflight:
            _req, _adm, _ = self._inflight[0]
            if (_adm.off < len(_adm.toks) and not _req.cancelled.is_set()
                    and (_req.deadline_at is None
                         or time.monotonic() < _req.deadline_at)):
                hyb_adm = _adm
        self.ledger.transition("hybrid" if hyb_adm is not None
                               else "decode_dispatch")
        use_spec = False
        alternating = False
        if getattr(self.engine, "spec_k", 0) and hyb_adm is None:
            # speculate while some live slot can actually accept drafts;
            # sampled, penalized, and spec_k=0 traffic rides the cycles one
            # token at a time (per-slot eligibility, resolved on device)
            draft = self.engine.spec_draft_k()
            elig = self.engine.spec_eligible()
            use_spec = any(draft[s] > 0 for s in self.slots)
            if use_spec and not all(elig[s] for s in self.slots):
                # gated alternation — the one case per-slot eligibility
                # cannot absorb: a live slot WITHOUT a K+1-row verify
                # window (context edge, exhausted page pool) freezes in
                # spec cycles, so plain decode chunks alternate in until
                # it finishes. Everything else rides the cycles.
                alternating = True
                use_spec = not self._spec_tick
        if inflight is not None and bool(inflight.spec) != use_spec:
            # mode switch: consume the in-flight chunk first. Crucially the
            # alternation toggle is NOT consumed here — an aborted
            # dispatch must not eat the plain-decode turn, or under
            # overlap every launched chunk would be spec and the frozen
            # slot would starve (the exact livelock alternation prevents)
            return None
        if alternating:
            self._spec_tick = use_spec  # turn consumed by a real dispatch
        n_disp = self.chunk
        if use_spec:
            # tail clamp: a chunk-sized spec launch can overshoot a
            # finishing request by up to chunk x (K+1) tokens of discarded
            # device work — when every live request fits inside ONE cycle's
            # ceiling, dispatch a single cycle instead (quantized to
            # {1, chunk} so the fused scan compiles exactly twice)
            k1 = int(self.engine.spec_k) + 1
            if all(req.max_tokens - req.produced <= k1
                   for req in self.slots.values()):
                n_disp = 1
        self._observe_host_gap(pipeline_empty, exclude_gap_s)

        def _launch():
            if hyb_adm is None:
                return self.engine.decode_dispatch(n_disp, spec=use_spec)
            if self._budget_ctl is not None:
                # SLO-driven budget: re-evaluated against the live ITL
                # window (rate-limited inside the controller)
                self._budget_now = self._budget_ctl.update(self.perf.itl)
            try:
                return self.engine.hybrid_dispatch(n_disp, hyb_adm,
                                                   self._budget_now)
            except faults.InjectedFault as e:
                if e.point != "engine.prefill":
                    raise  # decode-point drills keep the fatal contract
                # the per-request admission-failure contract survives
                # hybrid: the engine.prefill drill fires BEFORE
                # hybrid_dispatch mutates any state, so the engine is
                # clean — fail just the joiner and dispatch a plain chunk
                # for the batch. (A GENUINE failure inside the fused
                # launch is indistinguishable from a decode failure — the
                # jit donates the cache — and stays engine-fatal, handled
                # by warm restart.)
                req, adm, _reuse = self._inflight.pop(0)
                self._abort_admission(req, adm, e)
                return self.engine.decode_dispatch(n_disp, spec=False)

        tr = trace.TRACER
        if tr.enabled:
            t0 = tr.now()
            chunk = _launch()
            # the dispatch span: pure host work. Under overlap it lands
            # INSIDE the previous chunk's decode.device span — the
            # interleaving scripts/trace_smoke.sh asserts on.
            tr.span_at("decode.dispatch", t0, tr.now(), cat="decode",
                       track="scheduler", chunk=chunk.seq, n=chunk.n,
                       occupancy=len(self.slots), spec=use_spec,
                       pipelined=not pipeline_empty,
                       hybrid_tokens=(chunk.hybrid_tokens or None),
                       host_gap_ms=(None if self._last_gap_ms is None
                                    else round(self._last_gap_ms, 3)))
            if chunk.hybrid_tokens:
                # the flight recorder's prefill story stays complete under
                # hybrid: each fused slice is a prefill.chunk span for the
                # ADMITTING request, bracketing the dispatch
                _req = self._inflight[0][0] if self._inflight else None
                tr.span_at("prefill.chunk", t0, tr.now(), cat="prefill",
                           track="scheduler",
                           req_id=_req.req_id if _req else "",
                           slot=chunk.hybrid_slot, off=int(hyb_adm.off),
                           total=len(hyb_adm.toks), hybrid=True)
            return chunk, dict(self.slots)
        return _launch(), dict(self.slots)

    def _consume_chunk(self, chunk, snapshot) -> None:
        """Block on a dispatched chunk's tokens and emit them to the
        requests captured at dispatch time. A slot whose request finished
        while the chunk was in flight (EOS/budget found consuming the
        previous chunk, or a cancel) is skipped: those tokens are the
        one-chunk stop overrun — discarded, with release(keep_rows=) having
        rewound the slot to the truly-emitted prefix, so the prefix cache
        never serves overrun rows."""
        tr = trace.TRACER
        t0 = tr.now() if tr.enabled else 0.0
        self.ledger.transition("decode_wait")
        toks = self.engine.decode_consume(chunk)  # records decode.device
        self._t_dec_end = self._t_consumed = time.monotonic()
        self.ledger.transition("emit")
        if chunk.active.any():
            # roofline/goodput feed: price this chunk's HBM traffic at its
            # dispatch-time occupancy and mean live-KV horizon against the
            # exclusive device window decode_consume just measured. For a
            # spec chunk `n` is the number of verify cycles — each one
            # weight/KV sweep like a decode step — however many tokens the
            # cycles emitted (that gap IS the speculation win the goodput
            # series shows).
            self.perf.observe_chunk(
                occupancy=int(chunk.active.sum()),
                live_rows=float(chunk.start_pos[chunk.active].mean())
                + (chunk.n + 1) / 2.0,
                steps=chunk.n,
                tokens=int(chunk.advance.sum()),
                device_s=chunk.device_s)
        if tr.enabled:
            tr.span_at("decode.consume", t0, tr.now(), cat="decode",
                       track="scheduler", chunk=chunk.seq, n=chunk.n)
            t_emit = tr.now()
        bad = chunk.nonfinite()  # NaN guard: rows whose logits went
        # non-finite (or an armed decode.nan injection) — fail THOSE
        # requests, not the engine; their chunk tokens are garbage and are
        # never emitted, their rows are released unreusable
        for slot, req in snapshot.items():
            if self.slots.get(slot) is not req:
                continue  # finished mid-flight: overrun tokens discarded
            if bad is not None and bad[slot]:
                log.error("non-finite logits in decode chunk %d (slot %d); "
                          "failing the request, engine stays up",
                          chunk.seq, slot, extra=trace.log_extra(req.req_id))
                self.slot_tokens[slot] = []  # rows are poisoned: never reuse
                req.finish_reason = "error"  # before the put (client-visible)
                req.out.put(RuntimeError(
                    f"non-finite logits in decode chunk {chunk.seq}; "
                    "request failed (engine healthy)"))
                self._finish(req, "error")
                continue
            if chunk.spec and chunk.advance[slot]:
                # per-request acceptance record (timings()'s spec object):
                # cycles this request participated in, and tokens they gave
                req.spec_cycles += int((chunk.adv_cycles[:, slot] > 0).sum())
                req.spec_tokens += int(chunk.advance[slot])
            if tr.enabled and chunk.advance[slot]:
                # flight-recorder chunk entry BEFORE the tokens reach the
                # client queue: a response never races its own record
                tr.req_chunk(req.req_id, chunk.seq, int(chunk.advance[slot]))
            for i in range(int(chunk.advance[slot])):
                # row written when sampling token i: start + i (+1 = prefix len)
                if self._emit(req, toks[i, slot], int(chunk.start_pos[slot]) + i + 1):
                    break
        if tr.enabled:
            tr.span_at("emit.scan", t_emit, tr.now(), cat="decode",
                       track="scheduler", chunk=chunk.seq)

    def _loop(self) -> None:
        # end of the previous decode chunk (stall metric); instance attribute
        # so reset_latency_stats can rewind it from the caller's thread
        self._t_dec_end = None
        # anchor the time ledger (re-entrant across warm restarts): from
        # here until close(), every instant is billed to exactly one state
        self.ledger.start("idle")
        pending = None  # overlap mode: the dispatched-but-unconsumed chunk
        while not self._stop.is_set():
            self._heartbeat = time.monotonic()
            # scrape-visible view of the loop's state (set, not callbacks:
            # a dead scheduler's last values are a tombstone, never a
            # dangling closure keeping the engine alive)
            ins.QUEUE_DEPTH.set(self._queue_depth())
            ins.BUSY_SLOTS.set(len(self.slots))
            faults.fire("scheduler.loop")
            if pending is not None:
                # a chunk is in flight: keep the device busy by dispatching
                # its successor off the device-side carry BEFORE consuming —
                # the emit/EOS Python work below then runs concurrently with
                # device compute — unless boundary work needs the settled,
                # fully-consumed state first.
                if self._hybrid_on and not pending[0].spec:
                    # early commit + early admission start (ISSUE 12): a
                    # fully-pumped admission activates its slot NOW
                    # (blocking only on its own logits), and a queued
                    # arrival enters _inflight so its FIRST hybrid slice
                    # rides the very next successor dispatch — neither
                    # pays a full pipeline drain. Preemption and the other
                    # release-side boundary work still wait for settled
                    # state.
                    if self._inflight:
                        self._commit_ready_inflight()
                    if self._backlog or not self.pending.empty():
                        self.ledger.transition("admission")
                        self._admit_starts(boundary=False)
                nxt = (None if self._needs_boundary(pending[0])
                       else self._dispatch_chunk(pipeline_empty=False,
                                                 inflight=pending[0]))
                self._consume_chunk(*pending)
                pending = nxt
                continue
            t_boundary = time.monotonic()
            self.ledger.transition("admission")
            self._admit_starts()
            admitted = self._pump_admissions()
            # boundary scans below (cancels, deadlines, page starvation) are
            # admission-side work; this also bills the pump's open tail
            self.ledger.transition("admission")
            for slot, req in list(self.slots.items()):
                if req.cancelled.is_set():
                    self._finish(req, req.cancel_reason,
                                 keep_rows=int(self.engine.pos[slot]))
                elif (req.deadline_at is not None
                      and time.monotonic() >= req.deadline_at):
                    # per-request deadline: the stream ends cleanly at this
                    # chunk boundary with finish_reason="timeout"; the rows
                    # already emitted keep their prefix-cache value
                    trace.TRACER.event("request.timeout", cat="deadline",
                                       track="requests", req_id=req.req_id,
                                       where="decoding")
                    self._finish(req, "timeout",
                                 keep_rows=int(self.engine.pos[slot]))
                elif int(self.engine.pos[slot]) >= self.engine.seq_len:
                    self._finish(req, "length")
            if self.slots and hasattr(self.engine, "page_starved"):
                # paged pool exhaustion mid-decode: a starved slot (no page
                # for its next row, pool dry) waits frozen while batch-mates
                # run — their releases re-feed it. But when EVERY live slot
                # is starved nothing will ever free a page: finish the most-
                # advanced one with 'length' (least budget wasted) so its
                # pages unfreeze the rest. Admission reserves (+1 decode
                # page) make this a last resort, not the steady state.
                # the rescue must run even while an admission is mid-prefill
                # (_inflight): admissions only ADD page consumers, so waiting
                # on one can never un-starve the batch — and dispatching a
                # chunk with every slot at its limit would raise and crash
                # the worker instead
                starved = self.engine.page_starved()
                if starved.any() and all(
                    starved[s] for s in self.slots
                    if self.engine.active[s]
                ):
                    if self._reclaim_pages(len(self.slots)):
                        pass  # reclaimed idle caches; next dispatch tops up
                    else:
                        victim = max(
                            (s for s in self.slots if starved[s]),
                            key=lambda s: int(self.engine.pos[s]))
                        log.warning(
                            "kv page pool exhausted with every active slot "
                            "starved; finishing slot %d "
                            "(finish_reason=length) to free its pages",
                            victim)
                        self._finish(self.slots[victim], "length")
            if not self.slots:
                self._t_dec_end = None
                if not self._inflight:
                    self.ledger.transition("idle")
                    self._wake.wait(timeout=self.admit_timeout)
                    self._wake.clear()
                continue
            if admitted and self._t_dec_end is not None:
                # decode-to-decode gap attributable to admission work
                gap_ms = (time.monotonic() - self._t_dec_end) * 1000.0
                with self._metrics_lock:
                    self._admit_gaps_ms.append(gap_ms)
                    del self._admit_gaps_ms[:-256]
                ins.ADMISSION_STALL_SECONDS.observe(gap_ms / 1000.0)
            chunk = self._dispatch_chunk(
                exclude_gap_s=time.monotonic() - t_boundary)
            if self.overlap:
                pending = chunk
            else:
                self._consume_chunk(*chunk)
        # shutdown with work still in flight (drain timeout, hard stop): the
        # cut-off requests must surface as FAILURES to their clients — a bare
        # _END would read as a clean, complete generation (HTTP 200 with
        # silently truncated content). One path for all three places a client
        # can be parked: mid-admission, decoding, still queued.
        def cut(req: Request) -> None:
            # reason BEFORE the put: the client reads finish_reason as soon
            # as the exception lands — it must never observe None
            req.finish_reason = "shutdown"
            req.out.put(SchedulerDraining(
                "server shut down before this request completed"))
            self._finish(req, "shutdown")  # metrics ring + _END + slot release

        for req, adm, _ in self._inflight:
            self.slot_tokens[adm.slot] = []  # rows are mid-overwrite
            cut(req)
        self._inflight.clear()
        for req in list(self.slots.values()):
            cut(req)
        if self._deferred is not None:
            cut(self._deferred)
            self._deferred = None
        for req in self._recover:
            cut(req)
        self._recover = []
        for req in self._backlog:
            cut(req)
        self._backlog = []
        while True:
            try:
                cut(self.pending.get_nowait())
            except queue.Empty:
                break
