"""OpenAI-compatible HTTP API server — the `dllama-api` binary's role
(dllama-api.cpp:509-581).

Routes: POST /v1/chat/completions and the legacy POST /v1/completions (both
stream + non-stream), GET /v1/models, GET /health (+ /health/live,
/health/ready), GET /metrics (Prometheus text exposition of the process
registry — dllama_tpu/obs). Every POST mints (or adopts from an inbound
X-Request-Id) a per-request id `req_...`, propagated api -> scheduler ->
engine, returned on EVERY response (success, 4xx/5xx, SSE) as the
X-Request-Id header and attached to the request's log lines as the
structured `request_id` field. Request params override
the CLI defaults the way the reference's params do (dllama-api.cpp:455-484):
temperature, top_p, presence/frequency_penalty, seed, max_tokens, stop,
stream.

The **prefix cache** reproduces NaiveCache (dllama-api.cpp:264-309): the chat
history from the previous request is kept with its KV-cache position; when a
new request's messages extend the cached ones, only the delta is encoded and
prefilled — the engine rewinds to the cached position instead of replaying
the whole conversation. The continuous-batching tier has the same capability
per slot, at the token level, inside serve/scheduler.Scheduler.

Built on stdlib http.server (the reference hand-rolls HTTP/1.1 the same
spirit, dllama-api.cpp:104-179); requests are serialized with a lock because
one engine owns the KV cache — the reference is equally single-request
(blocking accept loop, dllama-api.cpp:522-533).
"""

from __future__ import annotations

import json
import logging
import select
import signal
import socket
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dllama_tpu import __version__
from dllama_tpu.engine.sampling import Sampler
from dllama_tpu.obs import metrics, new_request_id, trace
from dllama_tpu.obs import compile as compile_obs
from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import perf as perfmod
from dllama_tpu.utils import locks
from dllama_tpu.serve.scheduler import (
    QueueFull,
    SchedulerDraining,
    SchedulerRejected,
)
from dllama_tpu.tokenizer.chat import (
    ChatItem,
    ChatTemplate,
    ChatTemplateType,
    EosDetector,
    EosResult,
    chat_stops,
)

log = logging.getLogger("dllama_tpu.serve")

#: socket errors meaning "the client went away" — never worth a stack trace,
#: never answerable with an error response (the pipe is gone)
CLIENT_GONE = (BrokenPipeError, ConnectionResetError, ConnectionAbortedError,
               TimeoutError, socket.timeout)


class ClientDisconnected(Exception):
    """Raised inside a completion when the disconnect probe sees the client
    socket closed — generation is cancelled instead of running to completion
    into a dead socket."""


def _parse_timeout(body: dict) -> float | None:
    """Per-request deadline: `timeout_s` in the request body (do_POST also
    folds an `X-Request-Timeout` header into it). Seconds from submission
    until the request is ended with finish_reason="timeout" — expired-in-
    queue requests never prefill, running ones stop at the next chunk
    boundary. None/absent = no deadline."""
    v = body.get("timeout_s")
    if v is None:
        return None
    try:
        v = float(v)
    except (TypeError, ValueError):
        raise ApiError(400, "timeout_s must be a number of seconds") from None
    if not v > 0:
        raise ApiError(400, "timeout_s must be > 0")
    return v


def _parse_spec_k(body: dict) -> int | None:
    """Per-request speculation: `spec_k` in the request body — the draft
    length this request's slot runs at (0 disables speculation for this
    request even while batch-mates speculate; values above the serving
    --spec-k capacity clamp down to it; greedy output is bit-identical
    either way). None/absent = the CLI default."""
    v = body.get("spec_k")
    if v is None:
        return None
    try:
        v = int(v)
    except (TypeError, ValueError):
        raise ApiError(400, "spec_k must be an integer >= 0") from None
    if v < 0:
        raise ApiError(400, "spec_k must be an integer >= 0")
    return v


#: named priority classes the `priority` body field accepts alongside raw
#: integers (0=low, 1=normal, 2=high) — the scheduler picks strictly
#: between classes and may preempt a lower class for a higher one
PRIORITY_NAMES = {"low": 0, "normal": 1, "high": 2}


def _parse_priority(body: dict) -> int:
    """Scheduling class: `priority` in the request body — 0/'low',
    1/'normal' (the default), 2/'high'. Higher classes admit strictly
    first and (with --preempt) may suspend a running lower-class request
    at a chunk boundary; the suspended stream resumes byte-identical."""
    v = body.get("priority")
    if v is None:
        return 1
    if isinstance(v, str):
        if v not in PRIORITY_NAMES:
            raise ApiError(400, "priority must be an integer 0..2 or one of "
                                "low|normal|high")
        return PRIORITY_NAMES[v]
    try:
        v = int(v)
    except (TypeError, ValueError):
        raise ApiError(400, "priority must be an integer 0..2 or one of "
                            "low|normal|high") from None
    if not 0 <= v <= 2:
        raise ApiError(400, "priority must be an integer 0..2 or one of "
                            "low|normal|high")
    return v


def _parse_tenant(body: dict) -> str:
    """Fair-queueing key: `tenant` in the request body — requests of the
    same tenant share one weighted-fair-queue lane at admission ("" =
    the anonymous shared tenant; weights via --tenant-weight)."""
    v = body.get("tenant")
    if v is None:
        return ""
    if not isinstance(v, str) or len(v) > 64:
        raise ApiError(400, "tenant must be a string of at most 64 chars")
    return v


@dataclass
class PrefixCache:
    """NaiveCache equivalent: remember the last conversation's messages and
    the KV position right after them."""

    messages: list[tuple[str, str]] = field(default_factory=list)
    pos: int = 0
    bos_sent: bool = False

    def resolve(self, incoming: list[tuple[str, str]]) -> tuple[list[tuple[str, str]], int, bool]:
        """-> (delta_messages, start_pos, add_bos). Matches whole-message
        prefixes only, like resolveDeltaPrompt (dllama-api.cpp:286-308)."""
        n = len(self.messages)
        if n and len(incoming) > n and incoming[:n] == self.messages:
            return incoming[n:], self.pos, False
        return incoming, 0, True

    def clear(self) -> None:
        self.messages = []
        self.pos = 0
        self.bos_sent = False


class TokenAssembler:
    """Per-stream EOS/stop-string assembly of a batched token stream — the
    detector + incremental decoder + held-prefix bookkeeping that used to
    live inline in ``_run_batched``, extracted so the blocking tier and the
    aio front-end's cooperative SSE pump (serve/aio.py) process tokens
    identically (byte-identical text deltas either way)."""

    __slots__ = ("detector", "decoder", "parts", "n", "eos", "pending_ids",
                 "taken")

    def __init__(self, tokenizer, stops):
        self.detector = EosDetector(tokenizer.eos_ids, stops,
                                    padding_left=2, padding_right=2)
        self.decoder = tokenizer.make_stream_decoder()
        self.parts: list[str] = []
        self.n = 0
        self.eos = False
        # token-id journal feed (ISSUE 16): raw ids fed since the last
        # take_ids(), and the count already taken — the (position, ids)
        # pairs SSE frames carry so the router can journal resume state
        self.pending_ids: list[int] = []
        self.taken = 0

    def feed(self, t) -> str:
        """Process one token -> the text delta to emit now ("" while the
        detector holds a possible stop prefix). Sets ``eos`` when the
        token completed an EOS/stop sequence."""
        self.n += 1
        self.pending_ids.append(int(t))
        res = self.detector.append(t, self.decoder.decode(t))
        text = self.detector.get_delta()
        if text:
            self.parts.append(text)
        if res == EosResult.EOS:
            self.eos = True
        return text

    def take_ids(self) -> tuple[int, list[int]]:
        """Drain the pending raw ids for the frame about to go out:
        ``(position, ids)`` where ``position`` counts the ids taken by all
        PRIOR frames — a journaling router appends exactly when position
        matches its journal length, which makes duplicate frames after a
        failover self-suppressing. Ids held with a stop-prefix ride the
        NEXT emitted frame (frames and the text they carry stay atomic)."""
        pos, ids = self.taken, self.pending_ids
        self.taken += len(ids)
        self.pending_ids = []
        return pos, ids

    def flush(self) -> str:
        """End of stream without EOS (budget/timeout): release any held
        stop-prefix -> the final text delta to emit."""
        text = self.detector.flush()
        if text:
            self.parts.append(text)
        return text

    def content(self) -> str:
        return "".join(self.parts)


class ApiServer:
    def __init__(self, loaded, default_temperature=0.8, default_topp=0.9, default_seed=None,
                 scheduler=None, spec: int = 0,
                 slo_ttft_ms: float | None = None,
                 slo_itl_ms: float | None = None,
                 replica_id: str = "",
                 sse_heartbeat_s: float = 0.0):
        self.engine = loaded.engine
        self.tokenizer = loaded.tokenizer
        self.config = loaded.config
        self.template = ChatTemplate(
            ChatTemplateType.UNKNOWN, self.tokenizer.chat_template, ""
        )
        self.stops = chat_stops(self.tokenizer)
        self.defaults = dict(
            temperature=default_temperature, topp=default_topp, seed=default_seed
        )
        self.cache = PrefixCache()
        # multi-replica attribution (ISSUE 15): stamped on every response as
        # the X-Replica-Id header and the `replica` field of `timings`, so a
        # stream that crossed the router is attributable end to end. "" =
        # standalone (no header, no field); make_server defaults it to
        # host:port of the bound socket.
        self.replica_id = str(replica_id or "")
        # SSE keep-alive cadence (ISSUE 15): idle streams emit a `: keep-alive`
        # comment frame at this period so router/LB idle timeouts cannot kill
        # a slow-decode stream; 0 = off
        self.sse_heartbeat_s = float(sse_heartbeat_s or 0.0)
        # prompt-lookup speculative decoding for greedy single-engine serving
        # (generate() ignores it for sampled requests and the batched tier)
        self.spec = int(spec)
        self.lock = locks.make_lock("api.single")
        self.model_name = "dllama-tpu"
        # continuous-batching tier: a serve/scheduler.Scheduler over a
        # BatchEngine — concurrent requests share the device, no global lock
        self.scheduler = scheduler
        # flipped by the SIGTERM drain sequence: new requests get 503 while
        # in-flight ones finish (single-engine tier included — the scheduler
        # has its own draining flag for its admission queue)
        self.draining = False
        # startup HBM gauges (model_params_bytes / kv_cache_bytes): account
        # the engine that actually serves — the BatchEngine owns the slot
        # cache on the continuous tier, loaded.engine on the single tier
        from dllama_tpu.utils.profiling import set_memory_gauges

        eng = scheduler.engine if scheduler is not None else self.engine
        self.model_params_bytes, self.kv_cache_bytes = set_memory_gauges(
            eng.params, eng.cache)
        # build-info gauge (value always 1; the labels ARE the payload): what
        # exactly is serving — package + jax versions, backend platform, and
        # whether the overlapped pipeline is live. Also embedded in /health
        # so a probe answers "what is this replica running" without a scrape.
        import jax

        self.build_info = {
            "version": __version__,
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "overlap": ("n/a" if scheduler is None
                        else ("on" if scheduler.overlap else "off")),
            # boot precompile state (ISSUE 13): whether this replica warmed
            # its compiled-shape universe before taking traffic
            "warmup": ("n/a" if scheduler is None
                       else getattr(scheduler, "warmup", "off")),
        }
        ins.BUILD_INFO.labels(**self.build_info).set(1)
        # SLO policy for the /debug/requests/{req_id} postmortem verdict —
        # ONE policy object per process: the scheduler's aggregator owns it
        # on the continuous tier (it also burns the violation counters), the
        # api holds a standalone one on the single tier so postmortems still
        # get judged
        self.slo = (scheduler.perf.slo if scheduler is not None
                    else perfmod.SloPolicy(
                        None if slo_ttft_ms is None else float(slo_ttft_ms),
                        None if slo_itl_ms is None else float(slo_itl_ms)))

    # ---------------------------------------------------------------- health

    def health(self) -> dict:
        """Liveness/readiness payload for GET /health (and the /health/live,
        /health/ready sub-probes). The continuous-batching tier forwards the
        scheduler's supervision snapshot; the single-engine tier is live as
        long as the process answers."""
        if self.scheduler is not None:
            h = self.scheduler.health()
        else:
            h = {"live": True, "ready": True, "queue_depth": 0,
                 "busy_slots": 0, "n_slots": 0, "last_step_age_s": 0.0,
                 # compile observability rides the single tier's probe too
                 # (no warmup pass there — the batched scheduler owns it)
                 "compile": {
                     "warmup": "n/a",
                     "compiles": compile_obs.LEDGER.total_compiles(),
                     "unexpected_compiles":
                         compile_obs.LEDGER.total_unexpected(),
                 }}
        if self.draining:
            h["ready"] = False
            h["draining"] = True
        h["status"] = "ok" if h["live"] else "unhealthy"
        h["mode"] = "continuous" if self.scheduler is not None else "single"
        # HBM accounting rides the ready payload (and /metrics as gauges) so
        # capacity questions don't need a restart with --report
        h["model_params_bytes"] = self.model_params_bytes
        h["kv_cache_bytes"] = self.kv_cache_bytes
        h["build"] = self.build_info
        # process self-metrics ride every probe (and /metrics as gauges):
        # uptime answers "did it just restart", RSS + threads answer "is it
        # leaking" without a scrape pipeline
        h["process"] = ins.refresh_process_gauges()
        # NTP-lite clock payload (ISSUE 17): our monotonic clock at answer
        # time is the router's offset sample; the tracer epoch lets it map
        # our Chrome-export timestamps onto the mesh timeline
        h["clock"] = {"monotonic_s": time.monotonic(),
                      "trace_epoch_s": getattr(trace.TRACER, "epoch", None)}
        return h

    def precheck_capacity(self) -> None:
        """Raise the admission-control rejection a submit() would raise,
        WITHOUT submitting. Streaming handlers call this before the
        200/chunked headers go out, so an overloaded/draining server sheds
        stream requests with a clean 429/503 instead of a corrupted stream."""
        if self.draining:
            ins.REQUESTS_SHED.labels(reason="draining").inc()
            raise SchedulerDraining("server is draining")
        if self.scheduler is not None:
            self.scheduler.check_admission()

    # ------------------------------------------------------------------ core

    def complete(self, body: dict, emit=None, probe=None, req_id: str = "") -> dict:
        """Run one chat completion. `emit(text)` streams deltas when given.
        `probe()` (optional) returns True when the client socket is gone —
        polled during batched generation so a disconnected non-streaming
        client cancels its scheduler request instead of generating to
        completion into a dead socket. `req_id` tags the scheduler request
        (and thus the admission/finish log lines) with the HTTP request id.
        Returns the non-streaming response dict (also computed when
        streaming, for the final usage accounting)."""
        t_submit = time.monotonic()
        if self.scheduler is not None:
            # continuous-batching tier: one shared body parse (the same one
            # the aio front-end's SSE machine uses), then the blocking
            # submit/stream/finish loop
            p = self.prepare_request(body, legacy=False)
            content, finish, n_generated, timings = self._run_batched(
                p, emit, probe=probe, req_id=req_id)
            return {
                "timings": timings,
                "id": f"chatcmpl-{uuid.uuid4().hex[:16]}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": body.get("model", self.model_name),
                "choices": [
                    {
                        "index": 0,
                        "message": {"role": "assistant", "content": content},
                        "finish_reason": finish,
                    }
                ],
                "usage": {
                    "prompt_tokens": len(p["prompt_tokens"]),
                    "completion_tokens": n_generated,
                    "total_tokens": len(p["prompt_tokens"]) + n_generated,
                },
            }

        if body.get("resume") is not None:
            raise ApiError(400, "resume requires the batched scheduler tier")
        messages = [(m["role"], str(m["content"])) for m in body.get("messages", [])]
        if not messages:
            raise ApiError(400, "messages must be a non-empty array")
        temperature = float(body.get("temperature", self.defaults["temperature"]))
        topp = float(body.get("top_p", self.defaults["topp"]))
        # `or 0.0`: OpenAI treats an explicit JSON null as "use default"
        presence = float(body.get("presence_penalty") or 0.0)
        frequency = float(body.get("frequency_penalty") or 0.0)
        seed = body.get("seed", self.defaults["seed"])
        max_tokens = int(body.get("max_tokens") or body.get("max_completion_tokens") or 0)
        timeout_s = _parse_timeout(body)
        spec_k = _parse_spec_k(body)
        _parse_priority(body)  # accepted-but-inert on this tier: validate only
        _parse_tenant(body)
        extra_stops = body.get("stop") or []
        if isinstance(extra_stops, str):
            extra_stops = [extra_stops]

        self._trace_single_submit(req_id, t_submit)
        with self.lock:
            t_admit = time.monotonic()
            delta, start_pos, add_bos = self.cache.resolve(messages)
            if start_pos == 0:
                self.cache.clear()
            self.engine.reset(start_pos)
            generated = self.template.generate(
                [ChatItem(r, c) for r, c in delta], append_generation_prompt=True
            )
            prompt_tokens = self.tokenizer.encode(generated.content, add_bos=add_bos)
            budget, sampler = self._budget_and_sampler(
                len(prompt_tokens), max_tokens, temperature, topp, seed,
                presence, frequency)
            content, finish, n_generated, t_first = self._run_single(
                prompt_tokens, budget, sampler,
                self.stops + list(extra_stops), emit, probe=probe,
                deadline=None if timeout_s is None else t_submit + timeout_s,
                spec_k=spec_k)
            if finish == "timeout" and n_generated == 0:
                # expired on the engine lock: _run_single returned before
                # ANY engine work, so the pre-call cache state is still the
                # truth — recording the new conversation here would claim KV
                # rows that were never prefilled and make the next turn
                # resolve past a user message the model never saw
                pass
            else:
                # cache the full conversation incl. the reply for the next turn
                self.cache.messages = messages + [("assistant", content)]
                self.cache.pos = self.engine.pos
                self.cache.bos_sent = True
        timings = self._single_tier_timings(
            req_id, t_submit, t_admit, t_first, n_generated,
            len(prompt_tokens), start_pos, finish, timeout_s=timeout_s)

        return {
            "timings": timings,
            "id": f"chatcmpl-{uuid.uuid4().hex[:16]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": body.get("model", self.model_name),
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": content},
                    "finish_reason": finish,
                }
            ],
            "usage": {
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": n_generated,
                "total_tokens": len(prompt_tokens) + n_generated,
            },
        }

    @staticmethod
    def _normalize_legacy_prompt(body: dict) -> str:
        """The legacy endpoint's prompt field: a string or a 1-element list
        of strings. One definition serves prevalidate and complete_legacy."""
        prompt = body.get("prompt")
        if isinstance(prompt, list):
            if len(prompt) != 1:
                raise ApiError(400, "only a single prompt is supported")
            prompt = prompt[0]
        if not isinstance(prompt, str) or not prompt:
            raise ApiError(400, "prompt must be a non-empty string")
        return prompt

    def prevalidate(self, body: dict, legacy: bool = False) -> None:
        """Raise ApiError for request-shape problems that can be detected
        without touching the engine (used before streaming headers are
        sent — a failure after the 200/chunked headers would corrupt the
        stream). Deeper failures (context window) still surface as HTTP 4xx
        on the non-streaming path."""
        _parse_timeout(body)  # a malformed timeout_s is a clean 400 too
        _parse_spec_k(body)  # ...and a malformed spec_k
        _parse_priority(body)  # ...and a malformed priority
        _parse_tenant(body)  # ...and a malformed tenant
        if legacy:
            self._normalize_legacy_prompt(body)
            return
        messages = body.get("messages")
        if (not isinstance(messages, list) or not messages
                or not all(isinstance(m, dict) and "role" in m and "content" in m
                           for m in messages)):
            raise ApiError(400, "messages must be a non-empty array of "
                                "{role, content} objects")

    def _budget_and_sampler(self, prompt_len, max_tokens, temperature, topp,
                            seed, presence, frequency):
        """Shared single-engine budget clamp + Sampler construction (the
        seed-or-wallclock fallback must never diverge between endpoints)."""
        budget = self.engine.seq_len - self.engine.pos - prompt_len - 1
        if budget <= 0:
            raise ApiError(400, "context window exhausted")
        if max_tokens > 0:
            budget = min(budget, max_tokens)
        sampler = Sampler(temperature, topp,
                          seed if seed is not None else int(time.time()),
                          presence=presence, frequency=frequency)
        return budget, sampler

    @staticmethod
    def _trace_single_submit(req_id: str, t_submit: float) -> None:
        """Single-engine tier flight-recorder entry: on this tier the
        'queue' is the global engine lock, so submit is the handler entry
        (the batched tier records through the scheduler instead)."""
        tr = trace.TRACER
        if tr.enabled and req_id:
            tr.req_submit(req_id, t=t_submit)

    def _single_tier_timings(self, req_id, t_submit, t_admit, t_first,
                             n_generated, prompt_len, reused, finish,
                             timeout_s=None) -> dict:
        """Build the response `timings` object for a single-engine completion
        and close out its flight-recorder record (lock wait plays the role
        of queue wait; prefill has no separate mark on this tier — TTFT
        covers it). Deadline fields mirror the batched tier's
        Request.timings(): present whenever the request carried a deadline,
        so clients keying on `deadline_exceeded` behave the same on both
        serving tiers."""
        t_done = time.monotonic()
        timings = {
            "queue_wait_ms": round((t_admit - t_submit) * 1000.0, 3),
            "ttft_ms": (None if t_first is None
                        else round((t_first - t_submit) * 1000.0, 3)),
            "e2e_ms": round((t_done - t_submit) * 1000.0, 3),
            "decode_tokens": n_generated,
        }
        if timeout_s is not None:
            timings["timeout_s"] = timeout_s
            timings["deadline_exceeded"] = finish == "timeout"
        if self.replica_id:
            timings["replica"] = self.replica_id
        tr = trace.TRACER
        if tr.enabled and req_id:
            tr.req_admitted(req_id, t=t_admit)
            tr.req_mark(req_id, prompt_tokens=prompt_len,
                        reused_tokens=reused)
            if t_first is not None:
                tr.req_first_token(req_id, t=t_first)
            if finish == "timeout":
                # same postmortem breadcrumb the scheduler leaves: on this
                # tier "queued" means the deadline expired on the lock wait
                tr.event("request.timeout", cat="deadline", track="requests",
                         req_id=req_id,
                         where="queued" if n_generated == 0 else "decoding")
            tr.req_end(req_id, finish, t=t_done, **timings)
        return timings

    def _run_single(self, prompt_tokens, budget, sampler, stops, emit,
                    probe=None, deadline=None,
                    spec_k=None) -> tuple[str, str, int, float | None]:
        """Token loop of a single-engine completion (generate + EOS/stop
        detection + held-prefix flush) -> (content, finish_reason, n_tokens,
        first_token_monotonic_or_None — the TTFT mark of the `timings`
        response object).
        Shared by the chat and legacy endpoints — caller holds self.lock and
        has positioned the engine. `probe` (dead-client check) aborts the
        generation via ClientDisconnected — on THIS tier a dead request
        holds the global engine lock, so cancelling it unblocks every other
        client, not just a slot. The engine is left mid-generation; the next
        request's reset()/prefix-cache miss rewrites those rows."""
        if deadline is not None and time.monotonic() >= deadline:
            # expired while waiting on the engine lock (this tier's
            # "queue"): return before ANY engine work — no prefill, no
            # decode — matching the batched tier's expired-in-queue shed
            return "", "timeout", 0, None
        detector = EosDetector(self.tokenizer.eos_ids, stops,
                               padding_left=2, padding_right=2)
        self.tokenizer.reset_decoder()
        parts: list[str] = []
        n_generated = 0
        finish = "length"
        t_first = None
        timed_out = False
        probe_at = time.monotonic() + 0.25
        # per-request spec_k on this tier clamps to the CLI --spec
        # capacity, same contract as the batched tier (the engine caches
        # one compiled decoder per distinct k, bounded by --spec values)
        spec = self.spec if spec_k is None else min(int(spec_k), self.spec)
        for t in self.engine.generate(prompt_tokens, budget, sampler,
                                      spec=spec):
            if t_first is None:
                t_first = time.monotonic()
            if probe is not None and time.monotonic() >= probe_at:
                probe_at = time.monotonic() + 0.25
                if probe():
                    raise ClientDisconnected()
            n_generated += 1
            res = detector.append(t, self.tokenizer.decode(t))
            text = detector.get_delta()
            if text:
                parts.append(text)
                if emit is not None:
                    emit(text)
            if res == EosResult.EOS:
                finish = "stop"
                break
            if deadline is not None and time.monotonic() >= deadline:
                # per-request deadline on the single-engine tier: the lock
                # wait (this tier's "queue") counts toward it — a clean
                # terminal finish, never an error
                finish = "timeout"
                timed_out = True
                break
        else:
            # budget exhausted mid-held-prefix: the partial stop never completes
            text = detector.flush()
            if text:
                parts.append(text)
                if emit is not None:
                    emit(text)
        if timed_out:
            # flush any held stop-prefix like the budget path: what was
            # generated is delivered, just cut short
            text = detector.flush()
            if text:
                parts.append(text)
                if emit is not None:
                    emit(text)
        return "".join(parts), finish, n_generated, t_first

    def prepare_request(self, body: dict, legacy: bool = False) -> dict:
        """Parse a completions body into submit-ready params — ONE parser
        for the blocking batched tier and the aio front-end's SSE machine
        (serve/aio.py), so the two can never drift. Raises ApiError for
        shape problems; stream callers therefore run it BEFORE response
        headers go out. Returns the kwargs of :meth:`batched_submit` plus
        ``stops`` (chat adds the template stops; the legacy raw-prompt
        endpoint uses only explicit ones)."""
        temperature = float(body.get("temperature", self.defaults["temperature"]))
        topp = float(body.get("top_p", self.defaults["topp"]))
        # `or 0.0`: OpenAI treats an explicit JSON null as "use default"
        presence = float(body.get("presence_penalty") or 0.0)
        frequency = float(body.get("frequency_penalty") or 0.0)
        seed = body.get("seed", self.defaults["seed"])
        timeout_s = _parse_timeout(body)
        spec_k = _parse_spec_k(body)
        priority = _parse_priority(body)
        tenant = _parse_tenant(body)
        extra_stops = body.get("stop") or []
        if isinstance(extra_stops, str):
            extra_stops = [extra_stops]
        if legacy:
            prompt = self._normalize_legacy_prompt(body)
            prompt_tokens = self.tokenizer.encode(prompt, add_bos=True)
            stops = list(extra_stops)
            max_tokens = int(body.get("max_tokens") or 16)  # OpenAI legacy default
        else:
            messages = [(m["role"], str(m["content"]))
                        for m in body.get("messages", [])]
            if not messages:
                raise ApiError(400, "messages must be a non-empty array")
            generated = self.template.generate(
                [ChatItem(r, c) for r, c in messages],
                append_generation_prompt=True)
            prompt_tokens = self.tokenizer.encode(generated.content,
                                                  add_bos=True)
            stops = self.stops + list(extra_stops)
            max_tokens = int(body.get("max_tokens")
                             or body.get("max_completion_tokens") or 0)
        # mid-stream failover support (ISSUE 16): `include_token_ids` makes
        # every SSE frame carry the raw (position, token_ids) it consumed
        # (the router injects it so it can journal resume state); `resume`
        # re-enters a journaled stream on THIS replica — the emitted prefix
        # re-prefills via the radix/resume_commit path and the PRNG chain is
        # replayed from the request seed, so the continuation is bit-exact
        resume = body.get("resume")
        resume_tokens = resume_id = resume_created = None
        if resume is not None:
            if not isinstance(resume, dict):
                raise ApiError(400, "resume must be an object")
            # EMPTY tokens is legal: a stream that died after its role
            # delta but before any token resumes with tokens=[] purely to
            # keep its id/created and suppress the duplicate role delta
            toks = resume.get("tokens")
            if (not isinstance(toks, list)
                    or not all(isinstance(t, int) for t in toks)):
                raise ApiError(400, "resume.tokens must be an int array")
            if temperature > 0.0 and seed is None:
                # an unseeded sampled stream has no replayable key chain —
                # the router pins a seed at first proxy precisely so its
                # journal stays resumable; reject rather than silently
                # diverge from the already-emitted prefix
                raise ApiError(
                    400, "sampled resume requires the original seed")
            resume_tokens = [int(t) for t in toks]
            resume_id = str(resume.get("id") or "")
            resume_created = int(resume.get("created") or 0)
        return dict(prompt_tokens=prompt_tokens, stops=stops,
                    temperature=temperature, topp=topp,
                    max_tokens=max_tokens, seed=seed, presence=presence,
                    frequency=frequency, timeout_s=timeout_s, spec_k=spec_k,
                    priority=priority, tenant=tenant,
                    token_ids=bool(body.get("include_token_ids")),
                    resume_tokens=resume_tokens, resume_id=resume_id,
                    resume_created=resume_created)

    def batched_submit(self, p: dict, req_id: str = ""):
        """Budget-clamp + submit one parsed request (prepare_request's dict)
        to the scheduler -> the live Request. Shared by the blocking tier
        and the aio SSE machine; raises ApiError when the context window
        cannot fit the prompt, and the SchedulerRejected family on
        admission shed."""
        prompt_tokens = p["prompt_tokens"]
        budget = self.scheduler.engine.seq_len - len(prompt_tokens) - 1
        if budget <= 0:
            raise ApiError(400, "context window exhausted")
        if p["max_tokens"] > 0:
            budget = min(budget, p["max_tokens"])
        seed = p["seed"]
        return self.scheduler.submit(
            prompt_tokens, p["temperature"], p["topp"], budget,
            self.tokenizer.eos_ids,
            presence=p["presence"], frequency=p["frequency"],
            seed=int(seed) if seed is not None else None,
            req_id=req_id, timeout_s=p["timeout_s"],
            # None = the --spec-k serving default (the engine's compiled K);
            # the scheduler clamps explicit values to that capacity
            spec_k=p["spec_k"],
            # scheduling class + fair-queue tenant (ISSUE 12): the
            # scheduler's policy pick and preemption read these
            priority=p["priority"], tenant=p["tenant"],
            # cross-replica failover (ISSUE 16): the journaled emitted
            # prefix to re-prefill before the stream continues
            resume_tokens=p.get("resume_tokens"),
        )

    def finish_batched(self, req, ended_on_eos: bool,
                       n_generated: int) -> tuple[str, dict]:
        """Release a batched request's slot and derive the client-facing
        (finish_reason, timings) pair — the one finalization site for the
        blocking tier and the aio SSE machine. A release after the detector
        saw a string stop-sequence is a SUCCESSFUL stop, not a client
        cancellation — labeled so the finished{reason} metric matches what
        the client is told."""
        self.scheduler.cancel(
            req, reason="stop" if ended_on_eos else "cancelled")
        # scheduler reasons: stop/length/timeout pass through; a cancel here
        # means the stream ended on a string stop-sequence -> "stop"
        finish = (req.finish_reason
                  if req.finish_reason in ("stop", "length", "timeout")
                  else "stop")
        timings = req.timings()
        if timings["e2e_ms"] is None:
            # a stop-string release is finalized asynchronously by the worker;
            # from the client's seat the request is over NOW
            timings["e2e_ms"] = round(
                (time.monotonic() - req.submitted_at) * 1000.0, 3)
        # what the CLIENT received — the scheduler's `produced` may include
        # a stop-string overrun token the stream never surfaced
        timings["decode_tokens"] = n_generated
        if self.replica_id:
            # end-to-end attribution through the router (ISSUE 15): which
            # replica actually served this stream
            timings["replica"] = self.replica_id
        return finish, timings

    def _run_batched(self, p: dict, emit, probe=None,
                     req_id: str = "") -> tuple[str, str, int, dict]:
        """Token-level core of a BLOCKING batched completion: submit, stream-
        decode with EOS/stop detection, return (content, finish_reason,
        n_tokens, timings) — `timings` is the request's span-sourced latency
        object (queue wait / TTFT / e2e / token count) for the response
        body. `p` is prepare_request's dict. The aio front-end runs the same
        submit/assemble/finish seams cooperatively instead (serve/aio.py)."""
        asm = TokenAssembler(self.tokenizer, p["stops"])
        want_ids = bool(p.get("token_ids"))
        resume = p.get("resume_tokens")
        if resume:
            # failover re-entry (ISSUE 16): replay the journaled prefix
            # through a FRESH assembler so the stop detector / incremental
            # decoder reach the exact state the dead replica held — without
            # re-emitting anything (those deltas already reached the
            # client; the journal records only relayed frames). The
            # take_ids() drain keeps the position counter continuous, so
            # the continuation's first frame carries position = len(resume).
            for t in resume:
                asm.feed(t)
                if asm.eos:
                    break
            asm.take_ids()
            if asm.eos:
                # the journaled tokens already complete a stop sequence
                # (the replica died between the stop-completing frame and
                # its finish frame): the stream is over — finish now, no
                # engine work left
                timings: dict = {"e2e_ms": 0.0, "decode_tokens": 0}
                if self.replica_id:
                    timings["replica"] = self.replica_id
                return asm.content(), "stop", asm.n, timings
        req = self.batched_submit(p, req_id=req_id)
        probe_at = time.monotonic() + 0.25

        def probe_tick():
            # runs from tokens() whenever the stream goes quiet (queued,
            # mid-prefill, stalled device): a dead client cancels even
            # before its first token exists
            if probe():
                raise ClientDisconnected()

        try:
            for t in req.tokens(poll=probe_tick if probe is not None else None):
                if probe is not None and time.monotonic() >= probe_at:
                    # ...and at 4 Hz while tokens ARE flowing (a select()+
                    # MSG_PEEK syscall per token would dominate small models;
                    # this bounds wasted generation to a quarter second)
                    probe_at = time.monotonic() + 0.25
                    if probe():
                        raise ClientDisconnected()
                text = asm.feed(t)
                if text and emit is not None:
                    if want_ids:
                        emit(text, ids=asm.take_ids())
                    else:
                        emit(text)
                if asm.eos:
                    break
            if not asm.eos:
                text = asm.flush()
                if text and emit is not None:
                    if want_ids:
                        emit(text, ids=asm.take_ids())
                    else:
                        emit(text)
            finish, timings = self.finish_batched(req, asm.eos, asm.n)
        except BaseException:
            # disconnect/shed/crash: the slot must still be released, with
            # the honest "cancelled"/terminal reason (finish_batched's
            # labeling only applies to streams that ended cleanly)
            self.scheduler.cancel(
                req, reason="stop" if asm.eos else "cancelled")
            raise
        return asm.content(), finish, asm.n, timings

    def complete_legacy(self, body: dict, emit=None, probe=None,
                        req_id: str = "") -> dict:
        """POST /v1/completions — the pre-chat OpenAI surface some clients
        still speak: a RAW prompt string, no chat template, `text` in the
        choices. Shares the sampling params and generation machinery with
        the chat endpoint."""
        t_submit = time.monotonic()
        if self.scheduler is not None:
            # continuous-batching tier: one shared body parse (the same one
            # the aio SSE machine uses) — no duplicate prompt tokenization
            p = self.prepare_request(body, legacy=True)
            prompt_tokens = p["prompt_tokens"]
            content, finish, n_generated, timings = self._run_batched(
                p, emit, probe=probe, req_id=req_id)
        else:
            if body.get("resume") is not None:
                raise ApiError(
                    400, "resume requires the batched scheduler tier")
            prompt = self._normalize_legacy_prompt(body)
            temperature = float(body.get("temperature",
                                         self.defaults["temperature"]))
            topp = float(body.get("top_p", self.defaults["topp"]))
            presence = float(body.get("presence_penalty") or 0.0)
            frequency = float(body.get("frequency_penalty") or 0.0)
            seed = body.get("seed", self.defaults["seed"])
            max_tokens = int(body.get("max_tokens") or 16)  # legacy default
            timeout_s = _parse_timeout(body)
            spec_k = _parse_spec_k(body)
            _parse_priority(body)  # accepted-but-inert: validate only
            _parse_tenant(body)
            extra_stops = body.get("stop") or []
            if isinstance(extra_stops, str):
                extra_stops = [extra_stops]
            prompt_tokens = self.tokenizer.encode(prompt, add_bos=True)
            self._trace_single_submit(req_id, t_submit)
            with self.lock:
                t_admit = time.monotonic()
                # raw-prompt rows overwrite the chat prefix cache's claim
                self.cache.clear()
                self.engine.reset(0)
                budget, sampler = self._budget_and_sampler(
                    len(prompt_tokens), max_tokens, temperature, topp, seed,
                    presence, frequency)
                # legacy endpoint: no chat stop strings, only explicit ones
                content, finish, n_generated, t_first = self._run_single(
                    prompt_tokens, budget, sampler, list(extra_stops), emit,
                    probe=probe,
                    deadline=(None if timeout_s is None
                              else t_submit + timeout_s),
                    spec_k=spec_k)
            timings = self._single_tier_timings(
                req_id, t_submit, t_admit, t_first, n_generated,
                len(prompt_tokens), 0, finish, timeout_s=timeout_s)

        return {
            "timings": timings,
            "id": f"cmpl-{uuid.uuid4().hex[:16]}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": body.get("model", self.model_name),
            "choices": [
                {"index": 0, "text": content, "logprobs": None,
                 "finish_reason": finish}
            ],
            "usage": {
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": n_generated,
                "total_tokens": len(prompt_tokens) + n_generated,
            },
        }

    def models(self) -> dict:
        return {
            "object": "list",
            "data": [
                {
                    "id": self.model_name,
                    "object": "model",
                    "created": int(time.time()),
                    "owned_by": "dllama-tpu",
                }
            ],
        }


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


#: path -> bounded-cardinality endpoint label for the HTTP response counter
_KNOWN_PATHS = {
    "/v1/chat/completions": "/v1/chat/completions",
    "/chat/completions": "/v1/chat/completions",
    "/v1/completions": "/v1/completions",
    "/completions": "/v1/completions",
    "/v1/models": "/v1/models",
    "/health": "/health",
    "/health/live": "/health/live",
    "/health/ready": "/health/ready",
    "/metrics": "/metrics",
    "/debug/trace": "/debug/trace",
    "/debug/requests": "/debug/requests",
    "/debug/profile": "/debug/profile",
    "/debug/kv": "/debug/kv",
    "/debug/perf": "/debug/perf",
    "/debug/radix": "/debug/radix",
    "/debug/compile": "/debug/compile",
}


def _endpoint(path: str) -> str:
    """Label-safe endpoint name (unknown paths collapse to 'other' so a
    scanner can't explode the label cardinality; per-request flight-recorder
    lookups collapse their req_id for the same reason)."""
    if path.startswith("/debug/requests/"):
        return "/debug/requests/{req_id}"
    return _KNOWN_PATHS.get(path, "other")


#: SSE comment frame (spec: lines starting with ':' are ignored by
#: EventSource parsers) — the keep-alive heartbeat idle streams emit so a
#: router/LB idle timeout cannot kill a slow-decode stream (ISSUE 15)
SSE_HEARTBEAT = b": keep-alive\n\n"


def sse_chat_payload(cid: str, created: int, model: str, delta: dict,
                     finish=None, timings=None, ids=None) -> bytes:
    """One `chat.completion.chunk` SSE data frame — single definition for
    the blocking `_stream` and the aio SSE machine (byte-identical events
    on both front-ends). ``ids`` (``include_token_ids`` requests only) is
    TokenAssembler.take_ids()'s ``(position, token_ids)`` — the raw ids
    this frame's text consumed plus their stream offset, which is what the
    router journals for mid-stream failover (ISSUE 16)."""
    data = {
        "id": cid,
        "object": "chat.completion.chunk",
        "created": created,
        "model": model,
        "choices": [{"index": 0, "delta": delta, "finish_reason": finish}],
    }
    if ids is not None:
        data["position"], data["token_ids"] = ids[0], list(ids[1])
    if timings is not None:
        # the final (done) event carries the request's span-sourced
        # latency summary, like the non-stream response body
        data["timings"] = timings
    return b"data: " + json.dumps(data).encode() + b"\n\n"


def sse_text_payload(cid: str, created: int, model: str, text: str,
                     finish=None, timings=None, ids=None) -> bytes:
    """One legacy `text_completion` SSE data frame (see sse_chat_payload)."""
    data = {
        "id": cid,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [{"index": 0, "text": text, "finish_reason": finish}],
    }
    if ids is not None:
        data["position"], data["token_ids"] = ids[0], list(ids[1])
    if timings is not None:
        data["timings"] = timings
    return b"data: " + json.dumps(data).encode() + b"\n\n"


class RequestRoutes:
    """Transport-neutral HTTP route handling — every endpoint the serving
    surface speaks (completions, models, health probes, /metrics, the
    /debug family, SSE streaming), written against a SIX-method transport
    seam so the thread-per-connection tier (`_Handler`, stdlib
    BaseHTTPRequestHandler) and the selectors event-loop tier
    (serve/aio.py's context) serve byte-identical semantics from one
    definition site. Subclasses provide:

    * ``_send_raw(status, headers, body)`` — one complete response;
    * ``_start_sse()`` — the 200/chunked SSE response headers;
    * ``_write_chunk(payload)`` — one chunked-transfer frame (b"" ends);
    * ``_read_body()`` — the POST body bytes (may raise ValueError/OSError);
    * ``_drain_body()`` — keep-alive discipline for GETs with bodies;
    * ``_client_gone()`` — the disconnect probe.

    plus ``path``/``headers`` attributes of the current request."""

    api: ApiServer  # set by make_handler / the aio context
    _req_id: str | None = None  # minted per POST in do_POST
    path: str = ""

    def _send_json(self, status: int, payload: dict,
                   headers: dict | None = None) -> None:
        rid = self._req_id
        if rid and isinstance(payload.get("error"), dict):
            # error bodies carry the id too (429/503/500 included) so a
            # client-side report alone is enough to find the server logs
            payload["error"].setdefault("request_id", rid)
        data = json.dumps(payload).encode()
        hdrs = [("Content-Type", "application/json"),
                ("Content-Length", str(len(data)))]
        if rid:
            hdrs.append(("X-Request-Id", rid))
        if self.api.replica_id:
            # which replica answered — the router forwards it to the client
            # for end-to-end attribution (ISSUE 15)
            hdrs.append(("X-Replica-Id", self.api.replica_id))
        hdrs.extend((headers or {}).items())
        self._send_raw(status, hdrs, data)

    def do_GET(self):
        self._req_id = None
        if self.path == "/v1/models":
            self._send_json(200, self.api.models())
        elif self.path == "/metrics":
            # Prometheus text exposition of the process-global registry —
            # served from this (threaded) handler, so scrapes proceed while
            # completions run. Scrape-time refresh keeps the windowed/derived
            # gauges (latency quantiles, SLO attainment, roofline, process
            # self-metrics) current without putting their aggregation on the
            # serving hot path.
            ins.refresh_process_gauges()
            compile_obs.refresh_device_gauges()
            if self.api.scheduler is not None:
                self.api.scheduler.ledger.poke()
                self.api.scheduler.perf.refresh_gauges()
            body = metrics.REGISTRY.render().encode()
            self._send_raw(
                200,
                [("Content-Type", "text/plain; version=0.0.4; charset=utf-8"),
                 ("Content-Length", str(len(body)))],
                body)
        elif self.path.startswith("/debug/"):
            # the /debug family never touches admission (no request id is
            # minted, no scheduler counter moves) — pure read-side
            # observability plus the profiler trigger on the POST path
            self._drain_body()  # same keep-alive discipline as do_POST
            self._debug_get()
        elif self.path in ("/health", "/health/live", "/health/ready"):
            # /health: full snapshot, status by liveness (a restart signal);
            # /health/live and /health/ready: the k8s-style split probes —
            # ready goes 503 under drain/saturation while live stays 200,
            # so balancers stop routing without the supervisor killing us
            h = self.api.health()
            key = "ready" if self.path.endswith("/ready") else "live"
            self._send_json(200 if h[key] else 503, h)
        else:
            self._send_json(404, {"error": {"message": "not found"}})

    def _debug_kv(self) -> None:
        """GET /debug/kv — paged KV pool occupancy plus a full
        PagePool.audit() run on demand: the operator's allocator-integrity
        probe (refcounts vs block tables, free-list disjointness, gauge
        consistency). 200 with audit.ok=true when clean; 500 when the audit
        found corruption (alertable). Works without the span tracer."""
        sched = self.api.scheduler
        pool = (getattr(sched.engine, "pool", None)
                if sched is not None else None)
        if pool is None:
            self._send_json(200, {"layout": "dense", "pool": None,
                                  "audit": None})
            return
        report = pool.audit(raise_on_fail=False)
        self._send_json(200 if report["ok"] else 500,
                        {"layout": "paged", "page_size": pool.page_size,
                         "pool": pool.stats(), "audit": report,
                         # radix prefix-tree occupancy rides the allocator
                         # probe (the audit above already reconciled the
                         # tree's page refs against the pool refcounts)
                         "radix": sched.engine.radix_stats()
                         if hasattr(sched.engine, "radix_stats") else None})

    def _debug_radix(self) -> None:
        """GET /debug/radix — the cross-request prefix tree: cumulative
        hit/saved-token accounting plus a bounded dump of the live tree
        (page-granular edges, page ids, last-use ages). enabled=false on
        the dense layout, with --radix-cache off, or on the single-engine
        tier. Works without the span tracer."""
        sched = self.api.scheduler
        radix = (getattr(sched.engine, "radix", None)
                 if sched is not None else None)
        if radix is None:
            self._send_json(200, {"enabled": False, "stats": None,
                                  "tree": None})
            return
        self._send_json(200, {"enabled": True, "page_size": radix.page,
                              "stats": radix.stats(), "tree": radix.dump()})

    def _debug_perf(self) -> None:
        """GET /debug/perf — the ISSUE 7 join, one JSON document: sliding-
        window TTFT/ITL/e2e p50/p95/p99, SLO targets/attainment/burn totals,
        the scheduler time ledger (per-state seconds + fractions of loop
        wall time), roofline/goodput attribution of the decode path, and
        the process self-metrics. Works without the span tracer; the
        single-engine tier answers with mode=single and no scheduler views
        (it has no worker loop to ledger)."""
        sched = self.api.scheduler
        payload: dict = {"process": ins.refresh_process_gauges()}
        if sched is None:
            payload.update({
                "mode": "single",
                "slo": {"targets": {"ttft_ms": self.api.slo.ttft_ms,
                                    "itl_ms": self.api.slo.itl_ms},
                        "enabled": self.api.slo.enabled()},
            })
        else:
            sched.ledger.poke()  # bill the open span: a long idle park must
            # read as idle seconds now, not at the next state transition
            sched.perf.refresh_gauges()  # /metrics and this JSON agree
            payload["mode"] = "continuous"
            payload.update(sched.perf.snapshot(ledger=sched.ledger))
            # saved-prefill accounting (radix prefix cache; None when off):
            # hit_tokens are prompt rows that cost zero prefill FLOPs
            payload["radix"] = (sched.engine.radix_stats()
                                if hasattr(sched.engine, "radix_stats")
                                else None)
            # speculative-decoding acceptance record (None when --spec-k
            # 0): tokens_per_cycle = realized tokens per verify forward
            payload["spec"] = (sched.engine.spec_stats()
                               if hasattr(sched.engine, "spec_stats")
                               else None)
            # hybrid chunked-prefill + preemption state (ISSUE 12): the
            # live budget and the lifetime preempt/resume record
            payload["hybrid"] = {
                "prefill_budget": getattr(sched, "_budget_now", 0),
                "preemptions": getattr(sched, "preempt_count", 0),
                "resumed": getattr(sched, "resume_count", 0),
            }
        # compile-ledger summary (ISSUE 13; both tiers — the ledger is
        # process-global): compiles/seconds/unexpected + warmup state; the
        # full dump lives at GET /debug/compile
        payload["compile"] = compile_obs.LEDGER.summary()
        self._send_json(200, payload)

    def _debug_compile(self) -> None:
        """GET /debug/compile — the ISSUE 13 join, one JSON document: the
        jit compile ledger (per-fn totals + recent entries with shape
        signatures), shape-bucket contract coverage (declared / compiled /
        missing-warm / unexpected-seen per fn), the boot warmup report,
        host<->device transfer tallies by direction+site, and live device
        memory. Works without the span tracer; tier-independent (the
        ledger and transfer counters are process-global)."""
        sched = self.api.scheduler
        self._send_json(200, compile_obs.debug_payload(
            warmup_report=(sched.warmup_report if sched is not None
                           else None)))

    def _debug_get(self) -> None:
        """GET /debug/trace (Chrome trace-event JSON for Perfetto),
        GET /debug/requests (flight-recorder summaries),
        GET /debug/requests/{req_id} (one request's full timeline), and
        GET /debug/kv (paged-pool occupancy + on-demand audit)."""
        if self.path == "/debug/kv":
            self._debug_kv()  # independent of the span tracer
            return
        if self.path == "/debug/perf":
            self._debug_perf()  # also tracer-independent (registry + ledger)
            return
        if self.path == "/debug/radix":
            self._debug_radix()  # tracer-independent (tree + counters)
            return
        if self.path == "/debug/compile":
            self._debug_compile()  # tracer-independent (ledger + counters)
            return
        tr = trace.TRACER
        if not tr.enabled:
            self._send_json(404, {"error": {
                "message": "tracing is disabled; restart with "
                           "--trace-buffer N > 0"}})
            return
        if self.path == "/debug/trace":
            self._send_json(200, tr.export_chrome())
        elif self.path == "/debug/requests":
            self._send_json(200, {"requests": tr.requests_summary()})
        elif self.path.startswith("/debug/requests/"):
            rid = self.path[len("/debug/requests/"):]
            rec = tr.request_timeline(rid)
            if rec is None:
                self._send_json(404, {"error": {
                    "message": f"no flight-recorder entry for {rid!r} "
                               "(never seen, or evicted from the ring)"}})
            else:
                # postmortem SLO verdict from the record's own latency marks
                # (ttft/e2e/decode_tokens — ITL derived the same way
                # Request.itl_ms derives it), judged against the configured
                # targets; all-None verdicts when no SLO is configured
                rec["slo"] = self.api.slo.verdict_from_marks(
                    rec.get("ttft_ms"), rec.get("e2e_ms"),
                    rec.get("decode_tokens"))
                self._send_json(200, rec)
        else:
            self._send_json(404, {"error": {"message": "not found"}})

    def _log_done(self, rid: str, result: dict) -> None:
        u = result.get("usage", {})
        log.info("completion %s done: %d prompt + %d completion tokens",
                 rid, u.get("prompt_tokens", 0), u.get("completion_tokens", 0),
                 extra=trace.log_extra(rid))

    def do_POST(self):
        # the request id is minted at ADMISSION — before any outcome is
        # known — so even a request shed with 429/503 has a correlatable id
        # in its response headers and in the shed log line below
        rid = self._req_id = new_request_id(self.headers.get("X-Request-Id"))
        chat = self.path in ("/v1/chat/completions", "/chat/completions")
        legacy = self.path in ("/v1/completions", "/completions")
        # distributed trace context (ISSUE 17): a router hop header joins
        # this replica's spans/flight record to the mesh-wide trace — the
        # mark lands before admission so even shed requests correlate
        hopctx = trace.parse_hop(self.headers.get(trace.HOP_HEADER))
        if hopctx is not None and (chat or legacy):
            trace.TRACER.req_mark(rid, trace_id=hopctx[0],
                                  parent_span=hopctx[1], hop=hopctx[2])
        # the body is consumed BEFORE any early-return response: on the
        # keep-alive (HTTP/1.1) thread tier, unread body bytes would be
        # parsed as the NEXT request line — a 404'd POST must not poison its
        # connection (the aio tier buffers the body up front; same contract)
        try:
            raw = self._read_body()
        except (ValueError, OSError):
            self._send_json(400, {"error": {"message": "invalid request"}})
            return
        if self.path == "/debug/profile":
            # not a serving request: no request id, no admission counters,
            # usable even mid-drain (that is when postmortems happen) — but
            # the body was drained above like any POST on this keep-alive
            # server
            self._req_id = None
            self._handle_profile(raw)
            return
        if not (chat or legacy):
            self._send_json(404, {"error": {"message": "not found"}})
            return
        try:
            body = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            self._send_json(400, {"error": {"message": "invalid JSON body"}})
            return
        tmo_hdr = self.headers.get("X-Request-Timeout")
        if tmo_hdr is not None and isinstance(body, dict) \
                and "timeout_s" not in body:
            # header form of the per-request deadline (proxies/gateways set
            # it without touching the JSON body); an explicit body field wins
            body["timeout_s"] = tmo_hdr
        try:
            if self.api.draining:
                ins.REQUESTS_SHED.labels(reason="draining").inc()
                raise SchedulerDraining("server is draining")
            if body.get("stream"):
                # cheap validation BEFORE the 200/chunked headers go out — an
                # ApiError raised mid-stream would write a second status line
                # into the chunk stream (a protocol violation). Capacity is
                # prechecked for the same reason: overload sheds as a clean
                # 429/503, not a poisoned stream.
                self.api.prevalidate(body, legacy=legacy)
                self.api.precheck_capacity()
                self._stream(body, legacy=legacy)
            elif legacy:
                result = self.api.complete_legacy(
                    body, probe=self._client_gone, req_id=rid)
                result["request_id"] = rid
                self._log_done(rid, result)  # logged before the body goes out
                self._send_json(200, result)
            else:
                result = self.api.complete(
                    body, probe=self._client_gone, req_id=rid)
                result["request_id"] = rid
                self._log_done(rid, result)
                self._send_json(200, result)
        except ApiError as e:
            log.info("request %s rejected: %s", rid, e.message,
                     extra=trace.log_extra(rid))
            self._send_json(e.status, {"error": {"message": e.message}})
        except QueueFull as e:
            # load shedding: the request never entered the queue; tell the
            # client when to come back (429 per OpenAI's own rate responses).
            # The would-have-been id makes shed traffic correlatable: the
            # client got it in X-Request-Id, this line carries it too.
            log.warning("request %s shed (queue full): %s", rid, e,
                        extra=trace.log_extra(rid))
            self._send_json(429, {"error": {"message": str(e)}},
                            {"Retry-After": str(int(e.retry_after_s))})
        except SchedulerRejected as e:
            # draining or unhealthy: 503 so balancers retry elsewhere
            log.warning("request %s shed (%s): %s", rid,
                        e.__class__.__name__, e, extra=trace.log_extra(rid))
            self._send_json(503, {"error": {"message": str(e)}},
                            {"Retry-After": str(int(e.retry_after_s))})
        except ClientDisconnected:
            log.info("client disconnected; request %s cancelled", rid,
                     extra=trace.log_extra(rid))
        except CLIENT_GONE:
            log.info("client connection lost mid-response (request %s)", rid,
                     extra=trace.log_extra(rid))
        except Exception:
            log.exception("completion %s failed", rid,
                          extra=trace.log_extra(rid))
            try:
                self._send_json(500, {"error": {"message": "internal error"}})
            except CLIENT_GONE:
                pass

    def _handle_profile(self, raw: bytes) -> None:
        """POST /debug/profile — start a duration-capped jax.profiler
        capture (utils/profiling.start_profile; the same session the CLI's
        --trace uses). Body: {"duration_s": float, "dir": str}, both
        optional. 409 when a capture is already running."""
        from dllama_tpu.utils import profiling

        try:
            body = json.loads(raw or b"{}")
            if not isinstance(body, dict):
                raise ValueError
        except (ValueError, json.JSONDecodeError):
            self._send_json(400, {"error": {"message": "invalid JSON body"}})
            return
        try:
            info = profiling.start_profile(
                log_dir=body.get("dir"),
                duration_s=body.get("duration_s", 2.0))
        except profiling.ProfileBusy as e:
            self._send_json(409, {"error": {"message": str(e)}},
                            {"Retry-After": "2"})
            return
        except (TypeError, ValueError) as e:
            self._send_json(400, {"error": {"message": f"bad profile "
                                                       f"request: {e}"}})
            return
        log.info("device profile capture started: %.2fs -> %s",
                 info["duration_s"], info["dir"])
        self._send_json(200, {"profiling": info})

    def _stream(self, body: dict, legacy: bool = False) -> None:
        """SSE chunked streaming (dllama-api.cpp:203-223's role). `legacy`
        streams `text_completion` chunks (text field) instead of chat deltas.
        BLOCKING implementation — the thread tier runs every stream through
        it; the aio tier routes batched-tier streams to its cooperative SSE
        machine instead and uses this only for the single-engine tier
        (where the global engine lock serializes streams anyway)."""
        rid = self._req_id
        self._start_sse()
        # a failover resume keeps the dead upstream's stream identity: the
        # client already saw this id/created on the journaled frames, and a
        # mid-stream identity change would break strict SSE consumers
        resume = body.get("resume") if isinstance(body.get("resume"), dict) \
            else None
        cid = ((resume.get("id") if resume else None)
               or f"{'cmpl' if legacy else 'chatcmpl'}-{uuid.uuid4().hex[:16]}")
        created = int((resume.get("created") if resume else 0)
                      or time.time())
        model = body.get("model", self.api.model_name)
        chunk = self._write_chunk
        last_write = [time.monotonic()]

        def emit_chat(delta: dict, finish=None, timings=None,
                      ids=None) -> None:
            chunk(sse_chat_payload(cid, created, model, delta,
                                   finish=finish, timings=timings, ids=ids))
            last_write[0] = time.monotonic()

        def emit_text(text: str, finish=None, timings=None,
                      ids=None) -> None:
            chunk(sse_text_payload(cid, created, model, text,
                                   finish=finish, timings=timings, ids=ids))
            last_write[0] = time.monotonic()

        hb = self.api.sse_heartbeat_s

        def probe() -> bool:
            # the disconnect probe doubles as the keep-alive clock: it runs
            # at 4 Hz while tokens flow AND every poll interval while the
            # stream is quiet (queued, mid-prefill) — exactly the windows an
            # idle-timeout LB would kill (ISSUE 15)
            if hb and time.monotonic() - last_write[0] >= hb:
                chunk(SSE_HEARTBEAT)
                last_write[0] = time.monotonic()
            return self._client_gone()

        try:
            # streams get the disconnect probe too: a chunk write into a dead
            # socket fails on its own once tokens flow, but ONLY the probe
            # notices a client that vanished while queued / mid-prefill
            # (no tokens flowing yet)
            if legacy:
                result = self.api.complete_legacy(
                    body, emit=emit_text, probe=probe, req_id=rid)
                emit_text("", finish=result["choices"][0]["finish_reason"],
                          timings=result.get("timings"))
            else:
                if resume is None:
                    # a resumed stream's client already got the role delta
                    # from the dead upstream — re-sending it would duplicate
                    emit_chat({"role": "assistant"})
                result = self.api.complete(
                    body,
                    emit=lambda text, ids=None: emit_chat(
                        {"content": text}, ids=ids),
                    probe=probe, req_id=rid)
                emit_chat({}, finish=result["choices"][0]["finish_reason"],
                          timings=result.get("timings"))
            self._log_done(rid or "-", result)
        except (ClientDisconnected, *CLIENT_GONE):
            raise  # nothing to tell a dead socket; do_POST just logs it
        except Exception as e:
            # the 200/chunked headers are out — a second status line would
            # corrupt the stream. Emit an in-band SSE error event (the OpenAI
            # streaming error shape) and terminate the stream cleanly so the
            # client fails fast instead of hanging on a half-open stream.
            # Client-safe exception types keep their message; anything else
            # is masked like the non-stream 500 path (no internals leak).
            log.exception("streamed completion %s failed mid-stream", rid,
                          extra=trace.log_extra(rid))
            msg = (str(e) if isinstance(e, (ApiError, SchedulerRejected))
                   else "internal error")
            err = {"message": msg or e.__class__.__name__,
                   "type": "server_error"}
            if rid:
                err["request_id"] = rid  # SSE errors are correlatable too
            chunk(b"data: " + json.dumps({"error": err}).encode() + b"\n\n")
        chunk(b"data: [DONE]\n\n")
        chunk(b"")  # terminating zero-length chunk


class _Handler(RequestRoutes, BaseHTTPRequestHandler):
    """The thread-per-connection transport (`--frontend threads`): stdlib
    BaseHTTPRequestHandler provides parsing/keep-alive, RequestRoutes the
    endpoints, and this class only the six transport primitives."""

    server_version = "dllama-tpu"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.info("%s %s", self.address_string(), fmt % args)

    def _send_raw(self, status: int, headers, body: bytes) -> None:
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        # counted before the body write: once the client has read the
        # response, the counter has already moved (no scrape-after-response
        # race for tests or tight operators)
        ins.HTTP_RESPONSES.labels(endpoint=_endpoint(self.path),
                                  code=str(status)).inc()
        self.wfile.write(body)

    def _start_sse(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        if self._req_id:
            self.send_header("X-Request-Id", self._req_id)
        if self.api.replica_id:
            self.send_header("X-Replica-Id", self.api.replica_id)
        self.end_headers()
        ins.HTTP_RESPONSES.labels(endpoint=_endpoint(self.path),
                                  code="200").inc()

    def _write_chunk(self, payload: bytes) -> None:
        self.wfile.write(f"{len(payload):x}\r\n".encode() + payload + b"\r\n")
        self.wfile.flush()

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length)

    def _drain_body(self) -> None:
        """Read and discard any request body. The /debug endpoints answer
        early errors (404 unknown id, 404 tracing disabled, 409 profiler
        busy) on this keep-alive server, where unread body bytes would be
        parsed as the NEXT request line — the do_POST bug class, applied to
        the debug family (GETs with bodies are legal, if unusual)."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        if length > 0:
            try:
                self.rfile.read(length)
            except OSError:
                pass

    def _client_gone(self) -> bool:
        """Disconnect probe for non-streamed completions: a readable socket
        that MSG_PEEKs zero bytes is a closed peer (we never read mid-
        completion, so pending bytes can only be a pipelined request — in
        which case the client is certainly still there).

        Known trade-off: a client that legally HALF-closes its write side
        after the request body (shutdown(SHUT_WR), then reads) looks
        identical to a full close at this layer and gets cancelled. That's
        the same call Starlette/uvicorn make for their disconnect probes;
        real OpenAI-style clients keep the socket open until the response."""
        try:
            r, _, _ = select.select([self.connection], [], [], 0)
            if not r:
                return False
            return self.connection.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True


def make_server(loaded, host="127.0.0.1", port=0, n_slots: int = 0, **defaults):
    """-> (server, api). n_slots > 0 enables the continuous-batching tier: a
    BatchEngine with that many cache slots behind a Scheduler (concurrent
    requests share the device). n_slots == 0 keeps the single-engine tier
    with the NaiveCache prefix reuse (the reference server's semantics).
    `frontend` in **defaults picks the transport: 'aio' (default — the
    selectors event loop, serve/aio.py) or 'threads' (ThreadingHTTPServer);
    both answer the same routes and expose serve_forever/shutdown/
    server_close/server_address."""
    scheduler = None
    if n_slots <= 0 and any(
        defaults.get(k) is not None
        for k in ("admit_stall_budget_ms", "admit_ttft_deadline_ms")
    ):
        # same treatment as --spec on dp>1 meshes: an inapplicable serve
        # knob warns instead of vanishing silently
        log.warning("admission pacing flags (--admit-budget-ms / "
                    "--admit-ttft-deadline-ms) need --slots > 0; the "
                    "single-engine tier has no admission scheduler — ignored")
    if n_slots <= 0 and any(defaults.get(k) for k in ("max_queue", "stall_deadline_s")):
        log.warning("--max-queue / --stall-deadline-s need --slots > 0; the "
                    "single-engine tier has no admission queue or worker "
                    "thread to watch — ignored")
    if n_slots <= 0 and defaults.get("restart_max"):
        log.warning("--restart-max needs --slots > 0; the single-engine tier "
                    "has no scheduler worker to warm-restart — ignored")
    if n_slots <= 0 and defaults.get("kv_layout") == "paged":
        log.warning("--kv-layout paged needs --slots > 0; the single-engine "
                    "tier keeps its dense per-sequence cache — ignored")
    if n_slots <= 0 and defaults.get("radix_cache") == "on":
        log.warning("--radix-cache on needs --slots > 0; the single-engine "
                    "tier's NaiveCache has no page pool to share — ignored")
    if n_slots <= 0 and defaults.get("kv_host_pages"):
        log.warning("--kv-host-pages needs --slots > 0; the single-engine "
                    "tier has no page pool to spill from — ignored")
    if n_slots <= 0 and (defaults.get("prefill_budget") not in (None, "auto")
                         or defaults.get("preempt") not in (None, "auto")
                         or defaults.get("tenant_weights")):
        log.warning("--prefill-budget / --preempt / --tenant-weight need "
                    "--slots > 0; the single-engine tier serves one request "
                    "at a time — ignored (priority/tenant body fields are "
                    "accepted but inert)")
    if n_slots <= 0 and (defaults.get("warmup") not in (None, "off")
                         or defaults.get("transfer_guard")
                         not in (None, "off")):
        log.warning("--warmup / --transfer-guard need --slots > 0; the "
                    "single-engine tier has no BatchEngine shape contract "
                    "to precompile or guard — ignored")
    if n_slots > 0:
        from dllama_tpu.engine.batch import BatchEngine
        from dllama_tpu.serve.scheduler import Scheduler

        # batched speculation: greedy requests emit 1..K+1 tokens per verify
        # cycle, sampled requests decode exactly as before. dp meshes shard
        # the slot axis, which the per-slot history path doesn't support —
        # degrade to plain batched decode there instead of failing startup.
        spec_n = int(defaults.get("spec", 0))
        if (spec_n and loaded.shardings is not None
                and loaded.shardings.mesh.shape["dp"] > 1):
            log.warning("--spec is unavailable on dp>1 meshes; the "
                        "continuous-batching tier decodes without speculation")
            spec_n = 0
        # paged KV cache (--kv-layout): 'auto' — the serving default —
        # resolves to 'paged' on unsharded engines (the general paged
        # flash-decode kernel serves any page size, so the layout no longer
        # waits on tileability) and 'dense' on meshes (the pool has no slot
        # axis to shard; BatchEngine raises on paged+mesh — startup is the
        # right place to find an explicit 'paged' conflict out). The page
        # size shrinks to gcd(page_size, context) so short contexts stay
        # paged; a degenerate gcd (< 8 rows) falls back to dense.
        import math as _math

        kv_layout = defaults.get("kv_layout") or "auto"
        page_size = int(defaults.get("page_size") or 128)
        if kv_layout == "auto":
            if loaded.shardings is not None:
                kv_layout = "dense"
            else:
                # paged-by-default only where the flash-decode KERNEL could
                # route (paged_decode_supported): a config the kernel must
                # refuse — f8 pools, non-sublane-aligned pages — would
                # silently serve every step through the gather fallback's
                # re-materialized-view traffic, which is worse than the
                # dense default it replaced. Explicit --kv-layout paged
                # still honors the user's choice unconditionally.
                from dllama_tpu.ops.pallas.paged_attention import (
                    paged_decode_supported,
                )

                g = _math.gcd(page_size, loaded.engine.seq_len)
                capable = g >= 8 and paged_decode_supported(
                    (loaded.config.n_heads, loaded.config.head_size), g,
                    kv_dtype=loaded.engine.cache.k.dtype)
                kv_layout = "paged" if capable else "dense"
                if capable and g != page_size:
                    log.info("kv-layout auto: page size %d does not divide "
                             "context %d; using %d", page_size,
                             loaded.engine.seq_len, g)
                if capable:
                    page_size = g
            log.info("kv-layout auto -> %s", kv_layout)
        # cross-request radix prefix cache (--radix-cache, default auto = on
        # whenever the layout resolved paged): an explicit 'on' against a
        # dense resolution warns instead of failing startup — BatchEngine
        # itself raises only on the direct-library misuse
        radix_cache = defaults.get("radix_cache") or "auto"
        if radix_cache == "on" and kv_layout == "dense":
            log.warning("--radix-cache on requires the paged KV layout; this "
                        "engine resolved dense — the per-slot prefix cache "
                        "serves instead")
            radix_cache = "off"
        # host-RAM KV spill tier (--kv-host-pages, ISSUE 16): needs the
        # paged layout with the radix tree on (its token paths key the host
        # tier); warn-and-drop on an incompatible resolution rather than
        # failing startup, same policy as --radix-cache above
        kv_host_pages = int(defaults.get("kv_host_pages") or 0)
        if kv_host_pages > 0 and (kv_layout != "paged"
                                  or radix_cache == "off"):
            log.warning("--kv-host-pages requires the paged KV layout with "
                        "the radix cache on; this engine resolved "
                        "%s/radix=%s — the host spill tier stays off",
                        kv_layout, radix_cache)
            kv_host_pages = 0
        be = BatchEngine(
            loaded.config,
            loaded.engine.params,
            n_slots=n_slots,
            cache_dtype=loaded.engine.cache.k.dtype,
            max_seq_len=loaded.engine.seq_len,
            shardings=loaded.shardings,  # multi-chip serving keeps the mesh placement
            sync=getattr(loaded, "sync", "bf16"),
            spec=spec_n,
            kv_layout=kv_layout,
            page_size=page_size,
            kv_pages=int(defaults.get("kv_pages") or 0),
            radix_cache=radix_cache,
            kv_host_pages=kv_host_pages,
            # steady-state upload enforcement (--transfer-guard): 'strict'
            # turns an implicit per-chunk host->device transfer inside the
            # decode/spec dispatch window into an error
            transfer_guard=str(defaults.get("transfer_guard") or "off"),
        )
        # admission pacing (serve/scheduler.py): budget bounds the decode
        # stall a joining prefill may insert per visit; the optional TTFT
        # deadline hard-bounds a joiner's wait (CLI: --admit-budget-ms /
        # --admit-ttft-deadline-ms)
        sched_kw = {}
        if defaults.get("admit_stall_budget_ms") is not None:
            sched_kw["admit_stall_budget_ms"] = float(defaults["admit_stall_budget_ms"])
        if defaults.get("admit_ttft_deadline_ms") is not None:
            sched_kw["admit_ttft_deadline_ms"] = float(defaults["admit_ttft_deadline_ms"])
        # supervision knobs: bounded admission (--max-queue -> 429 shedding)
        # and the stall watchdog (--stall-deadline-s -> live=false on a hung
        # device chunk)
        if defaults.get("max_queue"):
            sched_kw["max_queue"] = int(defaults["max_queue"])
        if defaults.get("stall_deadline_s"):
            sched_kw["stall_deadline_s"] = float(defaults["stall_deadline_s"])
        # self-healing (--restart-max / --restart-window-s): warm engine
        # restart on worker crash, budgeted; 0 keeps crash = permanent
        # unhealthy (external supervisor owns the restart)
        if defaults.get("restart_max"):
            sched_kw["restart_max"] = int(defaults["restart_max"])
        if defaults.get("restart_window_s") is not None:
            sched_kw["restart_window_s"] = float(defaults["restart_window_s"])
        # overlapped decode pipeline (--overlap, default on): chunk N+1
        # dispatches before chunk N's tokens are consumed; off restores the
        # lockstep loop for A/B (token streams are identical either way)
        if defaults.get("overlap") is not None:
            sched_kw["overlap"] = bool(defaults["overlap"])
        # SLO targets (--slo-ttft-ms / --slo-itl-ms): the scheduler's perf
        # aggregator judges every terminal request against them (burn
        # counters, attainment gauge, goodput accounting)
        if defaults.get("slo_ttft_ms") is not None:
            sched_kw["slo_ttft_ms"] = float(defaults["slo_ttft_ms"])
        if defaults.get("slo_itl_ms") is not None:
            sched_kw["slo_itl_ms"] = float(defaults["slo_itl_ms"])
        # hybrid chunked prefill (--prefill-budget: auto|N|0) + preemption
        # (--preempt) + tenant fair-queue weights (--tenant-weight NAME=W)
        if defaults.get("prefill_budget") is not None:
            sched_kw["prefill_budget"] = defaults["prefill_budget"]
        if defaults.get("preempt") is not None:
            sched_kw["preempt"] = str(defaults["preempt"])
        if defaults.get("tenant_weights"):
            sched_kw["tenant_weights"] = dict(defaults["tenant_weights"])
        # boot precompile (--warmup auto): the scheduler declares its
        # compiled-shape universe and warms every bucket before the worker
        # takes traffic — first-request TTFT stops paying XLA cold-start
        if defaults.get("warmup"):
            sched_kw["warmup"] = str(defaults["warmup"])
        scheduler = Scheduler(be, **sched_kw)
    api = ApiServer(
        loaded,
        default_temperature=defaults.get("default_temperature", 0.8),
        default_topp=defaults.get("default_topp", 0.9),
        default_seed=defaults.get("default_seed"),
        scheduler=scheduler,
        spec=defaults.get("spec", 0),
        slo_ttft_ms=defaults.get("slo_ttft_ms"),
        slo_itl_ms=defaults.get("slo_itl_ms"),
        replica_id=defaults.get("replica_id") or "",
        sse_heartbeat_s=defaults.get("sse_heartbeat_s") or 0.0,
    )
    # front-end selection (ISSUE 15): 'aio' (default) multiplexes every
    # connection on a selectors event loop with a small fixed thread count;
    # 'threads' keeps the thread-per-connection ThreadingHTTPServer as the
    # A/B baseline. Same routes class either way — byte-identical semantics.
    frontend = str(defaults.get("frontend") or "aio")
    if frontend == "aio":
        from dllama_tpu.serve.aio import AioHttpServer

        httpd = AioHttpServer(
            (host, port), api,
            workers=int(defaults.get("aio_workers") or 0) or None)
    elif frontend == "threads":
        handler = type("Handler", (_Handler,), {"api": api})
        httpd = ThreadingHTTPServer((host, port), handler)
    else:
        raise ValueError(f"unknown frontend {frontend!r} (aio|threads)")
    if not api.replica_id:
        # default replica identity: the bound address — unique per replica
        # of a router mesh, stable for the life of the process. A wildcard
        # bind (0.0.0.0/::) names every machine's replica identically and
        # collapses the mesh's X-Replica-Id attribution to one bucket, so
        # substitute the hostname there
        ident = host
        if host in ("0.0.0.0", "::", ""):
            import socket as _socket
            ident = _socket.gethostname()
        api.replica_id = f"{ident}:{httpd.server_address[1]}"
    return httpd, api


def graceful_drain(httpd, api, timeout_s: float = 30.0) -> bool:
    """The deploy-time shutdown sequence (SIGTERM handler body, also callable
    directly from tests/embedding code):

    1. stop admission — new requests get 503 + Retry-After, /health/ready
       goes 503 so balancers route away;
    2. let in-flight requests (and already-queued ones) finish, bounded by
       `timeout_s`;
    3. shut down the scheduler and stop the HTTP accept loop.

    Returns True when everything in flight completed inside the timeout."""
    api.draining = True
    clean = True
    if api.scheduler is not None:
        clean = api.scheduler.drain(timeout_s)
    else:
        # single-engine tier: the global lock serializes requests; waiting
        # for it (with the same deadline) means the in-flight one finished
        clean = api.lock.acquire(timeout=max(0.0, timeout_s))
        if clean:
            api.lock.release()
    httpd.shutdown()
    return clean


def install_sigterm_drain(httpd, api, timeout_s: float = 30.0) -> bool:
    """SIGTERM -> graceful_drain in a helper thread (the handler itself must
    return fast; serve_forever keeps running until httpd.shutdown()). Returns
    False when not on the main thread, where signal.signal raises."""
    fired = threading.Event()

    def _term(signum, frame):
        if fired.is_set():
            return
        fired.set()
        log.info("SIGTERM: draining (timeout %.0fs) — new requests get 503",
                 timeout_s)
        threading.Thread(target=graceful_drain, args=(httpd, api, timeout_s),
                         name="dllama-drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _term)
        return True
    except ValueError:  # not the main thread (embedded/test usage)
        return False


def run_server(loaded, host="127.0.0.1", port=9990, n_slots: int = 0, **defaults) -> int:
    httpd, api = make_server(loaded, host, port, n_slots=n_slots, **defaults)
    drain_timeout_s = float(defaults.get("drain_timeout_s") or 30.0)
    install_sigterm_drain(httpd, api, drain_timeout_s)
    mode = f"continuous batching, {n_slots} slots" if n_slots else "single-request + prefix cache"
    log.info("serving on http://%s:%d (%s); telemetry at /metrics, probes "
             "at /health/live and /health/ready",
             host, httpd.server_address[1], mode)
    print(f"🚀 http://{host}:{httpd.server_address[1]}/v1/chat/completions ({mode})")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if api.scheduler is not None:
            api.scheduler.shutdown()
        httpd.server_close()
    return 0
