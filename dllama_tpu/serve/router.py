"""Multi-replica serving router (ISSUE 15) — `dllama-tpu router`.

One engine process owns one device; serving "millions of users" needs N of
them. This router is the separate process that fronts N engine replicas
(each a normal `dllama-tpu serve --slots ...` process) and gives the fleet
one OpenAI-compatible address, the way the reference's ROOT node fronts
its `NnNetwork` worker mesh (SURVEY.md L4/L5: the root performs a config
handshake with every worker, then scatter-gathers the actual work):

* **replica registry + config handshake** — at registration the router
  reads each replica's `/health` build payload and `/v1/models`; the first
  replica's (model, version) pair becomes the mesh config, and a replica
  that disagrees is quarantined (config_ok=False, never routed) instead of
  silently serving a different model — the root/worker handshake verdict,
  inverted for a pull-style mesh (replicas own their weights; the router
  verifies instead of distributing).
* **health polling + drain integration** — a poller thread GETs `/health`
  on a short cadence: `ready:false` (draining or saturated) stops NEW
  routing while in-flight requests finish; `live` flips the
  `dllama_replica_healthy` gauge; connection failure marks the replica
  down immediately at the first failed proxy attempt, not a poll later.
* **prefix-affinity routing** — requests carry their prefix fingerprint
  (the shared system prompt / leading prompt bytes, hashed); the router
  pins a fingerprint to the replica that served it last, so multi-turn
  chats and shared-template traffic land where PR 9's radix cache is
  already warm (SGLang's cache-aware routing, one level up). Token-id
  exactness lives in the replica's radix tree; the router only needs a
  stable warm HINT, so a text-prefix hash is sufficient and tokenizer-free.
  Capacity-aware: a warm replica that is overloaded relative to the
  least-loaded one (or not ready) is overridden, and the fingerprint is
  re-pinned to wherever the request actually lands.
* **failover** — a replica that refuses/resets before any response byte
  reached the client is NOT a client-visible failure: the request is
  re-routed to a surviving replica (bounded attempts, exponential backoff
  with jitter), the failed replica is marked down, and the reroute is
  counted.
* **mid-stream failover** (ISSUE 16) — the router sees every SSE frame it
  relays, so it JOURNALS each stream's resume state: the raw token ids the
  frames carried (`include_token_ids` is injected into every proxied
  stream body), the stream id/created, and a pinned per-request seed. When
  a replica dies mid-stream, the router resubmits to a survivor with a
  `resume` body — prompt plus the journaled emitted prefix, which the
  replica re-prefills through its radix/resume_commit path and whose PRNG
  chain it replays from the seed — so greedy AND sampled streams continue
  BIT-EXACT vs the uninterrupted run, duplicate-suppressed by journal
  position, with at most one in-band `: retrying` comment visible.
  Bounded by `--failover-max` resume attempts per stream under capped
  exponential backoff with jitter. Unresumable streams (journal ring
  full, journal over its token bound, no survivor, budget spent) keep the
  old exactly-once contract: a final SSE chunk with
  `finish_reason:"error"`, an in-band error event, then `[DONE]` — never
  a half-open socket. When every replica is down or shedding, the router
  sheds with the worst upstream's `Retry-After` honored.

* **fleet observability plane** (ISSUE 19) — the router is the one
  process that sees every request leg, so it owns the fleet's joined view:
  it mints a distributed trace context per request (`X-Dllama-Trace` hop
  header: trace id + parent span + hop count) and instruments its own path
  as first-class spans in a router-side tracer (`connect`, `proxy`,
  `poll`, `failover.attempt`, `resume`, `journal`, plus the
  `affinity.pick` instant event); the health poller doubles as an NTP-lite
  clock-offset estimator per replica (obs/perf.ClockOffset, min-RTT sample
  per poll window) and each poll exchange is itself a `poll` span;
  `GET /router/trace` fetches every replica's Chrome export, shifts it by
  the estimated offset, and merges it with the router's own track into ONE
  Perfetto file; `GET /metrics` (alias `/router/metrics`) federates every
  live replica's exposition (each series relabeled `replica=<rid>`,
  counters summed and histograms merged bucket-wise into an exact
  `dllama_fleet_*` view, dead replicas held at their last-known values
  with `dllama_fleet_scrape_age_seconds` growing); client-perspective
  TTFT/ITL is measured AT the router per replica and fleet-wide
  (`dllama_router_ttft_seconds`, `dllama_router_itl_seconds`,
  `dllama_router_slo_attainment{replica}`) so failover- and network-
  induced SLO misses invisible to any single replica are scored where the
  client feels them; `GET /router/fleet` joins health + SLO attainment +
  KV/spill/radix + clock offsets + failover counters vs client-observed
  errors with mesh-wide goodput; `GET /router/requests/{req_id}` joins the
  router's failover journal with each serving replica's flight recorder —
  one URL answers "what happened to this request" across retries,
  resumes, and deaths.

Transport: the same selectors event loop as `--frontend aio`
(serve/aio.AioHttpServer with a router context class); each in-flight
proxied request occupies one worker-pool thread for its upstream I/O.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import random
import re
import threading
import time
import uuid
from collections import OrderedDict

from dllama_tpu.obs import metrics, new_request_id, trace
from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs.perf import (ClockOffset, SloPolicy, WindowQuantiles,
                                 WindowSums)
from dllama_tpu.serve.aio import AioHttpServer, _AioContext
from dllama_tpu.utils import faults, locks

log = logging.getLogger("dllama_tpu.serve.router")

#: request paths the router proxies (completions surface only; /debug and
#: /metrics are per-replica diagnostics an operator hits directly)
_PROXY_POSTS = ("/v1/chat/completions", "/chat/completions",
                "/v1/completions", "/completions")

#: leading prompt characters the affinity fingerprint hashes — long enough
#: to separate real system prompts, short enough that giant pastes don't
#: dominate the hash cost
AFFINITY_PREFIX_CHARS = 512

#: how much busier (in-flight + queued) an affinity-warm replica may be
#: than the least-loaded one before warmth loses to capacity
AFFINITY_OVERLOAD = 8


class Replica:
    """Registry entry for one engine replica."""

    __slots__ = ("rid", "host", "port", "live", "ready", "draining",
                 "queue_depth", "busy_slots", "inflight", "build",
                 "model", "config_ok", "handshaken", "last_poll",
                 "last_picked", "fails", "clock", "trace_epoch",
                 "last_metrics_text", "last_metrics_t")

    def __init__(self, rid: str, host: str, port: int):
        self.rid = rid
        self.host = host
        self.port = port
        self.live = False
        self.ready = False
        self.draining = False
        self.queue_depth = 0
        self.busy_slots = 0
        self.inflight = 0  # router-side in-flight proxied requests
        self.build = None  # /health "build" payload from the handshake
        self.model = None  # /v1/models first id
        self.config_ok = True
        self.handshaken = False
        self.last_poll = 0.0
        self.last_picked = 0.0
        self.fails = 0
        # NTP-lite clock alignment (ISSUE 17): the health poller samples
        # this replica's monotonic clock against ours on every round trip;
        # trace_epoch is the replica tracer's t=0 in the replica's clock,
        # which is what /router/trace shifts Chrome timestamps by
        self.clock = ClockOffset()
        self.trace_epoch: float | None = None
        # last successful /metrics scrape (ISSUE 19 staleness contract):
        # a dead replica keeps federating these last-known series while
        # dllama_fleet_scrape_age_seconds grows — stale, never zero traffic
        self.last_metrics_text: str | None = None
        self.last_metrics_t = 0.0

    def load(self) -> int:
        """The routing load signal: what's running here plus what's queued
        (health-poll fresh) plus what this router already sent."""
        return self.inflight + self.queue_depth + self.busy_slots

    def snapshot(self) -> dict:
        return {"id": self.rid, "address": f"{self.host}:{self.port}",
                "live": self.live, "ready": self.ready,
                "draining": self.draining, "config_ok": self.config_ok,
                "queue_depth": self.queue_depth,
                "busy_slots": self.busy_slots, "inflight": self.inflight,
                "fails": self.fails, "model": self.model,
                "build": self.build,
                "clock": self.clock.estimate(),
                "last_poll_age_s": (round(time.monotonic() - self.last_poll,
                                          3) if self.last_poll else None)}


class _StreamJournal:
    """Per-stream resume state (ISSUE 16), built from the frames the router
    relays: the raw token ids (`token_ids`/`position` fields the injected
    ``include_token_ids`` makes every frame carry), the stream identity the
    client saw, and terminal-frame tracking. ``valid`` drops to False when
    the journal can no longer vouch for the client's view (ring full at
    admission, token bound exceeded, a position gap) — the stream then
    fails with the pre-failover exactly-once error contract."""

    __slots__ = ("tokens", "cid", "created", "finished", "valid", "counted")

    def __init__(self, valid: bool = True):
        self.tokens: list[int] = []
        self.cid: str | None = None
        self.created = 0
        self.finished = False  # terminal frame relayed (finish/error/[DONE])
        self.valid = valid
        self.counted = valid  # held a slot in the router's journal ring

    def note_frame(self, frame: bytes, max_tokens: int) -> bool:
        """Account one complete SSE frame -> whether to RELAY it (False =
        a duplicate the client already has, drop it). Appends ids only at
        the exact journal position, which makes replayed/overlapping
        frames after a failover self-suppressing."""
        if not frame.startswith(b"data: "):
            return True  # comment/heartbeat frames pass through
        payload = frame[len(b"data: "):].strip()
        if payload == b"[DONE]":
            self.finished = True
            return True
        try:
            obj = json.loads(payload)
        except ValueError:
            return True
        if "error" in obj:
            self.finished = True
            return True
        if self.cid is None:
            self.cid = obj.get("id")
            self.created = int(obj.get("created") or 0)
        ids = obj.get("token_ids")
        pos = obj.get("position")
        if ids and isinstance(pos, int):
            if pos == len(self.tokens):
                self.tokens.extend(int(t) for t in ids)
                if len(self.tokens) > max_tokens:
                    self.valid = False  # over the ring bound: stop vouching
            elif pos + len(ids) <= len(self.tokens):
                # the survivor replayed a frame the dead replica already
                # delivered: the client has these bytes — suppress
                return False
            else:
                self.valid = False  # gap: the journal lost sync
        try:
            if (obj.get("choices") or [{}])[0].get("finish_reason"):
                self.finished = True
        except (TypeError, AttributeError, IndexError):
            pass
        return True


class _UpstreamDead(Exception):
    """Connection-level failure before/while talking to a replica."""


class _UpstreamBusy(Exception):
    """Replica answered 429/503 — try elsewhere, honor Retry-After."""

    def __init__(self, status: int, retry_after: float):
        super().__init__(f"upstream {status}")
        self.status = status
        self.retry_after = retry_after


def _parse_replica(spec: str) -> Replica:
    """'host:port' or 'http://host:port' -> Replica (rid = host:port)."""
    s = spec.strip()
    if s.startswith("http://"):
        s = s[len("http://"):]
    s = s.rstrip("/")
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"replica spec {spec!r}: expected host:port")
    return Replica(f"{host}:{port}", host, int(port))


#: one exposition sample line: name, optional {labels}, value
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$")


def _parse_exposition(text: str):
    """Line-parse one Prometheus exposition -> (families, samples) where
    families maps name -> [kind, help] and samples are (family, sample_name,
    label_block, value_text) in input order. Family attribution for _bucket/
    _sum/_count rides the preceding HELP/TYPE block, the way the renderer
    emits them. Values stay TEXT — federation must not reformat a number it
    merely relays."""
    fams: dict[str, list] = {}
    samples: list[tuple[str, str, str, str]] = []
    cur = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            fams.setdefault(name, ["", ""])[1] = help_
            cur = name
        elif line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            fams.setdefault(name, ["", ""])[0] = kind.strip()
            cur = name
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            if not m:
                continue
            name, labels, value = m.groups()
            fam = cur if cur and name.startswith(cur) else name
            samples.append((fam, name, labels or "", value))
    return fams, samples


def _fleet_name(fam: str) -> str:
    return ("dllama_fleet_" + fam[len("dllama_"):]
            if fam.startswith("dllama_") else "fleet_" + fam)


def federate(own: str, parts: list[tuple[str, str]]) -> str:
    """Merge the router's exposition with each replica's into one (ISSUE
    19): every replica sample gains a leading ``replica="<rid>"`` label
    (the router's own series stay unlabeled — it IS the scrape target),
    families keep one HELP/TYPE block each, and counters AND histograms
    are additionally pre-aggregated across replicas into a
    ``dllama_fleet_*`` view so a dashboard gets mesh totals without a
    query-time sum. The histogram merge is EXACT, not approximate:
    buckets are fixed per family (obs/metrics registers one bucket tuple
    per histogram), so summing each ``le`` bucket, ``_sum``, and
    ``_count`` across replicas is the same histogram a single registry
    observing the union stream would render — property-tested in
    tests/test_fleet_obs.py."""
    fams: dict[str, list] = {}
    grouped: dict[str, list[str]] = {}
    fleet: dict[str, dict[str, float]] = {}
    # histograms: fam -> {(sample_name, label_block) -> summed value}, in
    # first-seen order (every replica renders one family's buckets in the
    # same ascending-le order, so insertion order IS exposition order)
    hfleet: dict[str, dict[tuple[str, str], float]] = {}

    def declare(name: str, kind: str, help_: str) -> None:
        cur = fams.setdefault(name, ["", ""])
        if kind and not cur[0]:
            cur[0] = kind
        if help_ and not cur[1]:
            cur[1] = help_

    own_fams, own_samples = _parse_exposition(own)
    for name, (kind, help_) in own_fams.items():
        declare(name, kind, help_)
    for fam, name, labels, value in own_samples:
        grouped.setdefault(fam, []).append(f"{name}{labels} {value}")

    for rid, text in parts:
        rep_fams, rep_samples = _parse_exposition(text)
        for name, (kind, help_) in rep_fams.items():
            declare(name, kind, help_)
        tag = f'replica="{metrics.escape_label_value(rid)}"'
        for fam, name, labels, value in rep_samples:
            inner = labels[1:-1] if labels else ""
            relabeled = "{" + tag + ("," + inner if inner else "") + "}"
            grouped.setdefault(fam, []).append(f"{name}{relabeled} {value}")
            kind = fams.get(fam, ["", ""])[0]
            if kind == "counter" and name == fam:
                try:
                    v = float(value)
                except ValueError:
                    continue
                acc = fleet.setdefault(fam, {})
                acc[labels] = acc.get(labels, 0.0) + v
            elif kind == "histogram" and name in (
                    fam + "_bucket", fam + "_sum", fam + "_count"):
                try:
                    v = float(value)
                except ValueError:
                    continue
                hacc = hfleet.setdefault(fam, {})
                hkey = (name, labels)
                hacc[hkey] = hacc.get(hkey, 0.0) + v

    out: list[str] = []
    for name in sorted(fams):
        kind, help_ = fams[name]
        if name not in grouped:
            continue  # declared but sampleless: nothing to expose
        out.append(f"# HELP {name} {help_ or name}")
        if kind in ("counter", "gauge", "histogram"):
            out.append(f"# TYPE {name} {kind}")
        out.extend(grouped[name])
    for fam in sorted(fleet):
        fname = _fleet_name(fam)
        out.append(f"# HELP {fname} Sum of {fam} across all scraped "
                   "replicas (pre-aggregated at the router)")
        out.append(f"# TYPE {fname} counter")
        for labels, v in sorted(fleet[fam].items()):
            out.append(f"{fname}{labels} {metrics.format_value(v)}")
    for fam in sorted(hfleet):
        fname = _fleet_name(fam)
        out.append(f"# HELP {fname} Bucket-wise sum of {fam} across all "
                   "scraped replicas (exact: buckets are fixed per family)")
        out.append(f"# TYPE {fname} histogram")
        for (name, labels), v in hfleet[fam].items():
            out.append(f"{fname}{name[len(fam):]}{labels} "
                       f"{metrics.format_value(v)}")
    return "\n".join(out) + "\n"


class Router:
    """The replica mesh + routing policy (transport-independent: the
    context class below adapts it onto the aio event loop)."""

    # the aio context reads these off `server.api`
    replica_id = ""
    sse_heartbeat_s = 0.0
    scheduler = None

    def __init__(self, replicas: list[str], poll_s: float = 0.5,
                 affinity: bool = True, connect_timeout_s: float = 2.0,
                 stream_idle_timeout_s: float = 120.0,
                 max_affinity_entries: int = 4096,
                 failover_max: int = 2,
                 max_live_journals: int = 1024,
                 max_journal_tokens: int = 16384,
                 fleet_obs: bool = True,
                 trace_capacity: int = 2048,
                 max_request_records: int = 512,
                 slo: SloPolicy | None = None):
        if not replicas:
            raise ValueError("router needs at least one --replica")
        self.replicas = [_parse_replica(s) for s in replicas]
        if len({r.rid for r in self.replicas}) != len(self.replicas):
            raise ValueError("duplicate --replica addresses")
        self.poll_s = float(poll_s)
        self.affinity_on = bool(affinity)
        self.connect_timeout_s = float(connect_timeout_s)
        self.stream_idle_timeout_s = float(stream_idle_timeout_s)
        self.max_affinity_entries = int(max_affinity_entries)
        # mid-stream failover (ISSUE 16): resume attempts per stream
        # (--failover-max; 0 restores the fail-exactly-once contract), the
        # live-journal ring bound (streams admitted past it relay fine but
        # are unresumable), and the per-journal token bound
        self.failover_max = int(failover_max)
        self.max_live_journals = int(max_live_journals)
        self.max_journal_tokens = int(max_journal_tokens)
        self._live_journals = 0
        # mesh observability plane (ISSUE 17): the router's OWN tracer (its
        # spans are the mesh trace's router track), gated by --fleet-obs so
        # the bench can A/B the plane's overhead; off => NULL tracer, no hop
        # header, no clock sampling. Postmortem records live in a bounded
        # insertion-ordered ring (oldest evicted), keyed by request id.
        self.fleet_obs = bool(fleet_obs)
        self.tracer = (trace.Tracer(int(trace_capacity))
                       if self.fleet_obs and int(trace_capacity) > 0
                       else trace.NULL_TRACER)
        self.max_request_records = int(max_request_records)
        self._requests: OrderedDict[str, dict] = OrderedDict()
        # router-side SLO attainment (ISSUE 19): CLIENT-perspective TTFT/
        # ITL windows per replica plus the replica="fleet" rollup, judged
        # against the router's own SloPolicy. A replica can meet its local
        # SLOs while the client misses them (failover gap, network): that
        # delta is precisely what these windows exist to expose.
        self.slo = slo or SloPolicy()
        self._client: dict[str, dict] = {"fleet": self._client_window()}
        for r in self.replicas:
            self._client[r.rid] = self._client_window()
        # the router's own trace epoch: merge math aligns every replica's
        # export onto THIS timeline (postmortem at_ms is relative to it too)
        self._boot = getattr(self.tracer, "epoch", None) or time.monotonic()
        self._mu = locks.make_lock("serve.router")
        self._affinity: dict[str, str] = {}  # fingerprint -> replica rid
        self._pick_seq = 0.0
        self.draining = False
        self._stop = threading.Event()
        self._pollers: list[threading.Thread] = []  # one per replica
        # mesh config (set by the first successful handshake): every other
        # replica must agree or it is quarantined
        self.mesh_model = None
        self.mesh_version = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        # one synchronous poll round first so the router comes up knowing
        # its mesh (the reference root performs its config handshake before
        # serving, nn-network root/worker synchronize the same way).
        # SEQUENTIAL in list order so mesh-config adoption is deterministic
        # — "the first replica's (model, version) becomes the mesh config"
        # must mean the first LISTED live replica, not a poll race winner.
        for rep in self.replicas:
            self._poll_one(rep)
        # steady state: ONE persistent poller thread per replica — polls of
        # the same replica are serialized by construction (a stale timed-out
        # poll can never overwrite a fresher one's verdict), an unreachable
        # replica's 2 s connect timeouts never stretch its neighbors'
        # cadence, and nothing spawns per tick
        for rep in self.replicas:
            t = threading.Thread(target=self._poll_replica_loop, args=(rep,),
                                 name=f"dllama-router-poll-{rep.rid}",
                                 daemon=True)
            self._pollers.append(t)
            t.start()

    def stop(self) -> None:
        self._stop.set()

    def drain(self) -> None:
        """Stop admitting NEW requests (503 + ready:false); in-flight
        proxied requests keep streaming until they finish."""
        self.draining = True

    # ---------------------------------------------------------- health poll

    def _poll_one(self, rep: Replica) -> None:
        t_send = time.monotonic()
        try:
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=self.connect_timeout_s)
            conn.request("GET", "/health")
            resp = conn.getresponse()
            payload = json.loads(resp.read() or b"{}")
            conn.close()
        except (OSError, ValueError, http.client.HTTPException) as e:
            # HTTPException (BadStatusLine/IncompleteRead from a replica
            # mid-restart) is not an OSError — escaping here would kill the
            # poller thread permanently
            self.tracer.span_at("poll", t_send, time.monotonic(),
                                cat="router", track="poll",
                                replica=rep.rid, ok=False)
            self._mark_down(rep, f"health poll failed: {e!r}")
            return
        t_recv = time.monotonic()
        # the poll exchange is itself a first-class span on the router's
        # "poll" track — it doubles as the NTP-lite clock sample below, so
        # a trace reader can see exactly which round trips fed alignment
        self.tracer.span_at("poll", t_send, t_recv, cat="router",
                            track="poll", replica=rep.rid, ok=True)
        if self.fleet_obs:
            # NTP-lite: the replica reports its own monotonic clock inside
            # the poll response; one (rtt, offset) sample per poll, min-RTT
            # wins over the window (the tightest round trip bounds the
            # asymmetry error best)
            clk = payload.get("clock") or {}
            t_remote = clk.get("monotonic_s")
            if isinstance(t_remote, (int, float)):
                rep.clock.sample(t_send, t_recv, float(t_remote))
                est = rep.clock.estimate()
                if est is not None:
                    ins.REPLICA_CLOCK_OFFSET.labels(replica=rep.rid).set(
                        est["offset_s"])
                    ins.REPLICA_CLOCK_UNCERTAINTY.labels(
                        replica=rep.rid).set(est["uncertainty_s"])
            epoch = clk.get("trace_epoch_s")
            if isinstance(epoch, (int, float)):
                rep.trace_epoch = float(epoch)
        rep.live = bool(payload.get("live"))
        rep.ready = bool(payload.get("ready")) and not payload.get("draining")
        rep.draining = bool(payload.get("draining"))
        rep.queue_depth = int(payload.get("queue_depth") or 0)
        rep.busy_slots = int(payload.get("busy_slots") or 0)
        rep.last_poll = time.monotonic()
        ins.REPLICA_HEALTHY.labels(replica=rep.rid).set(
            1.0 if rep.live else 0.0)
        if not rep.handshaken:
            self._handshake(rep, payload)

    def _handshake(self, rep: Replica, health: dict) -> None:
        """Config handshake (reference root/worker wire protocol's role):
        record the replica's build + served model, adopt the first
        replica's pair as the mesh config, quarantine disagreement."""
        rep.build = health.get("build")
        try:
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=self.connect_timeout_s)
            conn.request("GET", "/v1/models")
            resp = conn.getresponse()
            models = json.loads(resp.read() or b"{}")
            conn.close()
            rep.model = (models.get("data") or [{}])[0].get("id")
        except (OSError, ValueError, IndexError, http.client.HTTPException):
            return  # not handshaken yet; next poll retries
        rep.handshaken = True
        version = (rep.build or {}).get("version")
        with self._mu:
            if self.mesh_model is None:
                self.mesh_model = rep.model
                self.mesh_version = version
                rep.config_ok = True
                log.info("router mesh config from %s: model=%s version=%s",
                         rep.rid, rep.model, version)
                return
        ok = rep.model == self.mesh_model and version == self.mesh_version
        if not ok and rep.config_ok:
            log.error("replica %s FAILED the config handshake: serves "
                      "(%s, %s), mesh is (%s, %s) — quarantined",
                      rep.rid, rep.model, version, self.mesh_model,
                      self.mesh_version)
        elif ok and not rep.config_ok:
            # a formerly-quarantined replica came back (redeployed) with
            # the mesh's config: re-admit it
            log.info("replica %s re-passed the config handshake — "
                     "re-admitted", rep.rid)
        rep.config_ok = ok

    def _mark_down(self, rep: Replica, why: str) -> None:
        if rep.live or rep.ready:
            log.warning("replica %s marked down: %s", rep.rid, why)
        rep.live = False
        rep.ready = False
        # a down replica may come back as a DIFFERENT process (redeploy):
        # its identity must be re-verified before it is routed again — this
        # is also how a quarantined replica rejoins after being fixed
        rep.handshaken = False
        rep.fails += 1
        rep.last_poll = time.monotonic()
        ins.REPLICA_HEALTHY.labels(replica=rep.rid).set(0.0)

    def _poll_replica_loop(self, rep: Replica) -> None:
        while not self._stop.wait(self.poll_s):
            self._poll_one(rep)

    # -------------------------------------------------------------- routing

    @staticmethod
    def fingerprint(body: dict, legacy: bool) -> str | None:
        """Prefix fingerprint of a completions body — the warm-cache hint.
        Chat: the leading SYSTEM message when present (the shared-template
        prefix real traffic reuses), else the first message; legacy: the
        prompt's leading bytes. Deterministic text prefix => deterministic
        token prefix => the replica's radix tree resolves the real hit."""
        try:
            if legacy:
                text = str(body.get("prompt") or "")
            else:
                msgs = body.get("messages") or []
                first = msgs[0] if msgs else {}
                text = f"{first.get('role')}\x1f{first.get('content')}"
            if not text:
                return None
            return hashlib.sha1(
                text[:AFFINITY_PREFIX_CHARS].encode("utf-8", "replace")
            ).hexdigest()
        except (TypeError, AttributeError, IndexError):
            return None

    def _routable(self, exclude: set) -> list[Replica]:
        # handshaken is required, not just config_ok: before the handshake
        # completes the replica's identity is UNVERIFIED (config_ok still
        # holds its default) — never route there yet
        return [r for r in self.replicas
                if r.ready and r.handshaken and r.config_ok
                and r.rid not in exclude]

    def pick(self, fp: str | None,
             exclude: set) -> tuple[Replica | None, bool]:
        """-> (replica, via_affinity). Affinity wins when the pinned
        replica is routable and not overloaded relative to the least-
        loaded candidate; otherwise least-loaded (LRU tie-break). The
        fingerprint is (re)pinned to whatever is returned."""
        with self._mu:
            candidates = self._routable(exclude)
            if not candidates:
                return None, False
            least = min(candidates, key=lambda r: (r.load(), r.last_picked))
            chosen, warm = least, False
            if self.affinity_on and fp is not None:
                rid = self._affinity.get(fp)
                if rid is not None:
                    rep = next((r for r in candidates if r.rid == rid), None)
                    if rep is not None and (
                            rep.load() <= least.load() + AFFINITY_OVERLOAD):
                        chosen, warm = rep, True
                if len(self._affinity) >= self.max_affinity_entries \
                        and fp not in self._affinity:
                    # cheap cap: drop the oldest insertion (dict preserves
                    # insertion order); a fingerprint that matters re-pins
                    # on its next request
                    self._affinity.pop(next(iter(self._affinity)))
                self._affinity[fp] = chosen.rid
            self._pick_seq += 1.0
            chosen.last_picked = self._pick_seq
            chosen.inflight += 1
        if warm:
            ins.ROUTER_AFFINITY_HITS.inc()
        return chosen, warm

    def release(self, rep: Replica) -> None:
        with self._mu:
            rep.inflight = max(0, rep.inflight - 1)

    # ------------------------------------------------------ failover journal

    def journal_acquire(self) -> _StreamJournal:
        """One journal per live proxied stream, bounded: past the ring cap
        the stream still relays normally but starts unresumable (valid =
        False) — bounded memory beats a failover promise the router could
        only keep by buffering without limit."""
        with self._mu:
            if self._live_journals >= self.max_live_journals:
                return _StreamJournal(valid=False)
            self._live_journals += 1
            return _StreamJournal()

    def journal_release(self, js: _StreamJournal) -> None:
        if not js.counted:
            return  # cap-rejected at acquire: never held a ring slot
        js.counted = False
        with self._mu:
            self._live_journals = max(0, self._live_journals - 1)

    # -------------------------------------------------- postmortem records

    def _note_rec(self, rid: str) -> dict:
        """Get-or-create one request's postmortem record (lock held)."""
        rec = self._requests.get(rid)
        if rec is None:
            rec = self._requests[rid] = {
                "req_id": rid, "trace_id": None, "stream": None,
                "outcome": None, "retries": 0, "attempts": []}
            while len(self._requests) > self.max_request_records:
                self._requests.popitem(last=False)
        return rec

    def note_request(self, rid: str, **fields) -> None:
        """Merge scalar facts into the request's postmortem record."""
        if not rid:
            return
        with self._mu:
            rec = self._note_rec(rid)
            for k, v in fields.items():
                if v is not None:
                    rec[k] = v

    def note_attempt(self, rid: str, replica: str, kind: str,
                     outcome: str) -> None:
        """Append one routing leg (kind: forward|resume) and its verdict."""
        if not rid:
            return
        with self._mu:
            rec = self._note_rec(rid)
            rec["attempts"].append({
                "replica": replica, "kind": kind, "outcome": outcome,
                "at_ms": round((time.monotonic() - self._boot) * 1000.0, 1)})

    # ----------------------------------------------- client-perspective SLO

    @staticmethod
    def _client_window() -> dict:
        return {"ttft": WindowQuantiles(60.0, 6),
                "itl": WindowQuantiles(60.0, 6),
                "flow": WindowSums(60.0, 6)}

    def observe_client(self, rid: str, ttft_s: float | None,
                       itl_s: float | None = None) -> None:
        """Score one finished proxied request from the CLIENT's seat:
        feed the per-replica and fleet latency windows and the router
        histograms, judge against the router's SloPolicy. ``rid`` is the
        replica that delivered the scored latency (first token for TTFT;
        a failed-over stream's survivor inherits the failover gap in its
        ITL — that attribution is the point, the gap is real client
        time)."""
        if not self.fleet_obs:
            return
        if ttft_s is not None:
            ins.ROUTER_TTFT_SECONDS.observe(ttft_s)
        if itl_s is not None:
            ins.ROUTER_ITL_SECONDS.observe(itl_s)
        v = self.slo.verdict(
            None if ttft_s is None else ttft_s * 1000.0,
            None if itl_s is None else itl_s * 1000.0)
        for key in ("fleet", rid):
            w = self._client.get(key)
            if w is None:
                continue
            if ttft_s is not None:
                w["ttft"].observe(ttft_s)
            if itl_s is not None:
                w["itl"].observe(itl_s)
            w["flow"].add(finished=1, ok=1 if v["ok"] else 0)

    def _client_snapshot(self, key: str) -> dict | None:
        """Windowed client-perspective view for one replica (or "fleet")."""
        w = self._client.get(key)
        if w is None:
            return None
        out: dict = {}
        for name in ("ttft", "itl"):
            s = w[name].snapshot()
            out[name + "_ms"] = {
                "count": s["count"],
                **{p: (None if s[p] is None
                       else round(s[p] * 1000.0, 3))
                   for p in ("p50", "p95", "p99")}}
        f = w["flow"].totals()
        fin = f.get("finished", 0.0)
        out["window_finished"] = int(fin)
        out["attainment"] = (round(f.get("ok", 0.0) / fin, 6)
                             if fin else None)
        out["targets"] = {"ttft_ms": self.slo.ttft_ms,
                          "itl_ms": self.slo.itl_ms}
        return out

    def refresh_client_gauges(self) -> None:
        """Scrape-time refresh of dllama_router_slo_attainment{replica}
        (NaN when the window drained — unknown, not perfect)."""
        for key in self._client:
            snap = self._client_snapshot(key)
            att = snap["attainment"] if snap else None
            ins.ROUTER_SLO_ATTAINMENT.labels(replica=key).set(
                float("nan") if att is None else att)

    # -------------------------------------------------- fleet observability

    def _fetch(self, rep: Replica, path: str) -> tuple[int, bytes] | None:
        """One GET against one replica; None on any transport failure (a
        fleet view must degrade to the replicas it can reach, not 500)."""
        try:
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=self.connect_timeout_s)
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
            return resp.status, data
        except (OSError, http.client.HTTPException):
            return None

    def _fan_out(self, jobs: list[tuple[str, Replica, str]]
                 ) -> dict[str, tuple[int, bytes] | None]:
        """Concurrent GETs: jobs are (key, replica, path) -> {key: result}.
        One short-lived thread per job — scrape fan-out is poll-cadence
        work, not request-path work, so thread churn here is fine."""
        out: dict[str, tuple[int, bytes] | None] = {}

        def one(key: str, rep: Replica, path: str) -> None:
            out[key] = self._fetch(rep, path)

        threads = [threading.Thread(target=one, args=j, daemon=True)
                   for j in jobs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.connect_timeout_s * 4)
        return out

    def _scrape_targets(self) -> list[Replica]:
        # live (not necessarily ready): a draining replica's metrics and
        # traces are exactly what a postmortem needs
        return [r for r in self.replicas if r.live and r.handshaken]

    def merged_trace(self) -> dict:
        """ONE Perfetto/Chrome trace for the whole mesh: the router's own
        track plus every reachable replica's export, each replica's
        timestamps shifted onto the router's clock by the poller's offset
        estimate (shift_us = (epoch_replica - offset - epoch_router) µs:
        a replica monotonic instant t maps to t - offset on the router's
        clock, and Chrome ts values are relative to each tracer's epoch)."""
        own = self.tracer.export_chrome() if self.fleet_obs else {
            "traceEvents": [], "displayTimeUnit": "ms"}
        epoch = self._boot
        parts: list[tuple[str, dict, float]] = [("router", own, 0.0)]
        clocks: dict[str, dict] = {}
        targets = self._scrape_targets()
        got = self._fan_out([(r.rid, r, "/debug/trace") for r in targets])
        for rep in targets:
            res = got.get(rep.rid)
            if res is None or res[0] != 200:
                continue
            try:
                export = json.loads(res[1])
            except ValueError:
                continue
            est = rep.clock.estimate()
            offset = est["offset_s"] if est else 0.0
            aligned = est is not None and rep.trace_epoch is not None
            rep_epoch = (rep.trace_epoch if rep.trace_epoch is not None
                         else epoch + offset)
            shift_us = (rep_epoch - offset - epoch) * 1e6
            parts.append((rep.rid, export, shift_us))
            clocks[rep.rid] = {"aligned": aligned,
                               "offset_s": offset,
                               "uncertainty_s": (est or {}).get(
                                   "uncertainty_s"),
                               "trace_epoch_s": rep.trace_epoch,
                               "shift_us": round(shift_us, 1)}
        merged = trace.merge_chrome(parts)
        merged["otherData"] = {"router_epoch_s": epoch, "clock": clocks,
                               "replicas_merged": len(parts) - 1}
        return merged

    def federate_metrics(self) -> str:
        """One exposition for the mesh: the router's own registry plus every
        replica's /metrics with each series relabeled replica=<rid>,
        counters summed and histograms merged bucket-wise into
        dllama_fleet_*. Staleness contract (ISSUE 19): a replica the scrape
        can't reach keeps federating its LAST successful exposition — its
        counters hold their last-known values instead of vanishing (which a
        fleet sum would read as traffic dropping to zero) — while
        dllama_fleet_scrape_age_seconds{replica} grows to say how stale."""
        t0 = time.monotonic()
        ins.refresh_process_gauges()
        self.refresh_client_gauges()
        targets = self._scrape_targets()
        got = self._fan_out([(r.rid, r, "/metrics") for r in targets])
        now = time.monotonic()
        for rep in targets:
            res = got.get(rep.rid)
            if res is not None and res[0] == 200:
                rep.last_metrics_text = res[1].decode("utf-8", "replace")
                rep.last_metrics_t = now
        parts = []
        for rep in self.replicas:
            if rep.last_metrics_text is None:
                continue  # never scraped successfully: nothing to hold
            ins.FLEET_SCRAPE_AGE.labels(replica=rep.rid).set(
                max(now - rep.last_metrics_t, 0.0))
            parts.append((rep.rid, rep.last_metrics_text))
        text = federate(metrics.REGISTRY.render(), parts)
        ins.FEDERATION_SCRAPE_SECONDS.observe(time.monotonic() - t0)
        return text

    def fleet(self) -> dict:
        """The mesh as one system: per-replica health + SLO attainment +
        KV/spill/radix + clock offset + client-perspective latency, and
        fleet aggregates (goodput, throughput, request-weighted SLO
        attainment, failover counters vs client-observed errors)."""
        targets = self._scrape_targets()
        jobs = []
        for r in targets:
            for path in ("/debug/perf", "/debug/kv", "/debug/radix"):
                jobs.append((f"{r.rid}{path}", r, path))
        got = self._fan_out(jobs)

        def part(rep: Replica, path: str):
            res = got.get(f"{rep.rid}{path}")
            if res is None or res[0] != 200:
                return None
            try:
                return json.loads(res[1])
            except ValueError:
                return None

        reps = []
        thr = good = 0.0
        att_num = att_den = 0.0
        for r in self.replicas:
            entry = r.snapshot()
            entry["client"] = self._client_snapshot(r.rid)
            if r in targets:
                perf = part(r, "/debug/perf") or {}
                entry["slo"] = perf.get("slo")
                entry["window"] = perf.get("window")
                entry["roofline"] = perf.get("roofline")
                entry["kv"] = part(r, "/debug/kv")
                entry["radix"] = part(r, "/debug/radix")
                roof = perf.get("roofline") or {}
                thr += float(roof.get("throughput_tok_s") or 0.0)
                good += float(roof.get("goodput_tok_s") or 0.0)
                slo = perf.get("slo") or {}
                fin = float(slo.get("window_finished") or 0.0)
                att = slo.get("attainment")
                if fin > 0 and att is not None:
                    att_num += float(att) * fin
                    att_den += fin
            reps.append(entry)
        # reconciliation block (ISSUE 19): the router's failover counters
        # next to the client-observed error count they must explain — a
        # SIGKILL drill's exhausted+unresumable failovers ARE the stream
        # errors clients saw, and chaos --mesh asserts exactly that.
        # REGISTRY.sample() reads without creating series: an outcome that
        # never happened reads 0 here without polluting the exposition.
        def cval(name: str, **labels) -> float:
            v = metrics.REGISTRY.sample(name, labels)
            return 0.0 if v is None else float(v)

        failovers = {o: cval("dllama_router_failovers_total", outcome=o)
                     for o in ("retried", "resumed", "exhausted",
                               "unresumable")}
        rids = [r.rid for r in self.replicas] + ["none"]
        client_errors = {
            "stream_error": failovers["exhausted"]
            + failovers["unresumable"],
            "shed": sum(cval("dllama_router_requests_total",
                             replica=x, outcome="shed") for x in rids),
            "upstream_error": sum(cval("dllama_router_requests_total",
                                       replica=x, outcome="error")
                                  for x in rids),
        }
        return {
            "replicas": reps,
            "mesh": {"model": self.mesh_model, "version": self.mesh_version,
                     "draining": self.draining},
            "fleet": {
                "replicas": len(self.replicas),
                "live": sum(1 for r in self.replicas if r.live),
                "ready": sum(1 for r in self.replicas
                             if r.ready and r.handshaken and r.config_ok),
                "scraped": len(targets),
                "throughput_tok_s": round(thr, 3),
                "goodput_tok_s": round(good, 3),
                "slo_attainment": (round(att_num / att_den, 6)
                                   if att_den else None),
                "window_finished": int(att_den),
                "client": self._client_snapshot("fleet"),
                "failovers": failovers,
                "client_errors": client_errors,
            },
        }

    def postmortem(self, req_id: str) -> dict | None:
        """Cross-hop join for one request: the router's routing/failover
        record + every involved replica's flight-recorder timeline."""
        with self._mu:
            rec = self._requests.get(req_id)
            if rec is not None:
                rec = dict(rec)
                rec["attempts"] = [dict(a) for a in rec["attempts"]]
        if rec is None:
            return None
        rids = []
        for a in rec["attempts"]:
            if a["replica"] not in rids:
                rids.append(a["replica"])
        by_rid = {r.rid: r for r in self.replicas}
        jobs = [(rid, by_rid[rid], f"/debug/requests/{req_id}")
                for rid in rids if rid in by_rid]
        got = self._fan_out(jobs)
        legs: dict[str, dict] = {}
        for rid in rids:
            res = got.get(rid)
            if res is None:
                legs[rid] = {"error": "unreachable"}
                continue
            status, data = res
            try:
                legs[rid] = (json.loads(data) if status == 200
                             else {"error": f"status {status}"})
            except ValueError:
                legs[rid] = {"error": "bad payload"}
        return {"req_id": req_id, "trace_id": rec.get("trace_id"),
                "router": rec, "replicas": legs}

    # ------------------------------------------------------------- snapshot

    def health(self) -> dict:
        reps = [r.snapshot() for r in self.replicas]
        ready = any(r.ready and r.handshaken and r.config_ok
                    for r in self.replicas) and not self.draining
        return {"live": True, "ready": ready,
                "status": "ok", "mode": "router",
                "draining": self.draining,
                "replicas": reps,
                "mesh": {"model": self.mesh_model,
                         "version": self.mesh_version},
                "clock": {"monotonic_s": time.monotonic(),
                          "trace_epoch_s": self._boot},
                "process": ins.refresh_process_gauges()}


class _RouterContext(_AioContext):
    """Router endpoints over the aio transport. `self.api` is the Router."""

    def do_GET(self):
        self._req_id = None
        router: Router = self.api
        if self.path in ("/health", "/health/live", "/health/ready"):
            h = router.health()
            key = "ready" if self.path.endswith("/ready") else "live"
            self._send_json(200 if h[key] else 503, h)
        elif self.path == "/router/replicas":
            self._send_json(200, {"replicas": [r.snapshot()
                                               for r in router.replicas]})
        elif self.path == "/router/trace":
            self._send_json(200, router.merged_trace())
        elif self.path in ("/metrics", "/router/metrics"):
            # the router's /metrics IS the federated view (ISSUE 19): a
            # Prometheus pointed at the router gets the whole mesh —
            # replica-labeled series, exact dllama_fleet_* rollups, and
            # the router's own series — in one scrape
            body = router.federate_metrics().encode()
            self._send_raw(
                200,
                [("Content-Type", "text/plain; version=0.0.4; charset=utf-8"),
                 ("Content-Length", str(len(body)))],
                body)
        elif self.path == "/router/fleet":
            self._send_json(200, router.fleet())
        elif self.path.startswith("/router/requests/"):
            rec = router.postmortem(self.path[len("/router/requests/"):])
            if rec is None:
                self._send_json(404, {"error": {
                    "message": "unknown or expired request id"}})
            else:
                self._send_json(200, rec)
        elif self.path == "/v1/models":
            # answered from the handshake record — the mesh serves ONE model
            # by construction, no upstream round-trip needed
            self._send_json(200, {
                "object": "list",
                "data": [{"id": router.mesh_model or "dllama-tpu",
                          "object": "model", "created": int(time.time()),
                          "owned_by": "dllama-tpu"}]})
        else:
            self._send_json(404, {"error": {"message": "not found"}})

    def do_POST(self):
        router: Router = self.api
        rid = self._req_id = new_request_id(self.headers.get("X-Request-Id"))
        try:
            raw = self._read_body()
        except (ValueError, OSError):
            self._send_json(400, {"error": {"message": "invalid request"}})
            return
        if self.path not in _PROXY_POSTS:
            self._send_json(404, {"error": {"message": "not found"}})
            return
        if router.draining:
            self._send_json(503, {"error": {"message": "router is draining"}},
                            {"Retry-After": "5"})
            ins.ROUTER_REQUESTS.labels(replica="none",
                                       outcome="shed").inc()
            return
        _proxy(router, self, raw, rid)


def _proxy(router: Router, ctx: _RouterContext, raw: bytes,
           rid: str) -> None:
    """Route one completions request: pick -> forward -> (maybe) failover.
    Runs on a pool worker; a streamed response occupies the worker for the
    stream's lifetime (upstream I/O is blocking)."""
    legacy = ctx.path in ("/v1/completions", "/completions")
    # client-perspective latency starts HERE — queueing, backoff, and
    # failover gaps between this mark and the first relayed token are
    # client time no replica's own TTFT accounts for
    t_req = time.monotonic()
    # distributed trace context (ISSUE 17): ONE trace id covers every leg
    # this request takes — the router's own spans plus each replica's, the
    # hop header carrying (trace id, parent span, hop count) downstream
    tid = trace.new_trace_id() if router.fleet_obs else ""
    router.note_request(rid, trace_id=tid or None, path=ctx.path)
    if tid:
        # mark the router tracer's flight record: export_chrome stamps the
        # trace id into every router-track event carrying this req_id
        router.tracer.req_mark(rid, trace_id=tid)
    try:
        # shed drill (faults: router.proxy): a raise here is a clean 503
        # before any replica is picked — the chaos mesh's router-shed path
        faults.fire("router.proxy")
    except faults.InjectedFault:
        ins.ROUTER_REQUESTS.labels(replica="none", outcome="shed").inc()
        router.note_request(rid, outcome="shed")
        ctx._send_json(503, {"error": {"message": "router shed (fault)"}},
                       {"Retry-After": "1"})
        return
    try:
        body = json.loads(raw or b"{}")
        if not isinstance(body, dict):
            raise ValueError
    except (ValueError, json.JSONDecodeError):
        ctx._send_json(400, {"error": {"message": "invalid JSON body"}})
        return
    stream = bool(body.get("stream"))
    router.note_request(rid, stream=stream)
    if stream:
        # mid-stream failover needs two body amendments BEFORE the first
        # attempt: frames must carry their raw token ids (the journal
        # feed), and sampled streams must have a pinned seed — an unseeded
        # stream's PRNG chain exists only on the replica that started it,
        # so nothing could replay it bit-exact after a death
        body["include_token_ids"] = True
        if body.get("seed") is None:
            body["seed"] = random.getrandbits(31)
        raw = json.dumps(body).encode()
    fp = router.fingerprint(body, legacy)
    tr = router.tracer
    tried: set[str] = set()
    busy: list[_UpstreamBusy] = []
    backoff = 0.05
    hop = [0]  # shared leg counter: the hop header's monotone hop count
    attempts = len(router.replicas) + 1
    for _ in range(attempts):
        rep, warm = router.pick(fp, exclude=tried)
        if rep is None:
            break
        tr.event("affinity.pick", cat="router", track="router", req_id=rid,
                 replica=rep.rid, warm=warm)
        try:
            _forward(router, ctx, rep, raw, rid, stream, legacy, body, fp,
                     tid, hop, t_req)
            return
        except _UpstreamBusy as e:
            # the replica is shedding (429 queue-full / 503 draining):
            # honest capacity signal, not a crash — try the next one
            busy.append(e)
            tried.add(rep.rid)
            router.note_attempt(rid, rep.rid, "forward", "busy")
            ins.ROUTER_REQUESTS.labels(replica=rep.rid,
                                       outcome="busy").inc()
        except _UpstreamDead as e:
            # connection refused/reset with ZERO client-visible bytes:
            # idempotent from the client's seat — mark down, reroute
            router._mark_down(rep, f"proxy failed: {e}")
            tried.add(rep.rid)
            router.note_attempt(rid, rep.rid, "forward", "rerouted")
            ins.ROUTER_REQUESTS.labels(replica=rep.rid,
                                       outcome="rerouted").inc()
            log.warning("request %s: replica %s failed before response "
                        "start; rerouting", rid, rep.rid,
                        extra={"request_id": rid, "replica": rep.rid,
                               "trace_id": tid})
            # jittered: after a replica kill every pinned stream lands
            # here at once — synchronized retries would hammer the same
            # survivor at the same instant (thundering herd)
            t0 = tr.now()
            time.sleep(backoff * (0.5 + random.random() / 2.0))
            tr.span_at("failover.attempt", t0, tr.now(), cat="router",
                       track="router", req_id=rid, reroute=True)
            backoff = min(backoff * 2, 1.0)
        finally:
            router.release(rep)
    # every replica tried/saturated: shed. Prefer the upstreams' own
    # Retry-After (429 beats 503 as the status when any replica exists but
    # is saturated — the client should back off and retry, not fail over).
    ins.ROUTER_REQUESTS.labels(replica="none", outcome="shed").inc()
    router.note_request(rid, outcome="shed")
    if busy:
        retry_after = max(int(e.retry_after) for e in busy)
        status = 429 if any(e.status == 429 for e in busy) else 503
        ctx._send_json(status, {"error": {
            "message": "all replicas are saturated"}},
            {"Retry-After": str(max(retry_after, 1))})
    else:
        ctx._send_json(503, {"error": {
            "message": "no ready replicas"}}, {"Retry-After": "5"})


def _forward(router: Router, ctx: _RouterContext, rep: Replica,
             raw: bytes, rid: str, stream: bool, legacy: bool,
             body: dict | None = None, fp: str | None = None,
             tid: str = "", hop: list | None = None,
             t_req: float | None = None) -> None:
    """One forwarding attempt. Raises _UpstreamDead/_UpstreamBusy while the
    attempt is still idempotent (no client-visible bytes); once a streamed
    response starts, an upstream death enters the mid-stream failover path
    (journal resume on a survivor, bounded by --failover-max) and — only
    when that is exhausted or unresumable — terminates the client stream
    cleanly with finish_reason="error" instead of raising."""
    hop = hop if hop is not None else [0]
    t_req = t_req if t_req is not None else time.monotonic()
    headers = {"Content-Type": "application/json", "X-Request-Id": rid}
    if tid:
        hop[0] += 1
        headers[trace.HOP_HEADER] = trace.format_hop(tid, "connect",
                                                     hop[0])
    tmo = ctx.headers.get("X-Request-Timeout")
    if tmo:
        headers["X-Request-Timeout"] = tmo
    tr = router.tracer
    t0 = tr.now()
    try:
        # connect under the SHORT timeout so a black-holed replica (SYN
        # dropped, no RST) fails over in ~connect_timeout_s instead of
        # holding this worker for the whole read timeout; only the
        # established socket gets the long read deadline
        conn = http.client.HTTPConnection(rep.host, rep.port,
                                          timeout=router.connect_timeout_s)
        conn.connect()
        conn.sock.settimeout(router.stream_idle_timeout_s if stream
                             else max(router.stream_idle_timeout_s, 600.0))
        conn.request("POST", ctx.path, raw, headers)
        resp = conn.getresponse()
    except (OSError, http.client.HTTPException) as e:
        # HTTPException covers a replica dying mid-status-line
        # (BadStatusLine & co.) — still zero client-visible bytes, still
        # idempotent, still a reroute
        tr.span_at("connect", t0, tr.now(), cat="router",
                   track="router", req_id=rid, trace_id=tid,
                   replica=rep.rid, hop=hop[0], ok=False)
        raise _UpstreamDead(f"{e.__class__.__name__}: {e}") from None
    tr.span_at("connect", t0, tr.now(), cat="router", track="router",
               req_id=rid, trace_id=tid, replica=rep.rid, hop=hop[0],
               ok=True)
    ctype = resp.getheader("Content-Type") or ""
    if resp.status in (429, 503):
        try:
            resp.read()  # drain so the connection closes cleanly
        except (OSError, http.client.HTTPException):
            pass  # verdict (status + Retry-After) is already in hand; a
            # replica dying after its shed headers must still shed, not
            # escape _proxy and drop the client with no response
        conn.close()
        try:
            retry_after = float(resp.getheader("Retry-After") or 1)
        except ValueError:
            retry_after = 1.0
        raise _UpstreamBusy(resp.status, retry_after)
    replica_hdr = resp.getheader("X-Replica-Id") or rep.rid
    if not (stream and resp.status == 200
            and ctype.startswith("text/event-stream")):
        # non-stream (or upstream error answered as JSON): buffer fully,
        # THEN forward — a failure mid-read leaves the attempt idempotent
        try:
            data = resp.read()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            raise _UpstreamDead(f"read failed: {e!r}") from None
        conn.close()
        hdrs = [("Content-Type", ctype or "application/json"),
                ("Content-Length", str(len(data))),
                ("X-Request-Id", resp.getheader("X-Request-Id") or rid),
                ("X-Replica-Id", replica_hdr)]
        ctx._send_raw(resp.status, hdrs, data)
        outcome = "ok" if resp.status < 500 else "error"
        router.note_attempt(rid, rep.rid, "forward", outcome)
        router.note_request(rid, outcome=outcome, status=resp.status)
        ins.ROUTER_REQUESTS.labels(replica=rep.rid, outcome=outcome).inc()
        if resp.status < 500:
            # non-stream: the whole buffered response IS the first (and
            # only) client-visible byte burst — TTFT is the full leg
            router.observe_client(rep.rid, time.monotonic() - t_req)
        return
    # ---- streamed pass-through: client-visible from the headers on
    hdrs = [("Content-Type", "text/event-stream"),
            ("Cache-Control", "no-cache"),
            ("Transfer-Encoding", "chunked"),
            ("X-Request-Id", resp.getheader("X-Request-Id") or rid),
            ("X-Replica-Id", replica_hdr)]
    ins.HTTP_RESPONSES.labels(
        endpoint="/v1/completions" if legacy else "/v1/chat/completions",
        code="200").inc()
    ctx.server.enqueue(ctx.conn, ctx._head(200, hdrs))
    _relay_with_failover(router, ctx, rep, conn, resp, rid, legacy,
                         body or {}, fp, tid, hop, t_req)


def _relay_stream(ctx: _RouterContext, resp, js: _StreamJournal,
                  max_tokens: int, marks: dict | None = None) -> str:
    """Relay one upstream SSE response frame-by-frame, feeding the journal.
    -> "done" (terminal frame relayed), "client_gone", or "died: <why>"
    (socket error, or EOF before any terminal frame). ``marks`` (shared
    across failover legs) collects client-perspective frame timing: the
    monotonic instant of the first and last relayed data frame, the frame
    count, and the replica that delivered the first frame — the router-side
    SLO windows are fed from exactly these."""
    buf = b""
    try:
        while True:
            # read1: forward whatever is available NOW. read(n) on a
            # chunked response blocks until n bytes accumulate or EOF —
            # it would hold ~100-byte token deltas (and keep-alive
            # heartbeats) hostage until the stream ended, turning the
            # router into a buffer that defeats streaming entirely
            data = resp.read1(16384)
            if not data:
                # EOF on a journaled stream that never delivered a terminal
                # frame IS a death (the old pass-through silently truncated
                # here) — a SIGKILLed replica's socket just closes
                return ("done" if js.finished
                        else "died: eof before terminal frame")
            buf += data
            # relay COMPLETE frames only (the incomplete tail waits for
            # more bytes): the journal must account a frame's ids before
            # its bytes reach the client, or a death between the two
            # would resume short and duplicate tokens
            while True:
                frame, sep, rest = buf.partition(b"\n\n")
                if not sep:
                    break
                buf = rest
                if js.note_frame(frame + sep, max_tokens):
                    ctx._write_chunk(frame + sep)
                    if (marks is not None and frame.startswith(b"data: ")
                            and frame[len(b"data: "):].strip()
                            != b"[DONE]"):
                        t = time.monotonic()
                        if marks.get("first") is None:
                            marks["first"] = t
                        marks["last"] = t
                        marks["frames"] = marks.get("frames", 0) + 1
            if ctx.conn.dead:
                return "client_gone"
    except (OSError, http.client.HTTPException) as e:
        return f"died: {e.__class__.__name__}: {e}"


def _resume_raw(body: dict, js: _StreamJournal) -> bytes:
    """The resume request body a survivor replica re-enters the stream
    with: the ORIGINAL prompt/params (max_tokens included — the replica's
    produced-counter starts at the journal length) plus the journaled
    emitted prefix and the stream identity the client already saw."""
    b2 = dict(body)
    b2["resume"] = {"tokens": list(js.tokens), "id": js.cid or "",
                    "created": int(js.created or 0)}
    b2["include_token_ids"] = True
    return json.dumps(b2).encode()


def _fail_stream(ctx: _RouterContext, rid: str, legacy: bool,
                 model: str, why: str) -> None:
    """The exactly-once terminal error sequence for an unresumable or
    exhausted stream: finish_reason="error" chunk, in-band error event,
    [DONE], chunk terminator — never a half-open socket."""
    fail = {
        "id": f"{'cmpl' if legacy else 'chatcmpl'}-{uuid.uuid4().hex[:16]}",
        "object": "text_completion" if legacy else "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": 0,
                     **({"text": ""} if legacy else {"delta": {}}),
                     "finish_reason": "error"}],
    }
    err = {"message": why, "type": "server_error", "request_id": rid}
    ctx._write_chunk(b"data: " + json.dumps(fail).encode() + b"\n\n")
    ctx._write_chunk(b"data: " + json.dumps({"error": err}).encode()
                     + b"\n\n")
    ctx._write_chunk(b"data: [DONE]\n\n")
    ctx._write_chunk(b"")


def _relay_with_failover(router: Router, ctx: _RouterContext, rep: Replica,
                         conn, resp, rid: str, legacy: bool, body: dict,
                         fp: str | None, tid: str = "",
                         hop: list | None = None,
                         t_req: float | None = None) -> None:
    """Own a streamed response end-to-end: relay + journal, and on an
    upstream death resume on a survivor (at most --failover-max times,
    capped exponential backoff with jitter, one `: retrying` comment)."""
    hop = hop if hop is not None else [0]
    t_req = t_req if t_req is not None else time.monotonic()
    tr = router.tracer
    js = router.journal_acquire()
    t_j = tr.now()  # journal hold window opens: spanned at release
    model = router.mesh_model or "dllama-tpu"
    cur_rep, cur_conn, cur_resp = rep, conn, resp
    retries = 0
    commented = False
    # frame-timing marks shared across failover legs: first/last relayed
    # data frame + count, and the replica that delivered the first frame
    # (TTFT is attributed to it; ITL to whichever replica finishes)
    marks: dict = {"first": None, "last": None, "frames": 0}

    def score_client() -> None:
        ttft = (marks["first"] - t_req if marks["first"] is not None
                else None)
        itl = ((marks["last"] - marks["first"]) / (marks["frames"] - 1)
               if marks["frames"] >= 2 else None)
        if ttft is None and itl is None:
            return
        router.observe_client(marks.get("first_rid") or cur_rep.rid,
                              ttft, itl)

    try:
        while True:
            leg_kind = "resume" if retries else "forward"
            t0 = tr.now()
            verdict = _relay_stream(ctx, cur_resp, js,
                                    router.max_journal_tokens, marks)
            if marks["first"] is not None and "first_rid" not in marks:
                marks["first_rid"] = cur_rep.rid
            tr.span_at("proxy", t0, tr.now(), cat="router",
                       track="router", req_id=rid, replica=cur_rep.rid,
                       verdict=verdict.split(":")[0],
                       tokens=len(js.tokens))
            cur_conn.close()
            if verdict == "client_gone":
                # client hung up mid-stream: stop pulling tokens; closing
                # the upstream socket makes the REPLICA's disconnect probe
                # fire and free the slot
                ins.ROUTER_REQUESTS.labels(replica=cur_rep.rid,
                                           outcome="client_gone").inc()
                router.note_attempt(rid, cur_rep.rid, leg_kind,
                                    "client_gone")
                router.note_request(rid, outcome="client_gone",
                                    retries=retries)
                return
            if verdict == "done":
                # count BEFORE the terminating chunk: the client observes
                # stream end the instant that write lands, and a scrape
                # (or test) right after must already see the outcome
                ins.ROUTER_REQUESTS.labels(replica=cur_rep.rid,
                                           outcome="ok").inc()
                if retries:
                    ins.ROUTER_FAILOVERS.labels(outcome="resumed").inc()
                router.note_attempt(rid, cur_rep.rid, leg_kind, "ok")
                router.note_request(rid, outcome="ok", retries=retries,
                                    tokens=len(js.tokens))
                score_client()
                ctx._write_chunk(b"")  # clean upstream end; end our chunks
                return
            # ---- upstream death mid-stream
            router._mark_down(cur_rep, f"died mid-stream: {verdict}")
            ins.ROUTER_REQUESTS.labels(replica=cur_rep.rid,
                                       outcome="stream_error").inc()
            router.note_attempt(rid, cur_rep.rid, leg_kind,
                                "died_mid_stream")
            log.warning("request %s: replica %s died mid-stream (%s); "
                        "journal holds %d tokens", rid, cur_rep.rid,
                        verdict, len(js.tokens),
                        extra={"request_id": rid, "replica": cur_rep.rid,
                               "trace_id": tid})
            if js.finished:
                # death AFTER the terminal frame was relayed: from the
                # client's seat the stream already ended — just close
                router.note_request(rid, outcome="ok", retries=retries)
                score_client()
                ctx._write_chunk(b"")
                return
            if not js.valid:
                ins.ROUTER_FAILOVERS.labels(outcome="unresumable").inc()
                router.note_request(rid, outcome="error_unresumable",
                                    retries=retries)
                score_client()
                _fail_stream(ctx, rid, legacy, model,
                             f"replica {cur_rep.rid} failed mid-stream")
                return
            # ---- resume on a survivor, bounded + jittered
            nxt = None
            while retries < router.failover_max and nxt is None:
                retries += 1
                t_back = tr.now()
                delay = min(0.05 * (2 ** (retries - 1)), 1.0)
                time.sleep(delay * (0.5 + random.random() / 2.0))
                cand, warm = router.pick(fp, exclude={cur_rep.rid})
                tr.span_at("failover.attempt", t_back, tr.now(),
                           cat="router", track="router", req_id=rid,
                           attempt=retries)
                if cand is None:
                    continue
                tr.event("affinity.pick", cat="router", track="router",
                         req_id=rid, replica=cand.rid, warm=warm)
                if not commented:
                    # the ONE client-visible failover artifact: an SSE
                    # comment (ignored by EventSource parsers)
                    ctx._write_chunk(b": retrying\n\n")
                    commented = True
                ins.ROUTER_FAILOVERS.labels(outcome="retried").inc()
                h2 = {"Content-Type": "application/json",
                      "X-Request-Id": rid}
                if tid:
                    # the resume leg JOINS the same trace: same id, new
                    # hop, parented under the failover span
                    hop[0] += 1
                    h2[trace.HOP_HEADER] = trace.format_hop(
                        tid, "resume", hop[0])
                t_res = tr.now()
                try:
                    c2 = http.client.HTTPConnection(
                        cand.host, cand.port,
                        timeout=router.connect_timeout_s)
                    c2.connect()
                    c2.sock.settimeout(router.stream_idle_timeout_s)
                    c2.request("POST", ctx.path, _resume_raw(body, js), h2)
                    r2 = c2.getresponse()
                    ctype2 = r2.getheader("Content-Type") or ""
                    if (r2.status != 200
                            or not ctype2.startswith("text/event-stream")):
                        # shed or rejected the resume (e.g. its own 4xx/
                        # 5xx): drain the verdict, try the next candidate
                        try:
                            r2.read()
                        except (OSError, http.client.HTTPException):
                            pass
                        c2.close()
                        router.release(cand)
                        router.note_attempt(rid, cand.rid, "resume",
                                            f"rejected_{r2.status}")
                        continue
                    nxt = (cand, c2, r2)
                    tr.span_at("resume", t_res, tr.now(),
                               cat="router", track="router", req_id=rid,
                               replica=cand.rid, hop=hop[0],
                               tokens=len(js.tokens))
                except (OSError, http.client.HTTPException) as e:
                    router._mark_down(cand, f"resume connect failed: {e!r}")
                    router.release(cand)
                    router.note_attempt(rid, cand.rid, "resume",
                                        "connect_failed")
            if nxt is None:
                ins.ROUTER_FAILOVERS.labels(outcome="exhausted").inc()
                router.note_request(rid, outcome="error_exhausted",
                                    retries=retries)
                score_client()
                log.warning("request %s: failover budget spent (%d/%d); "
                            "failing the stream exactly once", rid,
                            retries, router.failover_max,
                            extra={"request_id": rid, "trace_id": tid})
                _fail_stream(ctx, rid, legacy, model,
                             f"replica {cur_rep.rid} failed mid-stream")
                return
            # hand accounting to the survivor. The ORIGINAL pick is the
            # caller's to release (its finally does); any replica WE
            # switched to is ours — release it before taking the next
            if cur_rep is not rep:
                router.release(cur_rep)
            cur_rep, cur_conn, cur_resp = nxt
            log.info("request %s: resumed on %s at token %d", rid,
                     cur_rep.rid, len(js.tokens),
                     extra={"request_id": rid, "replica": cur_rep.rid,
                            "trace_id": tid})
    finally:
        router.journal_release(js)
        # the journal hold window as ONE span, acquire to release: its
        # length is how long this stream's resume state was live, its args
        # whether the journal could still vouch for the client's view
        tr.span_at("journal", t_j, tr.now(), cat="router", track="router",
                   req_id=rid, valid=js.valid, tokens=len(js.tokens),
                   retries=retries)
        if cur_rep is not rep:
            # _proxy's finally releases `rep`; any replica we switched to
            # is ours to release
            router.release(cur_rep)


def make_router(replicas: list[str], host: str = "127.0.0.1", port: int = 0,
                poll_s: float = 0.5, affinity: bool = True,
                workers: int | None = None,
                failover_max: int = 2,
                fleet_obs: bool = True,
                trace_capacity: int = 2048,
                slo_ttft_ms: float | None = None,
                slo_itl_ms: float | None = None
                ) -> tuple[AioHttpServer, Router]:
    """Build (server, router) without starting either — the test seam.
    Call router.start() for the handshake + poller, then serve_forever."""
    router = Router(replicas, poll_s=poll_s, affinity=affinity,
                    failover_max=failover_max, fleet_obs=fleet_obs,
                    trace_capacity=trace_capacity,
                    slo=SloPolicy(ttft_ms=slo_ttft_ms, itl_ms=slo_itl_ms))
    server = AioHttpServer((host, port), router, workers=workers or 16,
                           ctx_factory=_RouterContext)
    return server, router


def run_router(replicas: list[str], host: str = "127.0.0.1",
               port: int = 9980, poll_s: float = 0.5, affinity: bool = True,
               workers: int | None = None,
               drain_timeout_s: float = 30.0,
               failover_max: int = 2,
               fleet_obs: bool = True,
               trace_capacity: int = 2048,
               slo_ttft_ms: float | None = None,
               slo_itl_ms: float | None = None) -> int:
    """CLI entry: boot the router, install SIGTERM drain, serve forever."""
    import signal

    server, router = make_router(replicas, host, port, poll_s=poll_s,
                                 affinity=affinity, workers=workers,
                                 failover_max=failover_max,
                                 fleet_obs=fleet_obs,
                                 trace_capacity=trace_capacity,
                                 slo_ttft_ms=slo_ttft_ms,
                                 slo_itl_ms=slo_itl_ms)
    router.start()

    fired = threading.Event()

    def _term(signum, frame):
        if fired.is_set():
            return
        fired.set()
        log.info("SIGTERM: router draining (timeout %.0fs)", drain_timeout_s)

        def _drain():
            router.drain()
            deadline = time.monotonic() + drain_timeout_s
            while time.monotonic() < deadline:
                if not any(r.inflight for r in router.replicas):
                    break
                time.sleep(0.1)
            server.shutdown()

        threading.Thread(target=_drain, name="dllama-router-drain",
                         daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:  # pragma: no cover - not the main thread
        pass
    n = len(router.replicas)
    log.info("router serving on http://%s:%d over %d replica(s); "
             "affinity=%s", host, server.server_address[1], n,
             "on" if affinity else "off")
    print(f"🔀 http://{host}:{server.server_address[1]}/v1/chat/completions "
          f"(router, {n} replicas, affinity "
          f"{'on' if affinity else 'off'})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover - interactive
        pass
    finally:
        router.stop()
        server.server_close()
    return 0
