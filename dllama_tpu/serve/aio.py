"""Selectors-based async serving front-end (ISSUE 15) — `--frontend aio`.

The thread-per-connection tier (serve/api.ThreadingHTTPServer) spends one
blocked OS thread per live connection: a thousand long-lived SSE streams is
a thousand parked threads. This front-end multiplexes EVERY connection's
I/O — accept, request parse, response/SSE writes, and disconnect detection
— on ONE selectors event loop thread, with a SMALL FIXED worker pool for
request handling and ONE pump thread that cooperatively advances every
live SSE stream. Thread count is a constant of the configuration, never of
the connection count (`dllama_process_threads` is the proof gauge).

Division of labor:

* **event loop** (`serve_forever`, the calling thread): non-blocking
  accept; per-connection read buffering and HTTP/1.1 request parsing
  (request line + headers via the stdlib parser, Content-Length bodies);
  outbound buffer flushing with write-readiness backpressure; keep-alive /
  pipelining; and the disconnect signal — a readable socket returning EOF
  marks the connection dead, which is how queued or mid-stream requests
  get cancelled WITHOUT any per-stream polling thread.
* **worker pool** (ThreadPoolExecutor, fixed size): runs the shared
  :class:`~dllama_tpu.serve.api.RequestRoutes` endpoints — the SAME route
  code the threads tier runs, over this module's transport primitives, so
  the two front-ends cannot drift. Non-streaming completions block their
  worker (bounded by the pool, queued beyond it); batched-tier SSE streams
  only SUBMIT here, then detach to the pump.
* **SSE pump** (one thread): drives every live stream through the
  scheduler's non-blocking :meth:`Request.poll_tokens` seam — drain what's
  available, assemble deltas (api.TokenAssembler — the same EOS/stop
  machinery as the blocking tier), enqueue chunked frames, emit
  `: keep-alive` heartbeats on idle streams, and finalize through
  api.finish_batched. One thread, any number of streams.

The single-engine tier (no scheduler) has no token queue to poll; its
streams run the blocking ``_stream`` on a pool worker — the global engine
lock serializes them anyway, so concurrency there is 1 by construction.

Lifecycle mirrors ThreadingHTTPServer: ``serve_forever()`` blocks until
``shutdown()``; ``server_close()`` releases the listener. SIGTERM drain
(api.graceful_drain) works unchanged: admission stops first, in-flight
requests finish, then shutdown() stops the loop after a bounded flush.
"""

from __future__ import annotations

import collections
import email.utils
import http
import io
import json
import logging
import os
import selectors
import socket
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from http.client import parse_headers

from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import trace
from dllama_tpu.serve import api as api_mod
from dllama_tpu.utils import locks

log = logging.getLogger("dllama_tpu.serve.aio")

#: request-head cap (status line + headers) before a 431 close — the same
#: order of magnitude as http.server's 64 KiB line limit
MAX_HEADER_BYTES = 65536
#: body cap: completions bodies are small; anything past this is abuse
MAX_BODY_BYTES = 64 * 1024 * 1024
#: outbound-buffer cap per connection: a client that stops READING while
#: its socket stays open gives no EOF signal, so unsent response bytes
#: would otherwise accumulate without bound (the threads tier gets natural
#: backpressure from its blocking writes) — past this the peer is treated
#: as gone
MAX_OUT_BYTES = 32 * 1024 * 1024
#: idle sleep of the pump when at least one stream is live but none
#: progressed — bounds added inter-token latency at well under a decode
#: chunk on any real model
PUMP_IDLE_S = 0.005


class _Conn:
    """One client connection's loop-side state. The deque is the outbound
    byte queue (worker/pump threads append, the loop pops — both ends are
    GIL-atomic, no lock on the hot path)."""

    __slots__ = ("sock", "addr", "inbuf", "out", "obytes", "busy", "dead",
                 "closing", "wmask", "continued")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.inbuf = bytearray()
        self.out: collections.deque = collections.deque()
        self.obytes = 0  # unsent bytes queued in `out` (loop + enqueue)
        self.busy = False  # a request is being handled (worker or pump owns it)
        self.dead = False  # peer EOF/reset observed by the loop
        self.closing = False  # close after the current response flushes
        self.wmask = False  # registered for write-readiness
        self.continued = False  # interim 100 Continue sent for this request


class _SseMachine:
    """One live batched-tier SSE stream, advanced cooperatively by the pump.

    Construction runs on a pool worker and does everything that may REJECT
    — body parse (ApiError -> clean 400) and scheduler submit (QueueFull /
    draining -> clean 429/503) — BEFORE the 200/chunked headers go out,
    then emits the headers (+ the initial role delta for chat) and hands
    the stream to the pump. ``pump()`` is non-blocking and returns whether
    it made progress."""

    def __init__(self, ctx, body: dict, legacy: bool):
        api = ctx.api
        self.ctx = ctx
        self.conn = ctx.conn
        self.api = api
        self.legacy = legacy
        self.rid = ctx._req_id
        self.model = body.get("model", api.model_name)
        p = api.prepare_request(body, legacy=legacy)
        self.asm = api_mod.TokenAssembler(api.tokenizer, p["stops"])
        # failover resume (ISSUE 16): replay the journaled prefix through
        # the fresh assembler (no emission — those deltas already reached
        # the client) so detector/decoder state and the position counter
        # continue exactly where the dead upstream stopped; keep its
        # stream identity. Mirrors the blocking tier's _run_batched seam.
        self.want_ids = bool(p.get("token_ids"))
        resume = p.get("resume_tokens")
        self.resumed_done = False
        if resume:
            for t in resume:
                self.asm.feed(t)
                if self.asm.eos:
                    break
            self.asm.take_ids()
        if resume and self.asm.eos:
            # the journaled tokens already complete a stop sequence: the
            # stream is over — no engine submit at all, just the finish
            # frame (pump() terminates on resumed_done)
            self.req = None
            self.resumed_done = True
        else:
            self.req = api.batched_submit(p, req_id=self.rid or "")
        self.cid = ((p.get("resume_id") or None) if resume else None) or (
            f"{'cmpl' if legacy else 'chatcmpl'}-{uuid.uuid4().hex[:16]}")
        self.created = int((p.get("resume_created") or 0) if resume else 0
                           ) or int(time.time())
        self.hb = api.sse_heartbeat_s
        self.done = False
        ctx._start_sse()
        if not legacy and not resume:
            # a resumed stream's client already got the role delta
            self._emit({"role": "assistant"})
        self.last_write = time.monotonic()

    # ------------------------------------------------------------- emission

    def _emit(self, delta_or_text, finish=None, timings=None,
              ids=None) -> None:
        if self.legacy:
            payload = api_mod.sse_text_payload(
                self.cid, self.created, self.model, delta_or_text,
                finish=finish, timings=timings, ids=ids)
        else:
            payload = api_mod.sse_chat_payload(
                self.cid, self.created, self.model, delta_or_text,
                finish=finish, timings=timings, ids=ids)
        self.ctx._write_chunk(payload)
        self.last_write = time.monotonic()

    def _emit_text(self, text: str) -> None:
        self._emit(text if self.legacy else {"content": text},
                   ids=self.asm.take_ids() if self.want_ids else None)

    def _terminate(self) -> None:
        self.ctx._write_chunk(b"data: [DONE]\n\n")
        self.ctx._write_chunk(b"")  # terminating zero-length chunk
        self._complete()

    def _complete(self) -> None:
        self.done = True
        self.ctx.server._request_done(self.conn)

    # ------------------------------------------------------------- stepping

    def pump(self) -> bool:
        """Advance the stream without blocking -> True when bytes moved or
        the stream reached a terminal state."""
        if self.done:
            return False
        if self.conn.dead:
            # the event loop saw EOF/reset on the socket: cancel the
            # scheduler request so its slot (and KV pages) free NOW —
            # no polling thread involved, the loop's readable/EOF signal
            # IS the probe (ISSUE 15 satellite)
            log.info("client disconnected; request %s cancelled", self.rid,
                     extra=trace.log_extra(self.rid))
            if self.req is not None:
                self.api.scheduler.cancel(self.req, reason="cancelled")
            self._complete()
            return True
        if self.resumed_done:
            # resume whose journaled tokens already completed the stream:
            # nothing was submitted — emit the finish frame and close
            timings: dict = {"e2e_ms": 0.0, "decode_tokens": 0}
            if self.api.replica_id:
                timings["replica"] = self.api.replica_id
            self._emit("" if self.legacy else {},
                       finish="stop", timings=timings)
            self._terminate()
            return True
        try:
            toks, ended = self.req.poll_tokens()
        except Exception as e:
            # terminal queue exception (worker crash / shutdown / shed after
            # admission): same in-band SSE error shape as the blocking
            # tier's mid-stream failure path, then a clean stream end
            self.api.scheduler.cancel(self.req, reason="cancelled")
            log.exception("streamed completion %s failed mid-stream",
                          self.rid, extra=trace.log_extra(self.rid))
            from dllama_tpu.serve.scheduler import SchedulerRejected

            msg = (str(e) if isinstance(e, (api_mod.ApiError,
                                            SchedulerRejected))
                   else "internal error")
            err = {"message": msg or e.__class__.__name__,
                   "type": "server_error"}
            if self.rid:
                err["request_id"] = self.rid
            self.ctx._write_chunk(
                b"data: " + json.dumps({"error": err}).encode() + b"\n\n")
            self._terminate()
            return True
        for t in toks:
            text = self.asm.feed(t)
            if text:
                self._emit_text(text)
            if self.asm.eos:
                # stop-string hit: overrun tokens already queued are
                # discarded, exactly like the blocking tier's loop break
                ended = True
                break
        if ended:
            if not self.asm.eos:
                tail = self.asm.flush()
                if tail:
                    self._emit_text(tail)
            finish, timings = self.api.finish_batched(
                self.req, self.asm.eos, self.asm.n)
            self._emit("" if self.legacy else {},
                       finish=finish, timings=timings)
            log.info("completion %s done: %d completion tokens",
                     self.rid, self.asm.n, extra=trace.log_extra(self.rid))
            self._terminate()
            return True
        if toks:
            return True
        if self.hb and time.monotonic() - self.last_write >= self.hb:
            # idle stream: SSE comment frame so LB/router idle timeouts
            # can't kill a slow decode (heartbeats don't count as progress
            # — the pump may still sleep)
            self.ctx._write_chunk(api_mod.SSE_HEARTBEAT)
            self.last_write = time.monotonic()
        return False


class _Pump(threading.Thread):
    """The one thread advancing every live SSE stream."""

    def __init__(self, server):
        super().__init__(name="dllama-aio-pump", daemon=True)
        self.server = server
        self._streams: list[_SseMachine] = []
        self._event = threading.Event()
        self._stop = threading.Event()

    def add(self, machine: _SseMachine) -> None:
        with self.server._mu:
            self._streams.append(machine)
        self._event.set()

    def stop(self) -> None:
        self._stop.set()
        self._event.set()

    def live_streams(self) -> int:
        with self.server._mu:
            return len(self._streams)

    def run(self) -> None:
        while not self._stop.is_set():
            with self.server._mu:
                streams = list(self._streams)
            progressed = False
            finished = []
            for m in streams:
                try:
                    progressed = m.pump() or progressed
                except Exception:
                    # a machine must never take the pump down with it
                    log.exception("SSE pump: stream %s failed", m.rid)
                    m.done = True
                    try:
                        m.api.scheduler.cancel(m.req, reason="cancelled")
                    except Exception:
                        pass
                    # the 200/chunked headers are already out: end the
                    # chunked response and retire the connection — leaving
                    # it open would hang the client mid-stream and let a
                    # pipelined request's bytes interleave into the
                    # unterminated chunk stream
                    try:
                        m.ctx._write_chunk(b"")
                    except Exception:
                        pass
                    m.conn.closing = True
                    self.server._request_done(m.conn)
                if m.done:
                    finished.append(m)
            if finished:
                with self.server._mu:
                    self._streams = [m for m in self._streams
                                     if m not in finished]
            if not progressed:
                self._event.wait(PUMP_IDLE_S if streams else 0.5)
                self._event.clear()


class _AioContext(api_mod.RequestRoutes):
    """RequestRoutes over the event-loop transport: responses are rendered
    to bytes and enqueued on the connection's outbound buffer; the loop
    flushes them as the socket accepts writes."""

    def __init__(self, server, conn: _Conn, command: str, path: str,
                 headers, body: bytes):
        self.server = server
        self.conn = conn
        self.command = command
        self.path = path
        self.headers = headers
        self._body = body
        self.api = server.api
        self.detached = False  # True once an SSE machine owns the connection

    # ------------------------------------------------- transport primitives

    def _read_body(self) -> bytes:
        return self._body

    def _drain_body(self) -> None:
        pass  # the loop buffered the whole body before dispatch

    def _client_gone(self) -> bool:
        return self.conn.dead

    @staticmethod
    def _head(status: int, headers) -> bytes:
        try:
            phrase = http.HTTPStatus(status).phrase
        except ValueError:  # pragma: no cover - nonstandard code
            phrase = ""
        lines = [f"HTTP/1.1 {status} {phrase}",
                 f"Server: dllama-tpu aio",
                 f"Date: {email.utils.formatdate(usegmt=True)}"]
        lines.extend(f"{k}: {v}" for k, v in headers)
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")

    def _send_raw(self, status: int, headers, body: bytes) -> None:
        ins.HTTP_RESPONSES.labels(endpoint=api_mod._endpoint(self.path),
                                  code=str(status)).inc()
        self.server.enqueue(self.conn, self._head(status, headers) + body)

    def _start_sse(self) -> None:
        hdrs = [("Content-Type", "text/event-stream"),
                ("Cache-Control", "no-cache"),
                ("Transfer-Encoding", "chunked")]
        if self._req_id:
            hdrs.append(("X-Request-Id", self._req_id))
        if self.api.replica_id:
            hdrs.append(("X-Replica-Id", self.api.replica_id))
        ins.HTTP_RESPONSES.labels(endpoint=api_mod._endpoint(self.path),
                                  code="200").inc()
        self.server.enqueue(self.conn, self._head(200, hdrs))

    def _write_chunk(self, payload: bytes) -> None:
        self.server.enqueue(
            self.conn,
            f"{len(payload):x}\r\n".encode() + payload + b"\r\n")

    # --------------------------------------------------- streaming override

    def _stream(self, body: dict, legacy: bool = False) -> None:
        """Batched-tier streams detach to the pump (zero blocked threads
        per stream); the single-engine tier runs the shared blocking
        implementation on this pool worker."""
        if self.api.scheduler is None:
            api_mod.RequestRoutes._stream(self, body, legacy)
            return
        machine = _SseMachine(self, body, legacy)
        self.detached = True
        self.server._pump.add(machine)


class AioHttpServer:
    """The event-loop front-end. Interface-compatible with the
    ThreadingHTTPServer the serving stack already drives: construct with
    ``(host, port)``, read ``server_address``, run ``serve_forever()`` in
    a thread, stop with ``shutdown()``, release with ``server_close()``."""

    def __init__(self, address, api, workers: int | None = None,
                 ctx_factory=None):
        host, port = address
        self.api = api
        self._ctx_factory = ctx_factory or _AioContext
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(256)
        self._listener.setblocking(False)
        self.server_address = self._listener.getsockname()
        self._sel = selectors.DefaultSelector()
        self._mu = locks.make_lock("serve.frontend")
        self._conns: dict = {}  # socket -> _Conn (loop thread mutates)
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        n = workers or min(8, max(2, (os.cpu_count() or 4)))
        self.workers = int(n)
        # per-server gauge series: several event loops can share a process
        # (replica servers + router fronts in tests/bench) and must not
        # clobber one another's counts
        self._conn_gauge = ins.FRONTEND_CONNECTIONS.labels(
            server=f"{self.server_address[0]}:{self.server_address[1]}")
        self._pool = ThreadPoolExecutor(max_workers=self.workers,
                                        thread_name_prefix="dllama-aio")
        # control plane gets its own tiny pool: /health probes, /metrics
        # scrapes, and registry reads must answer even when every request
        # worker is parked on a long completion (on the router tier each
        # proxied stream occupies a worker for its whole lifetime — an LB
        # probe queued behind 16 of those would flag a healthy process
        # dead and restart it, killing every in-flight stream)
        self._ctrl = ThreadPoolExecutor(max_workers=2,
                                        thread_name_prefix="dllama-aio-ctrl")
        self._pump = _Pump(self)
        self._stop = threading.Event()
        self._stopped = threading.Event()
        self._serving = False
        self._accepting = True

    # ------------------------------------------------------------ lifecycle

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        self._serving = True
        if not self._pump.is_alive():
            self._pump.start()
        self._sel.register(self._listener, selectors.EVENT_READ, "listen")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        try:
            while not self._stop.is_set():
                try:
                    events = self._sel.select(timeout=poll_interval)
                except OSError:  # pragma: no cover - fd churn at shutdown
                    continue
                for key, mask in events:
                    tag = key.data
                    if tag == "listen":
                        self._accept()
                    elif tag == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, InterruptedError):
                            pass
                        if not self._accepting:
                            try:
                                self._sel.unregister(self._listener)
                            except (KeyError, ValueError):
                                pass
                    else:
                        if mask & selectors.EVENT_READ:
                            self._read(tag)
                # post-select sweep: flush, parse pipelined requests, close
                for conn in list(self._conns.values()):
                    if conn.dead:
                        # marked dead off-loop (outbound-cap overflow): tear
                        # it down here — the loop owns socket/selector state
                        self._close(conn)
                        continue
                    if conn.out:
                        self._flush(conn)
                    if not conn.busy and not conn.dead \
                            and not conn.closing and conn.inbuf:
                        self._try_parse(conn)
                    if conn.closing and not conn.busy and not conn.out:
                        self._close(conn)
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            for sock in (self._listener, self._wake_r):
                try:
                    self._sel.unregister(sock)
                except (KeyError, ValueError):
                    pass
            self._stopped.set()

    def shutdown(self, flush_timeout_s: float = 5.0) -> None:
        """Stop accepting, give in-flight responses a bounded window to
        finish flushing (the scheduler drain has already run by the time
        the SIGTERM path calls this), then stop the loop."""
        self._accepting = False
        self._wake()
        deadline = time.monotonic() + flush_timeout_s
        while time.monotonic() < deadline:
            with self._mu:  # the loop thread pops _conns concurrently
                conns = list(self._conns.values())
            busy = any(c.busy or c.out for c in conns)
            if not busy and self._pump.live_streams() == 0:
                break
            time.sleep(0.02)
        self._stop.set()
        self._wake()
        if self._serving:
            self._stopped.wait(timeout=10.0)
        self._pump.stop()
        self._pool.shutdown(wait=False)
        self._ctrl.shutdown(wait=False)

    def server_close(self) -> None:
        for sock in (self._listener, self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except OSError:  # pragma: no cover
            pass

    # ------------------------------------------------------------- plumbing

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x01")
        except (BlockingIOError, InterruptedError, OSError):
            pass  # wake pipe full = a wake is already pending

    def enqueue(self, conn: _Conn, data: bytes) -> None:
        """Worker/pump threads hand response bytes to the loop."""
        if conn.dead:
            return  # the peer is gone; nothing to deliver to
        if conn.obytes > MAX_OUT_BYTES:
            # the peer stopped reading but kept the socket open (no EOF to
            # observe): treat it as gone so the stream's producer stops —
            # the pump/probe sees `dead` and cancels the request
            conn.dead = True
            self._wake()
            return
        conn.obytes += len(data)
        conn.out.append(data)
        self._wake()

    def _request_done(self, conn: _Conn) -> None:
        """A handler or stream finished its response: the connection may
        parse its next pipelined request (loop-side sweep picks it up)."""
        conn.busy = False
        self._wake()

    def _accept(self) -> None:
        while self._accepting:
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                break
            sock.setblocking(False)
            conn = _Conn(sock, addr)
            with self._mu:
                self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            self._conn_gauge.set(len(self._conns))

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            # EOF/reset: THE disconnect signal. Mark dead and tear the
            # socket down; a busy handler's probe / the pump notices the
            # flag and cancels the scheduler request.
            conn.dead = True
            self._close(conn)
            return
        conn.inbuf += data
        if len(conn.inbuf) > MAX_HEADER_BYTES + MAX_BODY_BYTES:
            # one request head + the largest legal body is the most a
            # well-behaved client ever buffers ahead (size limits are only
            # checked at parse time, which waits while a handler is busy);
            # past it the peer is flooding — drop the connection rather
            # than grow without bound. The threads tier gets the same
            # protection from its blocking reads' natural backpressure.
            conn.dead = True
            self._close(conn)

    def _flush(self, conn: _Conn) -> None:
        out = conn.out
        while out:
            data = out[0]
            try:
                n = conn.sock.send(data)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                conn.dead = True
                self._close(conn)
                return
            conn.obytes -= n
            if n < len(data):
                out[0] = data[n:]
                break
            out.popleft()
        if not out:
            # unlocked += from worker/pump threads can drift a few bytes
            # under GIL races; an empty queue is the exact ground truth, so
            # re-zero here (every fully-flushed moment) — the cap only has
            # to be approximately right, never cumulatively wrong
            conn.obytes = 0
        want_write = bool(out)
        if want_write != conn.wmask:
            conn.wmask = want_write
            mask = selectors.EVENT_READ | (
                selectors.EVENT_WRITE if want_write else 0)
            try:
                self._sel.modify(conn.sock, mask, conn)
            except (KeyError, ValueError):  # pragma: no cover - racing close
                pass

    def _close(self, conn: _Conn) -> None:
        with self._mu:
            existed = self._conns.pop(conn.sock, None)
        if existed is None:
            return
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._conn_gauge.set(len(self._conns))

    # -------------------------------------------------------------- parsing

    def _bad_request(self, conn: _Conn, status: int, message: str) -> None:
        body = (b'{"error": {"message": "' + message.encode() + b'"}}')
        head = _AioContext._head(status, [
            ("Content-Type", "application/json"),
            ("Content-Length", str(len(body))),
            ("Connection", "close")])
        # through enqueue like every other response: obytes accounting, the
        # loop wake (otherwise the bytes sit until the next select timeout
        # — the sweep's flush already ran for this connection), and the
        # response counter the threads tier's _send_json increments
        ins.HTTP_RESPONSES.labels(endpoint="other", code=str(status)).inc()
        self.enqueue(conn, head + body)
        # drop the offending bytes — a closing connection parses nothing
        # more, and leaving them buffered would re-answer the same error
        # every sweep while the close waits for the flush
        conn.inbuf.clear()
        conn.closing = True

    def _try_parse(self, conn: _Conn) -> None:
        """Parse one complete request off the connection's input buffer and
        dispatch it to the pool. Loop thread only; at most one in-flight
        request per connection (HTTP/1.1 pipelining is answered in order
        because the next parse waits for _request_done)."""
        buf = conn.inbuf
        idx = buf.find(b"\r\n\r\n")
        if idx < 0:
            if len(buf) > MAX_HEADER_BYTES:
                self._bad_request(conn, 431, "request header too large")
            return
        head = bytes(buf[:idx + 2])
        line, _, rest = head.partition(b"\r\n")
        parts = line.split()
        if len(parts) != 3 or not parts[2].startswith(b"HTTP/"):
            self._bad_request(conn, 400, "malformed request line")
            return
        command = parts[0].decode("latin-1")
        path = parts[1].decode("latin-1")
        version = parts[2].decode("latin-1")
        try:
            headers = parse_headers(io.BytesIO(rest + b"\r\n"))
        except Exception:
            self._bad_request(conn, 400, "malformed headers")
            return
        if headers.get("Transfer-Encoding"):
            # this parser frames bodies by Content-Length ONLY. Accepting a
            # TE request CL-framed is the CL.TE request-smuggling shape
            # behind any TE-honoring proxy (RFC 9112: TE wins or the
            # message must be rejected) — reject, never mis-frame
            self._bad_request(conn, 411,
                              "chunked request bodies are not supported; "
                              "send Content-Length")
            return
        cls = headers.get_all("Content-Length") or []
        if len(set(cls)) > 1:
            # differing duplicate Content-Length is the CL.CL smuggling
            # shape (a front proxy framing by the LAST value would leave
            # our first-value framing a desynchronized tail) — RFC 9112
            # requires rejection
            self._bad_request(conn, 400, "conflicting Content-Length")
            return
        try:
            length = int(cls[0]) if cls else 0
        except ValueError:
            self._bad_request(conn, 400, "invalid Content-Length")
            return
        if length < 0 or length > MAX_BODY_BYTES:
            self._bad_request(conn, 413, "body too large")
            return
        total = idx + 4 + length
        if len(buf) < total:
            # the threads tier (BaseHTTPRequestHandler) answers an interim
            # 100 Continue for HTTP/1.1 `Expect` bodies — clients like curl
            # withhold POST bodies >1 KB until they see it, so without this
            # every large-prompt request stalls on the client's expect
            # timeout (the _try_parse re-run each sweep is why the flag
            # guards a single send per request)
            if (not conn.continued and version != "HTTP/1.0"
                    and headers.get("Expect", "").lower() == "100-continue"):
                conn.continued = True
                self.enqueue(conn, b"HTTP/1.1 100 Continue\r\n\r\n")
            return  # body still arriving
        conn.continued = False
        body = bytes(buf[idx + 4:total])
        del buf[:total]
        if (version == "HTTP/1.0"
                or headers.get("Connection", "").lower() == "close"):
            conn.closing = True
        conn.busy = True
        ctx = self._ctx_factory(self, conn, command, path, headers, body)
        control = command == "GET" and path.startswith(
            ("/health", "/metrics", "/router/"))
        (self._ctrl if control else self._pool).submit(self._run_ctx, ctx)

    def _run_ctx(self, ctx: _AioContext) -> None:
        try:
            if ctx.command == "GET":
                ctx.do_GET()
            elif ctx.command == "POST":
                ctx.do_POST()
            else:
                ctx._send_json(501, {"error": {
                    "message": f"unsupported method {ctx.command}"}})
        except Exception:
            # do_GET/do_POST handle their own errors; anything escaping is
            # a transport-level failure — drop the connection (the threads
            # tier's handler thread dies the same way)
            log.exception("aio handler failed (%s %s)",
                          ctx.command, ctx.path)
            ctx.conn.closing = True
        finally:
            if not ctx.detached:
                self._request_done(ctx.conn)
