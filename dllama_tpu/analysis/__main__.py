"""``python -m dllama_tpu.analysis`` — run the invariant analyzer.

Exit 0 with zero findings, 1 otherwise. Diagnostics are one per line in
``file:line: rule-id message`` form (editor/CI clickable); ``--json``
emits the machine-readable document instead. ``--lock-graph`` prints the
static lock-order edges (holder -> acquired @ site) and exits 0 — the
graph behind the ``lock-order`` verdicts.

Stdlib-only and jax-free by construction: importing jax here would drag
seconds of startup into a gate scripts/checks.sh runs on every commit
(an assertion in scripts/analysis_smoke.sh pins this).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _detect_root(explicit: str | None) -> str:
    if explicit:
        return os.path.abspath(explicit)
    # <root>/dllama_tpu/analysis/__main__.py
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dllama_tpu.analysis",
        description="dllama-tpu static invariant analyzer (ISSUE 14)")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable diagnostics on stdout")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the static lock-order edges and exit")
    args = ap.parse_args(argv)

    # the container may pre-import jax via sitecustomize; what matters is
    # that the ANALYZER itself never pulls it in (sub-5s CI gate)
    had_jax = "jax" in sys.modules
    t0 = time.monotonic()
    from dllama_tpu.analysis.core import RULE_CATALOG, Project, run

    assert had_jax or "jax" not in sys.modules, \
        "the analyzer must not import jax"

    project = Project.from_disk(_detect_root(args.root))

    if args.lock_graph:
        from dllama_tpu.analysis.rules_locks import build_graph
        from dllama_tpu.utils.locks import LOCK_RANKS

        edges, _reentrant, _ca, _mg = build_graph(project)
        for holder, acquired, rel, line in sorted(set(edges)):
            hr = LOCK_RANKS.get(holder, "?")
            ar = LOCK_RANKS.get(acquired, "?")
            print(f"{holder}({hr}) -> {acquired}({ar})  @ {rel}:{line}")
        return 0

    diags = run(project)
    dt = time.monotonic() - t0
    if args.json:
        print(json.dumps({
            "findings": [{"path": d.path, "line": d.line, "rule": d.rule,
                          "message": d.message} for d in diags],
            "count": len(diags),
            "files": len(project.sources),
            "rules": len(RULE_CATALOG),
            "seconds": round(dt, 3),
        }, indent=2))
    else:
        for d in diags:
            print(d)
        status = "FAIL" if diags else "OK"
        print(f"analysis: {status} — {len(diags)} finding(s) over "
              f"{len(project.sources)} files, {len(RULE_CATALOG)} rules "
              f"({dt:.2f}s, no jax)", file=sys.stderr)
    return 1 if diags else 0


if __name__ == "__main__":
    raise SystemExit(main())
