"""Device-state write discipline (``dev-state``) and the steady-state
host-transfer lint (``transfer-note``).

``dev-state``: the decode carry arrays ``_pos_dev`` / ``_last_dev`` /
``_keys_dev`` are DEVICE-authoritative — the fused scans mutate them with
data-dependent values (sampled tokens, threefry splits, spec advances)
that the host cannot mirror mid-flight, so a bulk re-upload from a host
mirror can clobber an in-flight overlapped chunk's carry (the exact bug
class PR 10 shipped and PR 13's transfer guard only catches at runtime).
Sanctioned write shapes, everything else is an error:

* surgical per-row writes: ``self.X = self.X.at[row].set(...)``;
* carry unpacking from a jit call: ``(..., self.X, ...) = self._decode(...)``;
* rebinding a local name (itself a carry from an unpack);
* anything inside the boundary-rebuild sites ``__init__`` /
  ``warm_restart`` / ``_sync_vectors``.

``transfer-note``: inside the steady-state decode/spec functions of
``engine/batch.py``, any host<->device materialization (``np.asarray`` /
``jnp.asarray`` / ``device_get`` / ``device_put`` / ``block_until_ready``)
must sit AT a ``note_transfer``-annotated site: a statement within
``NOTE_WINDOW`` statements of a ``note_transfer`` call in some enclosing
statement list (so transfers nested under a ``with`` scope count their
enclosing statement's position). Function-level exemption would let a new
unannotated upload ride an unrelated note elsewhere in the function — an
unannotated transfer in the steady path is PR 3's zero-upload invariant
silently eroding. (Host-side ``.copy()`` of numpy mirrors is not a
transfer; the upload it feeds is caught at its ``jnp.asarray``. The one
aggregated-fan site, ``_sync_vectors``, carries a reasoned suppression.)
"""

from __future__ import annotations

import ast

from dllama_tpu.analysis.core import Diagnostic, dotted, parent_map

#: device-authoritative attrs (the host mirrors are pos/last_token/keys)
DEV_ATTRS = ("_pos_dev", "_last_dev", "_keys_dev")

#: functions allowed to rebuild the carries wholesale: construction, the
#: crash-recovery rebuild, and the boundary vector fan
SANCTIONED_FNS = ("__init__", "warm_restart", "_sync_vectors")

#: the steady-state functions of engine/batch.py the transfer lint guards
STEADY_FILE = "dllama_tpu/engine/batch.py"
STEADY_FNS = ("decode_dispatch", "_spec_dispatch", "hybrid_dispatch",
              "decode_consume", "_sync_vectors", "nonfinite")

_TRANSFER_CALLS = {"np.asarray", "numpy.asarray", "jnp.asarray",
                   "jnp.array", "jax.device_get", "jax.device_put",
                   "jax.block_until_ready"}

#: a transfer is "annotated" when a note_transfer call sits within this
#: many statements of it in some enclosing statement list
NOTE_WINDOW = 4


def _is_self_attr(node: ast.AST, attrs=DEV_ATTRS) -> str | None:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self" and node.attr in attrs):
        return node.attr
    return None


def _is_at_write(value: ast.AST, attr: str) -> bool:
    """value is self.<attr>.at[...].set/add/mul/...(...)?"""
    if not (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)):
        return False
    sub = value.func.value
    if not isinstance(sub, ast.Subscript):
        return False
    at = sub.value
    return (isinstance(at, ast.Attribute) and at.attr == "at"
            and _is_self_attr(at.value) == attr)


def _check_dev_state(src, diags):
    func_stack: list[str] = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            func_stack.append(node.name)
            self.generic_visit(node)
            func_stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def _sanctioned(self) -> bool:
            return any(f in SANCTIONED_FNS for f in func_stack)

        def _flag(self, node, attr, why):
            diags.append(Diagnostic(
                src.rel, node.lineno, "dev-state",
                f"whole-array rebind of device-authoritative self.{attr} "
                f"({why}) — write per-row via .at[slot].set(...), or do it "
                f"in {'/'.join(SANCTIONED_FNS)} (an in-flight overlapped "
                "chunk's carry would be clobbered)"))

        def visit_Assign(self, node):
            if not self._sanctioned():
                for t in node.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        # carry unpack from a jit call is THE sanctioned
                        # whole-array source; anything else is not
                        if not isinstance(node.value, ast.Call):
                            for el in t.elts:
                                a = _is_self_attr(el)
                                if a:
                                    self._flag(node, a,
                                               "tuple rebind from a "
                                               "non-call value")
                        continue
                    a = _is_self_attr(t)
                    if a is None:
                        continue
                    v = node.value
                    if _is_at_write(v, a) or isinstance(v, ast.Name):
                        continue
                    self._flag(node, a, f"assigned {type(v).__name__}")
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            a = _is_self_attr(node.target)
            if a and not self._sanctioned():
                self._flag(node, a, "augmented assignment")
            self.generic_visit(node)

    V().visit(src.tree)


def _is_note(stmt: ast.AST) -> bool:
    """The statement ITSELF (not a nested sub-block) calls note_transfer —
    descending into child statement lists would let a compound statement
    (an ``if`` holding both a transfer and a note deep inside) annotate
    its own transfers from the outer level."""
    stack = [stmt]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            d = dotted(n.func)
            if d is not None and d.split(".")[-1] == "note_transfer":
                return True
        for name, value in ast.iter_fields(n):
            if name in ("body", "orelse", "finalbody", "handlers") \
                    and isinstance(value, list):
                continue  # nested statement lists are their own level
            if isinstance(value, list):
                stack.extend(v for v in value if isinstance(v, ast.AST))
            elif isinstance(value, ast.AST):
                stack.append(value)
    return False


def _blocks_of(fn: ast.FunctionDef):
    """Every statement list in `fn` (bodies of the function, ifs, withs,
    loops, try arms) as (list, {stmt_node: index})."""
    out = []
    stack = [fn]
    while stack:
        node = stack.pop()
        for attr in ("body", "orelse", "finalbody"):
            stmts = getattr(node, attr, None)
            if isinstance(stmts, list) and stmts:
                out.append((stmts, {id(s): i for i, s in enumerate(stmts)}))
                stack.extend(stmts)
        for h in getattr(node, "handlers", []) or []:
            out.append((h.body, {id(s): i for i, s in enumerate(h.body)}))
            stack.extend(h.body)
    return out


def _check_transfers(src, diags, parents):
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.FunctionDef) \
                or node.name not in STEADY_FNS:
            continue
        blocks = _blocks_of(node)
        noted_idx = [({id(s) for s in stmts},
                      sorted(i for i, s in enumerate(stmts) if _is_note(s)))
                     for stmts, _ in blocks]

        def annotated(call: ast.AST) -> bool:
            # walk ancestor statements: at each enclosing statement list,
            # is a note_transfer-bearing statement within NOTE_WINDOW?
            cur = call
            while cur is not node:
                parent = parents.get(cur)
                if parent is None:
                    break
                for (stmts, index), (ids, notes) in zip(blocks, noted_idx):
                    if id(cur) in ids:
                        i = index[id(cur)]
                        if any(abs(i - j) <= NOTE_WINDOW for j in notes):
                            return True
                cur = parent
            return False

        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            d = dotted(sub.func)
            is_xfer = (d in _TRANSFER_CALLS
                       or (isinstance(sub.func, ast.Attribute)
                           and sub.func.attr == "block_until_ready"))
            if is_xfer and not annotated(sub):
                diags.append(Diagnostic(
                    src.rel, sub.lineno, "transfer-note",
                    f"host<->device transfer ({d or 'block_until_ready'}) "
                    f"in steady-state {node.name}() with no "
                    f"note_transfer(...) within {NOTE_WINDOW} statements — "
                    "the zero-steady-upload invariant (PR 3/13) erodes "
                    "invisibly"))


def check(project) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    for src in project.py_sources("dllama_tpu/engine/"):
        _check_dev_state(src, diags)
    steady = project.source(STEADY_FILE)
    if steady is not None and steady.parse_error() is None:
        _check_transfers(steady, diags, parent_map(steady.tree))
    return diags
