"""Repo-native static invariant analyzer (ISSUE 14).

A dependency-free (stdlib ``ast`` only, no jax import) rule engine that
machine-checks the conventions the serving stack's correctness rests on —
run as ``python -m dllama_tpu.analysis`` (wired into scripts/checks.sh as
a hard CI gate) with ``file:line: rule-id message`` diagnostics, inline
suppressions (``# dllama: allow[rule-id] reason``) and a ``--json`` mode.

Rule families (the README "Static analysis & lock discipline" table is
drift-checked against :data:`RULE_CATALOG` both directions):

* **jit** — every cached-jit dispatch in ``engine/`` is bracketed in
  ``LEDGER.scope(fn, key)`` with a label from ``obs/compile.COMPILE_FNS``
  (PR 12's ledger only catches an unattributed compile if that path runs;
  this fails CI at the callsite).
* **dev** — the device-authoritative decode arrays (``_pos_dev``,
  ``_last_dev``, ``_keys_dev``) are written per-row (``.at[...]``) or from
  jit carries, never bulk-rebuilt from host mirrors outside the sanctioned
  boundary sites (the PR 10 bug class).
* **catalog** — metrics families, span/event names and fault points
  register only through their single-site catalogs.
* **transfer** — host<->device transfers inside the steady-state
  decode/spec paths only at ``note_transfer``-annotated sites.
* **lock** — the static cross-module lock-order graph (named locks from
  ``utils/locks``) must strictly ascend ``LOCK_RANKS``; nothing is ever
  acquired under the metrics/tracer leaf locks. The runtime half is the
  ``DLLAMA_LOCK_AUDIT=1`` sanitizer in ``utils/locks``.
* **gate** — the repo contracts scripts/checks.sh used to grep for
  (paged-route README table, bench records, perfdiff rules, the AOT
  inventory), now with real ``file:line`` diagnostics.
* **doc** — the README rule-catalog and lock-rank tables match the code's
  definition sites exactly, both directions.
"""

from dllama_tpu.analysis.core import (  # noqa: F401
    Diagnostic,
    Project,
    RULE_CATALOG,
    run,
)
